"""Disaggregated data service: dispatcher + batch workers + trainer clients.

The tf.data-service-shaped tier above the reader library (arxiv 2210.14826's
disaggregation argument, cedar's arxiv 2401.08895 pipeline split): input CPU
work moves off the trainer host onto a fleet of **batch workers**, each
wrapping an ordinary ``make_reader``-family pipeline and serving ready numpy
batches over length-prefixed TCP
(:mod:`petastorm_tpu.reader_impl.framed_socket`). A single **dispatcher**
owns the split plan — which row-group pieces each client's workers read —
and the **client** (:class:`ServiceBatchSource`) plugs into
:class:`~petastorm_tpu.jax_utils.loader.JaxDataLoader` through its
``batch_source=`` seam, so the trainer-side staging/prefetch/diagnostics
machinery is reused unchanged.

Sharding modes (dispatcher ``mode=``):

- ``static`` — each client declares ``(client_index, num_clients)``; the
  dispatcher shards row groups per client (``pieces[client_index::
  num_clients]``) and partitions each client's shard across live workers.
  Deterministic per-client data; resumable (``ServiceBatchSource.
  state_dict()``).
- ``fcfs`` — one shared split queue; any client takes the next row group
  first-come-first-served (dispatcher-owned epoch refills). Maximum
  utilization, no per-client determinism.

Failure semantics are at-least-once at row-group-set granularity: a worker
dying mid-stream triggers client reconnect with bounded exponential backoff
(:func:`petastorm_tpu.utils.retry_with_backoff`), then dispatcher
re-assignment of the dead worker's pieces to survivors — re-delivered from
the start of the piece set, so no sample is lost (duplicates possible,
exactly the reader layer's buffered-row resume contract).

The control plane itself is fault-tolerant: the dispatcher journals its
state to a WAL (:mod:`petastorm_tpu.service.journal`) and rebuilds it on
restart; workers and clients heartbeat (lease expiry evicts hung workers;
workers re-register automatically); and a monotonically increasing fencing
epoch makes every party resync after a recovery instead of acting on a
stale plan. :mod:`petastorm_tpu.service.chaos` injects these failures at
configurable rates so the invariants stay tested end to end.

CLI: ``python -m petastorm_tpu.service dispatcher|worker``; architecture
walkthrough in ``docs/guides/service.md``.
"""

from petastorm_tpu.service.chaos import ChaosInjector
from petastorm_tpu.service.client import ServiceBatchSource, ServiceError
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.service.fleet import (
    AutoscaleConfig,
    AutoscalePlanner,
    JobHandle,
    end_job,
    plan_fair_shares,
    register_job,
)
from petastorm_tpu.service.journal import Journal
from petastorm_tpu.service.mixture import (
    MixedBatchSource,
    MixtureSampler,
    MixtureSpec,
    get_mixture_weights,
    set_mixture_weights,
)
from petastorm_tpu.service.packing_stage import (
    PackedBatchSource,
    PackingSpec,
    StreamPacker,
)
from petastorm_tpu.service.worker import BatchWorker

__all__ = [
    "Dispatcher",
    "BatchWorker",
    "ServiceBatchSource",
    "ServiceError",
    "Journal",
    "ChaosInjector",
    "AutoscaleConfig",
    "AutoscalePlanner",
    "JobHandle",
    "register_job",
    "end_job",
    "plan_fair_shares",
    "MixedBatchSource",
    "MixtureSampler",
    "MixtureSpec",
    "set_mixture_weights",
    "get_mixture_weights",
    "PackedBatchSource",
    "PackingSpec",
    "StreamPacker",
]
