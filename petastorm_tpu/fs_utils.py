"""URL → filesystem resolution.

Reference parity: ``petastorm/fs_utils.py`` (``FilesystemResolver``,
``get_filesystem_and_path_or_paths``, ``get_dataset_path``) — SURVEY.md §2.4.

TPU-first design difference: the reference resolves ``hdfs://`` through its
own namenode-resolution machinery (``petastorm/hdfs/namenode.py``) and s3/gcs
through fsspec wrappers. Here every scheme goes through
``pyarrow.fs.FileSystem`` — the same C++ filesystem layer pyarrow's Parquet
reader uses natively — with fsspec as the fallback for exotic schemes
(wrapped via ``pyarrow.fs.PyFileSystem``). On a TPU pod each host resolves the
filesystem independently; there is no cross-host data-plane traffic
(SURVEY.md §5 "distributed communication backend").
"""

from __future__ import annotations

from urllib.parse import urlparse

import pyarrow.fs as pafs


class FilesystemResolver:
    """Resolves a dataset URL into a ``pyarrow.fs.FileSystem`` + path.

    Supported: local paths, ``file://``, ``hdfs://host:port``, ``s3://``,
    ``gs://``/``gcs://``, plus anything fsspec can open (via
    ``storage_options``). A pre-built ``filesystem`` short-circuits resolution.

    ``fast_gcs_listing=True`` (reader construction): ``gs://`` URLs resolve
    through :class:`~petastorm_tpu.gcsfs_helpers.gcsfs_fast_list.
    FastListingFilesystem` — ONE recursive listing sweep at construction
    serves all of dataset discovery's ``ls``/``info``/``walk`` traffic from
    memory instead of one network round-trip per directory. Read-only
    contexts only (the cached tree would be stale under concurrent writes —
    the ETL writer never sets it).
    """

    def __init__(self, dataset_url, hadoop_configuration=None, connector=None,
                 hdfs_driver="libhdfs", user=None, storage_options=None,
                 filesystem=None, fast_gcs_listing=False):
        if not isinstance(dataset_url, str):
            raise ValueError(f"dataset_url must be a string, got {type(dataset_url)}")
        self._dataset_url = dataset_url.rstrip("/")
        self._user = user
        self._storage_options = storage_options or {}
        self._fast_gcs_listing = fast_gcs_listing

        parsed = urlparse(self._dataset_url)
        self._scheme = parsed.scheme

        if filesystem is not None:
            self._filesystem = _ensure_arrow_filesystem(filesystem)
            self._path = _strip_scheme(self._dataset_url)
            return

        if self._scheme in ("", "file"):
            self._filesystem = pafs.LocalFileSystem()
            self._path = parsed.path if self._scheme == "file" else self._dataset_url
        elif self._scheme == "hdfs":
            self._filesystem, self._path = self._resolve_hdfs(parsed)
        elif self._scheme in ("s3", "s3a", "s3n", "gs", "gcs") or self._storage_options:
            self._filesystem, self._path = self._resolve_remote(parsed)
        else:
            try:
                self._filesystem, self._path = pafs.FileSystem.from_uri(self._dataset_url)
            except Exception as exc:
                raise ValueError(
                    f"Unsupported dataset URL scheme {self._scheme!r} in "
                    f"{dataset_url!r}: {exc}"
                ) from exc

    def _resolve_hdfs(self, parsed):
        from petastorm_tpu.hdfs.namenode import connect_hdfs

        return connect_hdfs(parsed, user=self._user)

    def _resolve_remote(self, parsed):
        url = self._dataset_url
        if self._scheme in ("s3a", "s3n"):
            url = "s3" + url[len(self._scheme):]
        if self._scheme in ("gcs",):
            url = "gs" + url[len(self._scheme):]
        if self._scheme in ("gs", "gcs") and self._fast_gcs_listing:
            resolved = self._resolve_gcs_fast(url)
            if resolved is not None:
                return resolved
        if self._storage_options:
            # fsspec honors storage_options; wrap the result for pyarrow.
            import fsspec

            fs, path = fsspec.core.url_to_fs(url, **self._storage_options)
            return _ensure_arrow_filesystem(fs), path
        fs, path = pafs.FileSystem.from_uri(url)
        return fs, path

    def _resolve_gcs_fast(self, url):
        """gs:// through the one-sweep listing wrapper (or None to fall back
        to the default resolution when no fsspec GCS implementation is
        available — e.g. gcsfs not installed).

        Trade-off (reference parity — upstream petastorm routes GCS through
        gcsfs too): the wrapped filesystem serves CONTENT reads through
        fsspec/gcsfs rather than pyarrow's native C++ GCS client. Discovery
        becomes one listing sweep instead of one round-trip per directory —
        the dominant cost at reader construction on a pod — while parquet
        byte-range reads go through gcsfs's HTTP client. Prefer
        ``fast_gcs_listing=False`` if your deployment depends on
        arrow-native GCS auth or its C++ read path."""
        import logging

        from petastorm_tpu.gcsfs_helpers.gcsfs_fast_list import (
            FastListingFilesystem,
        )

        try:
            import fsspec

            # Dispatches to whatever implements the "gs" protocol (gcsfs in
            # production; tests register a fake).
            fs, path = fsspec.core.url_to_fs(url, **self._storage_options)
        except (ImportError, ValueError) as exc:
            # gcsfs absent is the normal state of arrow-native installs; a
            # per-reader-construction UserWarning would be noise.
            logging.getLogger(__name__).debug(
                "fast GCS listing unavailable (%s); falling back to "
                "per-directory discovery", exc)
            return None
        fast = FastListingFilesystem(fs, path)
        return _ensure_arrow_filesystem(fast), path

    def filesystem(self):
        return self._filesystem

    def get_dataset_path(self):
        return self._path

    @property
    def parsed_dataset_url(self):
        return urlparse(self._dataset_url)


def _ensure_arrow_filesystem(filesystem):
    if isinstance(filesystem, pafs.FileSystem):
        return filesystem
    # fsspec filesystem → wrap through the pyarrow FSSpecHandler
    try:
        from pyarrow.fs import FSSpecHandler, PyFileSystem

        return PyFileSystem(FSSpecHandler(filesystem))
    except Exception as exc:
        raise ValueError(f"Cannot adapt filesystem {filesystem!r}: {exc}") from exc


def _strip_scheme(url):
    parsed = urlparse(url)
    if parsed.scheme in ("", "file"):
        return parsed.path or url
    return (parsed.netloc + parsed.path) if parsed.scheme in ("s3", "gs", "gcs") \
        else parsed.path


def get_filesystem_and_path_or_paths(url_or_urls, hdfs_driver="libhdfs",
                                     storage_options=None, filesystem=None,
                                     fast_gcs_listing=False):
    """Reference parity: ``petastorm/fs_utils.py::get_filesystem_and_path_or_paths``.

    Accepts one URL or a list; all must share a scheme. Returns
    ``(filesystem, path_or_paths)``.
    """
    urls = url_or_urls if isinstance(url_or_urls, list) else [url_or_urls]
    if not urls:
        raise ValueError("Empty dataset URL list")
    schemes = {urlparse(u).scheme for u in urls}
    if len(schemes) > 1:
        raise ValueError(f"All dataset URLs must share one scheme, got {schemes}")
    resolvers = [
        FilesystemResolver(u, hdfs_driver=hdfs_driver,
                           storage_options=storage_options,
                           filesystem=filesystem,
                           # The fast-listing wrapper's cache is rooted at
                           # ONE url's prefix, and only resolvers[0]'s
                           # filesystem is returned — with several URLs the
                           # other prefixes would be unlisted. Multi-URL
                           # reads keep default resolution.
                           fast_gcs_listing=fast_gcs_listing
                           and len(urls) == 1)
        for u in urls
    ]
    fs = resolvers[0].filesystem()
    paths = [r.get_dataset_path() for r in resolvers]
    return fs, paths if isinstance(url_or_urls, list) else paths[0]


def get_dataset_path(parsed_url):
    """Path portion of a parsed dataset URL (reference-parity helper)."""
    if parsed_url.scheme in ("s3", "s3a", "s3n", "gs", "gcs"):
        return parsed_url.netloc + parsed_url.path
    return parsed_url.path
