"""Shared-memory ring transport: zero-syscall, zero-copy batch delivery.

The colocated deployment the tf.data service paper names (PAPERS.md,
2210.14826) — the autoscaler packs a worker onto the trainer's host — pays
TCP framing, socket syscalls, and at least one copy per batch for bytes
that never leave the machine. This module is the shm tier the negotiation
layer (``service/transport.py``) switches such streams onto: a
memfd-backed, mmap'd **ring arena** carrying the exact framed-message
vocabulary ``reader_impl/framed_socket.py`` defines, plus a worker-global
**frame pool** so warm decoded-batch cache hits (stored as one contiguous
pre-serialized frame buffer since the cache PRs) are *mapped* into the
ring as ``(offset, length)`` references instead of copied.

Layout — one arena is a 256-byte header page followed by a byte-stream
SPSC circular data region. Header fields are 8-byte little-endian words at
fixed offsets::

    0   magic "PTSHMR1\\0"      40  write_pos  (producer-owned, monotonic)
    8   version                 48  read_pos   (consumer-owned, monotonic)
    16  generation              56  consumer_waiting
    24  data_offset             64  producer_waiting
    32  data_size               72  flags (1=producer gone, 2=consumer gone)

``write_pos``/``read_pos`` are absolute byte counts, never wrapped —
``write_pos - read_pos`` is the occupancy, so a completely full ring is
unambiguous. The producer copies a whole record into the data region
(wrapping at the edge) and only then publishes it by bumping
``write_pos``: the consumer can never observe a partial record. Records
are ``u8 kind | u64 payload_len | payload``:

- **kind 1 (inline)** — the payload is the exact byte string the TCP
  transport would have put on the wire (header JSON + format tag + frame
  table). One memcpy in, one out; byte-identical message semantics fall
  out of reusing the same structs.
- **kind 2 (mapped)** — the frame table carries ``(pool_offset, len)``
  references into the shared frame pool instead of frame bytes. The warm
  cache-hit path: the worker publishes a few dozen bytes of offsets for a
  multi-megabyte batch whose frames already live in shared memory.
- **kind 3 (spill)** — an ordering marker with no payload: the real
  framed message follows on the paired TCP socket (it was bigger than the
  ring). The marker is committed to the ring BEFORE the TCP send, so the
  consumer's total order is always the ring order.

Doorbells are a pair of eventfds (data: producer→consumer, space:
consumer→producer) rung **only when the peer advertised it is waiting**
via the header flags — under sustained flow both sides find the next
record/space by reading shared memory and the steady-state syscall count
per message is zero (``petastorm_transport_syscalls_total``). A waiter
publishes its flag, re-checks the condition (so a wakeup can never be
lost), then parks in a bounded ``select`` that also watches the paired
socket — peer death without a doorbell surfaces as TCP EOF within one
poll interval, never a hang.

Failure semantics mirror the TCP tier exactly: a vanished producer is
:class:`ConnectionClosedError` (after every committed record is drained —
a clean ``end`` is never lost to the close that follows it), a desynced
or fenced arena is :class:`ProtocolError` — both funnel into the client's
existing broken-stream recovery (watermarks, takeover, dedup). Three
failpoints are compiled into the producer (``shm-detach``,
``torn-doorbell``, ``stale-arena``; see ``failpoints.POINTS``) so the
chaos fuzzer exercises all three paths.

Every live mapping and doorbell fd is registered here
(:func:`live_shm_counts`) — the tests' conftest leak guard fails any test
that orphans one, same as threads/sockets/cache dirs.
"""

from __future__ import annotations

import errno
import gc
import json
import mmap
import os
import select
import socket
import struct
import tempfile
import threading
import time

import numpy as np

from petastorm_tpu import failpoints as _failpoints
from petastorm_tpu.reader_impl.framed_socket import (
    _FMT,
    _LEN,
    _NFRAMES,
    ConnectionClosedError,
    ProtocolError,
    _check_header_len,
    _decode_header,
    _decode_payload,
    _encode_payload,
    send_framed_frames,
)
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    SHM_ARENAS,
    SHM_FRAMES,
    TRANSPORT_BYTES,
    TRANSPORT_FRAMES,
    TRANSPORT_MESSAGES,
    TRANSPORT_SYSCALLS,
)

logger = service_logger(__name__)

# Interned children (one lock-guarded add per message, no dict lookup) —
# the shm-tier counterparts of framed_socket's tcp children.
_TX_MESSAGES = TRANSPORT_MESSAGES.labels("sent", "shm")
_TX_FRAMES = TRANSPORT_FRAMES.labels("sent", "shm")
_TX_BYTES = TRANSPORT_BYTES.labels("sent", "shm")
_RX_MESSAGES = TRANSPORT_MESSAGES.labels("recv", "shm")
_RX_FRAMES = TRANSPORT_FRAMES.labels("recv", "shm")
_RX_BYTES = TRANSPORT_BYTES.labels("recv", "shm")
_SYSCALLS = TRANSPORT_SYSCALLS.labels("shm")
_FRAMES_MAPPED = SHM_FRAMES.labels("mapped")
_FRAMES_COPIED = SHM_FRAMES.labels("copied")
_FRAMES_SPILLED = SHM_FRAMES.labels("spilled")
_ARENAS_RING = SHM_ARENAS.labels("ring")
_ARENAS_POOL = SHM_ARENAS.labels("pool")

_MAGIC = b"PTSHMR1\0"
_VERSION = 1
_HEADER_BYTES = 256
_OFF_MAGIC = 0
_OFF_VERSION = 8
_OFF_GENERATION = 16
_OFF_DATA_OFFSET = 24
_OFF_DATA_SIZE = 32
_OFF_WRITE_POS = 40
_OFF_READ_POS = 48
_OFF_CONSUMER_WAITING = 56
_OFF_PRODUCER_WAITING = 64
_OFF_FLAGS = 72

FLAG_PRODUCER_DETACHED = 1
FLAG_CONSUMER_DETACHED = 2

_U64 = struct.Struct("<Q")
_REC = struct.Struct("<BQ")       # record prefix: kind, payload length
_POOL_REF = struct.Struct("!QQ")  # mapped frame reference: offset, length

REC_INLINE = 1
REC_MAPPED = 2
REC_SPILL = 3

#: Default ring data-region size. Big enough that typical collated batch
#: messages (tens of KB to ~1 MB) ride inline or mapped; anything larger
#: spills to the paired socket behind an ordering marker.
DEFAULT_RING_BYTES = int(os.environ.get("PETASTORM_SHM_RING_BYTES",
                                        4 * 1024 * 1024))
#: Default worker-global frame pool size (backs mapped cache serves).
DEFAULT_POOL_BYTES = int(os.environ.get("PETASTORM_SHM_POOL_BYTES",
                                        32 * 1024 * 1024))
#: Bounded-park interval: a waiter re-checks peer liveness (TCP EOF,
#: detach flags) at least this often even if every doorbell is lost.
_PARK_S = 0.2

memfd_name_prefix = "ptshm"


class ShmSetupError(OSError):
    """Arena/pool creation failed (memfd unavailable, shm exhaustion).
    The negotiation layer downgrades the stream to TCP — never errors it."""


class ShmAttachError(OSError):
    """The consumer could not attach an offered arena (container
    boundary, dead producer, fd-reopen refused). The client nacks the
    offer and the stream proceeds over TCP."""


# ---------------------------------------------------------------------------
# live-resource registry (the conftest leak guard's hook)

_LIVE_LOCK = threading.Lock()
_LIVE = {"rings": 0, "pools": 0, "eventfds": 0}


def live_shm_counts():
    """Snapshot of live shm resources in this process: mapped ring ends
    (producer and consumer each count one), mapped pools, and open
    doorbell eventfds. All-zero between tests; anything else is a leak."""
    with _LIVE_LOCK:
        return dict(_LIVE)


def _register(key, n=1):
    with _LIVE_LOCK:
        _LIVE[key] += n
    if key == "rings":
        _ARENAS_RING.inc(n)
    elif key == "pools":
        _ARENAS_POOL.inc(n)


def _deregister(key, n=1):
    with _LIVE_LOCK:
        _LIVE[key] -= n
    if key == "rings":
        _ARENAS_RING.dec(n)
    elif key == "pools":
        _ARENAS_POOL.dec(n)


# ---------------------------------------------------------------------------
# arena plumbing

def _create_shm_fd(name, size):
    """A pre-faulted shared-memory fd of ``size`` bytes, or
    :class:`ShmSetupError`. memfd first (name-scoped so the leak guard
    can spot orphans in /proc/self/fd; not subject to the /dev/shm mount
    cap); an unlinked /dev/shm tempfile as the fallback. Pre-faulting
    writes every page NOW so tmpfs exhaustion surfaces here as a
    catchable setup error — not later as SIGBUS on a lazy first touch
    mid-stream (the PR 12 ENOSPC-degradation discipline)."""
    fd = None
    try:
        fd = os.memfd_create(f"{memfd_name_prefix}-{name}")
    except (AttributeError, OSError) as exc:
        try:
            tmp = tempfile.NamedTemporaryFile(
                prefix=f"{memfd_name_prefix}-{name}-", dir="/dev/shm",
                delete=False)
        except OSError:
            raise ShmSetupError(
                f"no shared-memory backing available (memfd_create: "
                f"{exc})") from exc
        fd = os.dup(tmp.file.fileno())
        tmp.file.close()
        try:
            os.unlink(tmp.name)
        except OSError:
            logger.warning("could not unlink shm fallback file %s",
                           tmp.name)
    try:
        os.ftruncate(fd, size)
        chunk = b"\0" * min(size, 1 << 20)
        off = 0
        while off < size:
            off += os.pwrite(fd, chunk[:min(len(chunk), size - off)], off)
    except OSError as exc:
        os.close(fd)
        raise ShmSetupError(
            f"could not pre-fault {size}-byte shm arena "
            f"({errno.errorcode.get(exc.errno, exc.errno)}: {exc}) — "
            f"shared memory exhausted?") from exc
    return fd


def _reopen_fd(pid, fd, nonblock=False):
    """A local fd for a peer's fd: same process → dup; otherwise reopen
    through /proc (works for memfds and eventfds alike when the peer is
    truly on this host and not behind a container/pidns boundary)."""
    if pid == os.getpid():
        return os.dup(fd)
    flags = os.O_RDWR | (os.O_NONBLOCK if nonblock else 0)
    return os.open(f"/proc/{pid}/fd/{fd}", flags)


def _close_fd_quiet(fd):
    try:
        os.close(fd)
    except OSError:
        pass


def _shutdown_quiet(sock):
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass


def _close_mmap(mm, what):
    """Close ``mm``, absorbing lingering buffer exports: collect and
    retry once; an export that survives (a frame still referenced
    somewhere) downgrades to a logged leak-until-exit rather than a
    crash. Returns whether the mapping actually closed."""
    try:
        mm.close()
        return True
    except BufferError:
        gc.collect()
        try:
            mm.close()
            return True
        except BufferError:
            logger.warning(
                "%s mmap still has exported buffers at close; leaving "
                "the mapping to process exit", what)
            return False


def _sock_eof(sock):
    """Nonblocking peek: has the peer closed its end? (False on plain
    'no data yet'; True on EOF or a reset — both mean the peer is gone.)"""
    try:
        return sock.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
    except (BlockingIOError, InterruptedError):
        return False
    except OSError:
        return True


def _eventfd_drain(efd):
    try:
        os.eventfd_read(efd)
    except (BlockingIOError, InterruptedError):
        pass
    except OSError:
        pass  # closed under us during teardown


class _Arena:
    """One mapped arena end (producer or consumer): the mmap, the header
    accessors, and wrap-aware data-region copies."""

    def __init__(self, mm, size):
        self.mm = mm
        self.size = size
        self.data_offset = self.get(_OFF_DATA_OFFSET)
        self.data_size = self.get(_OFF_DATA_SIZE)
        if (self.data_offset != _HEADER_BYTES
                or self.data_offset + self.data_size != size):
            raise ProtocolError(
                f"shm arena geometry is inconsistent (data_offset="
                f"{self.data_offset}, data_size={self.data_size}, "
                f"mapped={size})")

    def get(self, off):
        return _U64.unpack_from(self.mm, off)[0]

    def put(self, off, value):
        _U64.pack_into(self.mm, off, value)

    def copy_in(self, pos, buf):
        """Write ``buf`` at absolute stream position ``pos`` (wrapping);
        returns the next absolute position."""
        view = memoryview(buf).cast("B") if not isinstance(buf, bytes) \
            else buf
        n = len(view)
        rel = pos % self.data_size
        first = min(n, self.data_size - rel)
        start = self.data_offset + rel
        self.mm[start:start + first] = view[:first]
        if first < n:
            self.mm[self.data_offset:self.data_offset + n - first] = \
                view[first:]
        return pos + n

    def copy_out(self, pos, n):
        """Read ``n`` bytes at absolute stream position ``pos`` into a
        fresh bytearray (wrapping)."""
        out = bytearray(n)
        rel = pos % self.data_size
        first = min(n, self.data_size - rel)
        start = self.data_offset + rel
        out[:first] = self.mm[start:start + first]
        if first < n:
            out[first:] = self.mm[self.data_offset:
                                  self.data_offset + n - first]
        return out


class RingProducer:
    """The worker-side end of one stream's ring: creates the arena and
    doorbells, exposes the framed ``send``/``send_frames`` interface, and
    carries the three shm failpoints. One producer per stream, driven by
    one serve thread."""

    def __init__(self, sock, pool=None, data_size=None):
        data_size = DEFAULT_RING_BYTES if data_size is None else data_size
        total = _HEADER_BYTES + data_size
        fd = _create_shm_fd("ring", total)
        efd_data = efd_space = None
        try:
            efd_data = os.eventfd(0, os.EFD_NONBLOCK)
            efd_space = os.eventfd(0, os.EFD_NONBLOCK)
        except (AttributeError, OSError) as exc:
            _close_fd_quiet(fd)
            if efd_data is not None:
                _close_fd_quiet(efd_data)
            raise ShmSetupError(f"eventfd unavailable ({exc})") from exc
        try:
            mm = mmap.mmap(fd, total)
        except (OSError, ValueError) as exc:
            for f in (fd, efd_data, efd_space):
                _close_fd_quiet(f)
            raise ShmSetupError(f"could not map ring arena ({exc})") \
                from exc
        mm[_OFF_MAGIC:_OFF_MAGIC + len(_MAGIC)] = _MAGIC
        for off, value in ((_OFF_VERSION, _VERSION),
                           (_OFF_GENERATION, 1),
                           (_OFF_DATA_OFFSET, _HEADER_BYTES),
                           (_OFF_DATA_SIZE, data_size),
                           (_OFF_WRITE_POS, 0), (_OFF_READ_POS, 0),
                           (_OFF_CONSUMER_WAITING, 0),
                           (_OFF_PRODUCER_WAITING, 0), (_OFF_FLAGS, 0)):
            _U64.pack_into(mm, off, value)
        self._arena = _Arena(mm, total)
        self._fd = fd
        self._efd_data = efd_data
        self._efd_space = efd_space
        self._sock = sock
        self._pool = pool
        self._write_pos = 0
        self._generation = 1
        self._closed = False
        self.transport = "shm"
        _register("rings")
        _register("eventfds", 2)

    def descriptor(self):
        """What the ``shm_offer`` message carries: everything a colocated
        consumer needs to attach (fds are reopened via /proc when the
        consumer is another process)."""
        return {"pid": os.getpid(), "fd": self._fd,
                "efd_data": self._efd_data, "efd_space": self._efd_space,
                "size": self._arena.size,
                "data_size": self._arena.data_size,
                "generation": self._generation}

    def drop_pool(self):
        """Stop emitting mapped (pool-reference) records: the negotiation
        layer calls this when the consumer acked the ring but could not
        attach the frame pool — every frame then travels inline, which is
        correct (just copied) for any consumer."""
        self._pool = None

    # -- framed send interface ------------------------------------------

    def send(self, header, payload=None):
        fmt, frames = _encode_payload(payload)
        self.send_frames(header, fmt, frames)

    def send_frames(self, header, fmt, frames):
        if self._closed:
            raise ConnectionClosedError("shm ring producer is closed")
        fp = _failpoints.ACTIVE
        if fp is not None:  # disarmed cost: one global load + None branch
            self._inject(fp)
        header_bytes = json.dumps(header).encode("utf-8")
        refs = None
        if self._pool is not None and frames:
            refs = self._pool.locate(frames)
        if refs is not None:
            self._send_mapped(header_bytes, fmt, frames, refs)
        else:
            self._send_inline(header, header_bytes, fmt, frames)

    def _send_mapped(self, header_bytes, fmt, frames, refs):
        parts = [_LEN.pack(len(header_bytes)), header_bytes,
                 _FMT.pack(fmt), _NFRAMES.pack(len(refs))]
        frame_bytes = 0
        for off, length in refs:
            parts.append(_POOL_REF.pack(off, length))
            frame_bytes += length
        payload_len = sum(len(p) for p in parts)
        self._append(REC_MAPPED, parts, payload_len)
        _TX_MESSAGES.inc()
        _TX_FRAMES.inc(len(refs))
        _TX_BYTES.inc(payload_len + frame_bytes)
        _FRAMES_MAPPED.inc(len(refs))

    def _send_inline(self, header, header_bytes, fmt, frames):
        views = [memoryview(f) for f in frames]
        parts = [_LEN.pack(len(header_bytes)), header_bytes,
                 _FMT.pack(fmt), _NFRAMES.pack(len(views))]
        payload_len = sum(len(p) for p in parts)
        for view in views:
            parts.append(_LEN.pack(view.nbytes))
            parts.append(view)
            payload_len += _LEN.size + view.nbytes
        if _REC.size + payload_len > self._arena.data_size:
            # Bigger than the ring can ever hold: spill to the paired
            # socket. The marker is committed BEFORE the socket send so
            # the consumer's ring order is the message order.
            self._append(REC_SPILL, (), 0)
            send_framed_frames(self._sock, header, fmt, frames)
            _FRAMES_SPILLED.inc(len(views))
            return
        self._append(REC_INLINE, parts, payload_len)
        _TX_MESSAGES.inc()
        _TX_FRAMES.inc(len(views))
        _TX_BYTES.inc(payload_len)
        _FRAMES_COPIED.inc(len(views))

    def _append(self, kind, parts, payload_len):
        needed = _REC.size + payload_len
        self._wait_space(needed)
        pos = self._arena.copy_in(self._write_pos,
                                  _REC.pack(kind, payload_len))
        for part in parts:
            pos = self._arena.copy_in(pos, part)
        self._write_pos = pos
        self._arena.put(_OFF_WRITE_POS, pos)
        if self._arena.get(_OFF_CONSUMER_WAITING):
            self._ring(self._efd_data)

    def _ring(self, efd):
        try:
            os.eventfd_write(efd, 1)
            _SYSCALLS.inc()
        except OSError:
            pass  # peer-side teardown race: the flags/EOF checks govern

    def _wait_space(self, needed):
        arena = self._arena
        while True:
            if self._closed:
                raise ConnectionClosedError("shm ring producer is closed")
            if arena.get(_OFF_FLAGS) & FLAG_CONSUMER_DETACHED:
                raise ConnectionClosedError(
                    "shm ring consumer detached")
            free = arena.data_size - (self._write_pos
                                      - arena.get(_OFF_READ_POS))
            if free >= needed:
                return
            arena.put(_OFF_PRODUCER_WAITING, 1)
            try:
                free = arena.data_size - (self._write_pos
                                          - arena.get(_OFF_READ_POS))
                if free >= needed:
                    continue
                try:
                    readable, _, _ = select.select(
                        [self._efd_space], [], [], _PARK_S)
                except (OSError, ValueError):
                    raise ConnectionClosedError(
                        "shm ring doorbell closed while waiting for "
                        "space") from None
                _SYSCALLS.inc()
                if readable:
                    _eventfd_drain(self._efd_space)
                    _SYSCALLS.inc()
                elif _sock_eof(self._sock):
                    raise ConnectionClosedError(
                        "peer closed the paired socket while the shm "
                        "ring was full")
            finally:
                arena.put(_OFF_PRODUCER_WAITING, 0)

    # -- failpoints ------------------------------------------------------

    def _inject(self, fp):
        if fp.fire("shm-detach") == "detach":
            self._arena.put(
                _OFF_FLAGS,
                self._arena.get(_OFF_FLAGS) | FLAG_PRODUCER_DETACHED)
            self._ring(self._efd_data)
            _shutdown_quiet(self._sock)
            raise ConnectionResetError(
                "failpoint shm-detach: producer detached mid-stream")
        if fp.fire("torn-doorbell") == "torn":
            # A garbage record header is published — the shm analogue of
            # a torn TCP length prefix. The consumer must refuse it as a
            # protocol error; the socket reset makes the damage
            # two-sided, as a real producer crash would.
            free = self._arena.data_size - (
                self._write_pos - self._arena.get(_OFF_READ_POS))
            if free >= _REC.size:
                pos = self._arena.copy_in(
                    self._write_pos, _REC.pack(0xFF, (1 << 63) + 1))
                self._write_pos = pos
                self._arena.put(_OFF_WRITE_POS, pos)
            self._ring(self._efd_data)
            _shutdown_quiet(self._sock)
            raise ConnectionResetError(
                "failpoint torn-doorbell: garbage record committed")
        if fp.fire("stale-arena") == "stale":
            self._arena.put(_OFF_GENERATION, self._generation + 1)
            self._ring(self._efd_data)
            _shutdown_quiet(self._sock)
            raise ConnectionResetError(
                "failpoint stale-arena: arena generation fenced")

    def close(self):
        """Detach: raise the producer-gone flag, ring the doorbell so a
        parked consumer wakes to drain what is committed, then release
        the mapping and fds. Never tears down the paired socket — the
        connection owner does that."""
        if self._closed:
            return
        self._closed = True
        try:
            self._arena.put(
                _OFF_FLAGS,
                self._arena.get(_OFF_FLAGS) | FLAG_PRODUCER_DETACHED)
            self._ring(self._efd_data)
        except (OSError, ValueError):
            logger.warning("shm ring producer flag/doorbell write failed "
                           "at close", exc_info=True)
        _close_mmap(self._arena.mm, "ring producer")
        for fd in (self._fd, self._efd_data, self._efd_space):
            _close_fd_quiet(fd)
        _deregister("rings")
        _deregister("eventfds", 2)


class RingConsumer:
    """The client-side end: attaches a producer's descriptor and exposes
    the framed ``recv`` interface. One consumer per stream, driven by one
    reader thread. ``reader`` is the connection's FramedReader — spilled
    messages are received through it so its buffered bytes stay coherent."""

    def __init__(self, descriptor, sock, reader):
        self._sock = sock
        self._reader = reader
        pid = int(descriptor["pid"])
        fds = []
        try:
            self._fd = _reopen_fd(pid, int(descriptor["fd"]))
            fds.append(self._fd)
            self._efd_data = _reopen_fd(pid, int(descriptor["efd_data"]),
                                        nonblock=True)
            fds.append(self._efd_data)
            self._efd_space = _reopen_fd(pid, int(descriptor["efd_space"]),
                                         nonblock=True)
            fds.append(self._efd_space)
            mm = mmap.mmap(self._fd, int(descriptor["size"]))
        except (OSError, ValueError) as exc:
            for fd in fds:
                _close_fd_quiet(fd)
            raise ShmAttachError(
                f"could not attach shm arena from pid {pid} ({exc})") \
                from exc
        if mm[_OFF_MAGIC:_OFF_MAGIC + len(_MAGIC)] != _MAGIC:
            mm.close()
            for fd in fds:
                _close_fd_quiet(fd)
            raise ShmAttachError("attached arena has no ring magic")
        try:
            self._arena = _Arena(mm, int(descriptor["size"]))
        except ProtocolError as exc:
            mm.close()
            for fd in fds:
                _close_fd_quiet(fd)
            raise ShmAttachError(str(exc)) from exc
        self._generation = int(descriptor["generation"])
        self._read_pos = self._arena.get(_OFF_READ_POS)
        self._pool = None
        self._closed = False
        self.transport = "shm"
        _register("rings")
        _register("eventfds", 2)

    def attach_pool(self, pool):
        """Arm the mapped-record path with an attached FramePool (or
        leave unattached: mapped records then fail as protocol errors,
        which negotiation prevents by ack'ing ``pool: false``)."""
        self._pool = pool

    # -- framed recv interface ------------------------------------------

    def recv(self, timeout=None):
        """Receive one framed message → ``(header dict, payload)`` —
        same contract (and exception vocabulary) as FramedReader.recv."""
        deadline = None if timeout is None else time.monotonic() + timeout
        empty_sock_strikes = 0
        while True:
            if self._closed:
                raise ConnectionClosedError("shm ring consumer is closed")
            gen = self._arena.get(_OFF_GENERATION)
            if gen != self._generation:
                raise ProtocolError(
                    f"shm arena generation moved {self._generation} → "
                    f"{gen} under the stream (stale arena) — the mapping "
                    f"is fenced")
            record = self._try_pop()
            if record is not None:
                kind, payload = record
                if kind == REC_SPILL:
                    return self._reader.recv()
                return self._parse(kind, payload)
            if self._arena.get(_OFF_FLAGS) & FLAG_PRODUCER_DETACHED:
                raise ConnectionClosedError(
                    "shm ring producer detached (every committed record "
                    "was drained first)")
            if deadline is not None and time.monotonic() >= deadline:
                raise socket.timeout("timed out waiting on the shm ring")
            empty_sock_strikes = self._park(deadline, empty_sock_strikes)

    def _park(self, deadline, strikes):
        """Publish the waiting flag, re-check, park in a bounded select
        on the doorbell + the paired socket. Returns the updated
        consecutive count of 'socket readable but ring empty' wakeups —
        a few in a row mean bytes arrived with no marker committed
        first, which no healthy producer can produce."""
        arena = self._arena
        arena.put(_OFF_CONSUMER_WAITING, 1)
        try:
            if arena.get(_OFF_WRITE_POS) != self._read_pos \
                    or arena.get(_OFF_FLAGS) & FLAG_PRODUCER_DETACHED \
                    or arena.get(_OFF_GENERATION) != self._generation:
                return 0
            wait = _PARK_S if deadline is None \
                else max(0.0, min(_PARK_S, deadline - time.monotonic()))
            try:
                readable, _, _ = select.select(
                    [self._efd_data, self._sock], [], [], wait)
            except (OSError, ValueError):
                raise ConnectionClosedError(
                    "shm ring doorbell or paired socket closed while "
                    "waiting for data") from None
            if not readable:
                return 0
            if self._efd_data in readable:
                _eventfd_drain(self._efd_data)
                return 0
            # Socket readable with (apparently) nothing in the ring:
            # either EOF (peer gone — drain, then the caller raises), or
            # a spill marker that became visible between our check and
            # the select (benign), or a true desync.
            if arena.get(_OFF_WRITE_POS) != self._read_pos:
                return 0
            if _sock_eof(self._sock):
                if arena.get(_OFF_WRITE_POS) == self._read_pos:
                    raise ConnectionClosedError(
                        "peer closed the paired socket with the shm "
                        "ring drained")
                return 0
            strikes += 1
            if strikes >= 3:
                raise ProtocolError(
                    "bytes arrived on the spill socket with no marker "
                    "committed to the shm ring — desynced producer")
            time.sleep(0.005)
            return strikes
        finally:
            arena.put(_OFF_CONSUMER_WAITING, 0)

    def _try_pop(self):
        """One committed record, or ``None`` — never blocks. Validates
        the record header against the committed region: a kind outside
        the vocabulary or a length beyond what the producer published is
        a desync (the torn-doorbell failure mode)."""
        arena = self._arena
        write_pos = arena.get(_OFF_WRITE_POS)
        avail = write_pos - self._read_pos
        if avail == 0:
            return None
        if avail > arena.data_size or avail < _REC.size:
            raise ProtocolError(
                f"shm ring positions desynced (write_pos={write_pos}, "
                f"read_pos={self._read_pos}, data_size="
                f"{arena.data_size})")
        kind, payload_len = _REC.unpack(
            bytes(arena.copy_out(self._read_pos, _REC.size)))
        if kind not in (REC_INLINE, REC_MAPPED, REC_SPILL) \
                or _REC.size + payload_len > avail:
            raise ProtocolError(
                f"shm ring record header is garbage (kind={kind}, "
                f"payload_len={payload_len}, committed={avail}) — torn "
                f"producer write")
        payload = arena.copy_out(self._read_pos + _REC.size, payload_len) \
            if payload_len else b""
        self._read_pos += _REC.size + payload_len
        arena.put(_OFF_READ_POS, self._read_pos)
        if arena.get(_OFF_PRODUCER_WAITING):
            try:
                os.eventfd_write(self._efd_space, 1)
            except OSError:
                pass  # producer-side teardown race
        return kind, payload

    def _parse(self, kind, payload):
        view = memoryview(payload)
        try:
            pos = 0
            header_len = _LEN.unpack_from(view, pos)[0]
            pos += _LEN.size
            _check_header_len(header_len)
            header = _decode_header(bytes(view[pos:pos + header_len]))
            pos += header_len
            fmt = _FMT.unpack_from(view, pos)[0]
            pos += _FMT.size
            n_frames = _NFRAMES.unpack_from(view, pos)[0]
            pos += _NFRAMES.size
            frames = []
            total_bytes = pos
            if kind == REC_INLINE:
                for _ in range(n_frames):
                    frame_len = _LEN.unpack_from(view, pos)[0]
                    pos += _LEN.size
                    if pos + frame_len > len(view):
                        raise ProtocolError(
                            "shm inline record frame overruns its "
                            "payload — torn producer write")
                    # Each frame keeps TCP's writable-private-buffer
                    # semantics: out-of-band reconstruction may hand it
                    # to a numpy array the trainer mutates.
                    frames.append(bytearray(view[pos:pos + frame_len]))
                    pos += frame_len
                    total_bytes += _LEN.size + frame_len
            else:  # REC_MAPPED: (pool offset, length) references
                if self._pool is None:
                    raise ProtocolError(
                        "mapped shm record but no frame pool attached — "
                        "negotiation desync")
                for _ in range(n_frames):
                    off, frame_len = _POOL_REF.unpack_from(view, pos)
                    pos += _POOL_REF.size
                    frames.append(self._pool.read(off, frame_len))
                    total_bytes += frame_len
        except struct.error as exc:
            raise ProtocolError(
                f"shm record payload truncated ({exc}) — torn producer "
                f"write") from exc
        result = _decode_payload(fmt, frames)
        _RX_MESSAGES.inc()
        _RX_FRAMES.inc(n_frames)
        _RX_BYTES.inc(total_bytes)
        return header, result

    def close(self):
        """Detach: raise the consumer-gone flag (waking a producer parked
        on space), release the mapping and fds."""
        if self._closed:
            return
        self._closed = True
        try:
            self._arena.put(
                _OFF_FLAGS,
                self._arena.get(_OFF_FLAGS) | FLAG_CONSUMER_DETACHED)
            os.eventfd_write(self._efd_space, 1)
        except (OSError, ValueError):
            pass  # producer already gone: nothing to wake
        _close_mmap(self._arena.mm, "ring consumer")
        for fd in (self._fd, self._efd_data, self._efd_space):
            _close_fd_quiet(fd)
        _deregister("rings")
        _deregister("eventfds", 2)


class FramePool:
    """A worker-global shared-memory bump allocator for pre-serialized
    frame bytes. The decoded-batch cache routes entry buffers through
    :meth:`allocate`, so a warm hit's frames already live in shared
    memory and the ring publishes them as ``(offset, len)`` references —
    the mapped-serve path. Allocation is bump-only (no free): offsets
    handed to a consumer stay valid for the pool's lifetime, which is
    what makes the references safe without cross-process refcounting. A
    full pool degrades new entries to heap buffers (served inline), never
    errors."""

    def __init__(self, size=None, _attach=None):
        self._lock = threading.Lock()
        if _attach is None:
            self.size = DEFAULT_POOL_BYTES if size is None else int(size)
            self._fd = _create_shm_fd("pool", self.size)
            try:
                self._mm = mmap.mmap(self._fd, self.size)
            except (OSError, ValueError) as exc:
                _close_fd_quiet(self._fd)
                raise ShmSetupError(
                    f"could not map frame pool ({exc})") from exc
            self._owner = True
        else:
            pid, fd, self.size = _attach
            try:
                self._fd = _reopen_fd(pid, fd)
            except OSError as exc:
                raise ShmAttachError(
                    f"could not reopen frame pool fd from pid {pid} "
                    f"({exc})") from exc
            try:
                self._mm = mmap.mmap(self._fd, self.size)
            except (OSError, ValueError) as exc:
                _close_fd_quiet(self._fd)
                raise ShmAttachError(
                    f"could not map frame pool ({exc})") from exc
            self._owner = False
        self._mv = memoryview(self._mm)
        arr = np.frombuffer(self._mm, dtype=np.uint8)
        self._base = int(arr.__array_interface__["data"][0])
        del arr
        self._bump = 0
        self._closed = False
        _register("pools")

    @classmethod
    def attach(cls, descriptor):
        """Consumer-side attach from a producer's :meth:`descriptor`."""
        return cls(_attach=(int(descriptor["pid"]),
                            int(descriptor["fd"]),
                            int(descriptor["size"])))

    def descriptor(self):
        return {"pid": os.getpid(), "fd": self._fd, "size": self.size}

    def allocate(self, nbytes):
        """A writable memoryview of ``nbytes`` pool bytes, or ``None``
        when the pool is exhausted (bump-only — the caller degrades to a
        heap buffer). This is the cache's frame-allocator hook."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return None
        with self._lock:
            if self._closed or self._bump + nbytes > self.size:
                return None
            offset = self._bump
            self._bump = (self._bump + nbytes + 7) & ~7  # 8-byte align
            return self._mv[offset:offset + nbytes]

    def locate(self, frames):
        """``[(offset, len), ...]`` when EVERY frame's bytes live inside
        this pool, else ``None`` (one foreign frame makes the whole
        message inline — a mixed record would still copy, for no win).
        Detection is by address: frames served from a pool-backed cache
        entry are memoryview slices of this very mapping."""
        refs = []
        base, top = self._base, self._base + self.size
        for frame in frames:
            view = memoryview(frame)
            if view.nbytes == 0:
                refs.append((0, 0))
                continue
            if not view.c_contiguous:
                return None
            addr = int(np.frombuffer(view.cast("B"), dtype=np.uint8)
                       .__array_interface__["data"][0])
            if not (base <= addr and addr + view.nbytes <= top):
                return None
            refs.append((addr - base, view.nbytes))
        return refs

    def read(self, offset, nbytes):
        """A private writable copy of pool bytes (consumer side): the
        delivered batch must tolerate in-place trainer mutation without
        corrupting the producer's cache entry."""
        if offset + nbytes > self.size:
            raise ProtocolError(
                f"mapped frame reference ({offset}+{nbytes}) overruns "
                f"the {self.size}-byte pool")
        return bytearray(self._mv[offset:offset + nbytes])

    def used_bytes(self):
        with self._lock:
            return self._bump

    def close(self):
        if self._closed:
            return
        with self._lock:
            self._closed = True
        self._mv.release()
        _close_mmap(self._mm, "frame pool")
        _close_fd_quiet(self._fd)
        _deregister("pools")
