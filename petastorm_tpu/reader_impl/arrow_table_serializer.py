"""Arrow-IPC payload serializer: near-zero-copy ``pa.Table`` transport.

Reference parity: ``petastorm/reader_impl/arrow_table_serializer.py``. Used by
the batch reader's process pool: a table is written as an Arrow IPC stream
(columnar buffers, no per-cell pickling) and mapped back on the consumer side
without copies where possible.
"""

from __future__ import annotations

import pyarrow as pa


class ArrowTableSerializer:
    def serialize(self, table):
        if not isinstance(table, pa.Table):
            raise ValueError(f"ArrowTableSerializer serializes pa.Table, got {type(table)}")
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def deserialize(self, serialized_rows):
        with pa.ipc.open_stream(pa.BufferReader(serialized_rows)) as reader:
            return reader.read_all()

    # -- zero-copy multipart surface (zmq_copy_buffers=True) ---------------

    def serialize_to_frames(self, table):
        """One frame per table: the IPC stream buffer, passed as a buffer
        object (not ``to_pybytes``) so zmq can send it without copying."""
        if not isinstance(table, pa.Table):
            raise ValueError(
                f"ArrowTableSerializer serializes pa.Table, got {type(table)}")
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return [sink.getvalue()]  # pa.Buffer supports the buffer protocol

    def deserialize_from_frames(self, frames):
        """Map the received frame back to a table; arrow reads the IPC stream
        directly from the frame's memory (zero-copy column buffers)."""
        buf = frames[0] if len(frames) == 1 else b"".join(
            bytes(f) for f in frames)
        with pa.ipc.open_stream(pa.BufferReader(pa.py_buffer(buf))) as reader:
            return reader.read_all()
