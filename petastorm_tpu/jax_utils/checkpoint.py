"""Joint model + input-pipeline checkpointing (orbax + reader state).

The reference has no checkpointable reader state at all (SURVEY.md §5
"Checkpoint / resume: absent for readers"); this framework added resumable
iteration (``Reader.state_dict`` / ``resume_state=``,
``JaxDataLoader.state_dict``). What was still the user's job is gluing that
to MODEL checkpointing so a preempted training job restores both halves
consistently — this module is that glue:

- model arrays (params / optimizer state — any pytree of jax/numpy arrays)
  go through ``orbax.checkpoint`` (async-capable, TPU-aware restore);
- the loader/reader input state (a small JSON-serializable dict) rides in
  the same checkpoint directory as a JSON file, captured BETWEEN steps from
  the training thread — the consistency point the resume machinery is
  specified against (at-least-once delivery on restore).

On a pod every host checkpoints its OWN input state (shard identity is part
of it) while orbax handles the array layout; restore hands each host back
the state it saved (``input_state.<process_index>.json``).
"""

from __future__ import annotations

import json
import os

_INPUT_STATE_TMPL = "input_state.{}.json"
_ARRAYS_DIR = "arrays"


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax missing/uninitialized
        return 0


def save_training_state(directory, arrays, loader=None, input_state=None,
                        force=True):
    """Write ``arrays`` (pytree) + the input-pipeline state under
    ``directory``.

    :param arrays: pytree of params / optimizer state (jax or numpy arrays).
    :param loader: a :class:`~petastorm_tpu.jax_utils.loader.JaxDataLoader`
        to snapshot via its ``state_dict()`` (call between steps). Mutually
        exclusive with ``input_state``.
    :param input_state: a pre-captured reader/loader state dict.
    :param force: overwrite an existing checkpoint at ``directory``.
    """
    if loader is not None and input_state is not None:
        raise ValueError("pass loader OR input_state, not both")
    if loader is not None:
        input_state = loader.state_dict()

    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(directory, _ARRAYS_DIR), arrays, force=force)
    ckptr.wait_until_finished()
    if input_state is not None:
        path = os.path.join(directory,
                            _INPUT_STATE_TMPL.format(_process_index()))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(input_state, f)
        os.replace(tmp, path)  # atomic publish
    return directory


def restore_training_state(directory, abstract_arrays=None):
    """Restore ``(arrays, input_state)`` from ``directory``.

    :param abstract_arrays: optional pytree of ``jax.ShapeDtypeStruct`` (or
        concrete arrays) guiding orbax's typed/sharded restore; ``None``
        restores as saved.
    :return: ``(arrays, input_state_or_None)`` — pass the input state as
        ``resume_state=`` to the reader factory feeding a fresh loader
        (buffered-but-unyielded rows are re-read: at-least-once).
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    ckptr = ocp.StandardCheckpointer()
    arrays_path = os.path.join(directory, _ARRAYS_DIR)
    if abstract_arrays is None:
        arrays = ckptr.restore(arrays_path)
    else:
        arrays = ckptr.restore(arrays_path, abstract_arrays)
    path = os.path.join(directory,
                        _INPUT_STATE_TMPL.format(_process_index()))
    input_state = None
    if os.path.exists(path):
        with open(path) as f:
            input_state = json.load(f)
    return arrays, input_state
