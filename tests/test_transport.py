"""Transport negotiation: shm grant, downgrade-to-TCP paths, mode knobs.

The contract under test (docs/guides/service.md#transport-tiers): shm is
an optimization the stream setup *negotiates*, never a requirement — any
failure on the shm path (arena setup, client attach) serves the SAME
stream request over TCP without erroring the stream or losing the credit
window, counted in ``petastorm_transport_downgrades_total``. Delivery
invariance across tiers is covered by the ``transport``-parametrized
tests in test_determinism / test_service / test_dynamic_sharding; this
file covers the negotiation machinery itself.
"""

import pytest

from petastorm_tpu.service import BatchWorker, Dispatcher, ServiceBatchSource
from petastorm_tpu.service import shm_ring
from petastorm_tpu.service import transport as transport_mod
from petastorm_tpu.telemetry.metrics import TRANSPORT_DOWNGRADES

pytestmark = pytest.mark.service


def _fleet(url, transport=None):
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    worker = BatchWorker(url, dispatcher_address=dispatcher.address,
                         batch_size=7, reader_factory="row", worker_id="w0",
                         transport=transport,
                         reader_kwargs={"workers_count": 2}).start()
    return dispatcher, worker


def _stream_all(source):
    return sorted(int(i) for batch in source() for i in batch["id"])


def _expected_ids(dataset):
    return sorted(int(r["id"]) for r in dataset.rows)


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------

def test_resolve_mode_precedence(monkeypatch):
    monkeypatch.delenv("PETASTORM_TRANSPORT", raising=False)
    assert transport_mod.resolve_mode() == "auto"
    assert transport_mod.resolve_mode("tcp") == "tcp"
    monkeypatch.setenv("PETASTORM_TRANSPORT", "tcp")
    assert transport_mod.resolve_mode() == "tcp"
    # An explicit argument outranks the env var.
    assert transport_mod.resolve_mode("shm") == "shm"
    with pytest.raises(ValueError, match="transport must be one of"):
        transport_mod.resolve_mode("carrier-pigeon")


def test_advertisement_shape():
    assert transport_mod.advertisement("tcp") is None
    advert = transport_mod.advertisement("auto")
    assert advert["modes"] == ["shm"]
    assert advert["host"] == transport_mod.host_token()


# ---------------------------------------------------------------------------
# the grant path, and forcing TCP
# ---------------------------------------------------------------------------

def test_loopback_auto_negotiates_shm(petastorm_dataset):
    """Defaults on both ends, same host: streams ride the ring (the
    positive check that the rest of the suite isn't silently on TCP)."""
    dispatcher, worker = _fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        assert _stream_all(source) == _expected_ids(petastorm_dataset)
        metrics = worker.diagnostics_snapshot()["metrics"]
        assert metrics["transport_streams_shm_total"] >= 1
        assert metrics["transport_streams_tcp_total"] == 0
    finally:
        worker.stop()
        dispatcher.stop()


@pytest.mark.parametrize("side", ["client", "worker"])
def test_transport_tcp_on_either_side_forces_tcp(petastorm_dataset, side):
    """``--transport tcp`` on EITHER end pins the stream to TCP — the
    escape hatch must not depend on which process got the flag."""
    before = TRANSPORT_DOWNGRADES.labels("arena_setup").value \
        + TRANSPORT_DOWNGRADES.labels("client_nack").value
    dispatcher, worker = _fleet(
        petastorm_dataset.url,
        transport="tcp" if side == "worker" else None)
    try:
        source = ServiceBatchSource(
            dispatcher.address,
            transport="tcp" if side == "client" else None)
        assert _stream_all(source) == _expected_ids(petastorm_dataset)
        metrics = worker.diagnostics_snapshot()["metrics"]
        assert metrics["transport_streams_shm_total"] == 0
        assert metrics["transport_streams_tcp_total"] >= 1
    finally:
        worker.stop()
        dispatcher.stop()
    # Choosing TCP is not a downgrade: nothing failed.
    after = TRANSPORT_DOWNGRADES.labels("arena_setup").value \
        + TRANSPORT_DOWNGRADES.labels("client_nack").value
    assert after == before


def test_cross_host_peer_serves_tcp_without_counting_a_downgrade(
        petastorm_dataset, monkeypatch):
    """A client on another host advertises shm too — the worker's host
    check routes it to TCP silently (the right tier, not a failure)."""
    monkeypatch.setattr(
        transport_mod, "advertisement",
        lambda mode: None if mode == "tcp" else
        {"modes": ["shm"], "host": "some-other-host", "pid": 1})
    before = TRANSPORT_DOWNGRADES.labels("arena_setup").value \
        + TRANSPORT_DOWNGRADES.labels("client_nack").value
    dispatcher, worker = _fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        assert _stream_all(source) == _expected_ids(petastorm_dataset)
        metrics = worker.diagnostics_snapshot()["metrics"]
        assert metrics["transport_streams_shm_total"] == 0
        assert metrics["transport_streams_tcp_total"] >= 1
    finally:
        worker.stop()
        dispatcher.stop()
    after = TRANSPORT_DOWNGRADES.labels("arena_setup").value \
        + TRANSPORT_DOWNGRADES.labels("client_nack").value
    assert after == before


# ---------------------------------------------------------------------------
# downgrade paths: the stream must complete on the SAME request
# ---------------------------------------------------------------------------

def test_arena_setup_failure_downgrades_same_request(
        petastorm_dataset, monkeypatch):
    """/dev/shm exhaustion at ring construction: the worker logs the
    downgrade, serves this same stream request over TCP, and the client
    never notices (no stream error, no retry, full delivery)."""

    def exploding_producer(*args, **kwargs):
        raise shm_ring.ShmSetupError("injected: /dev/shm exhausted")

    monkeypatch.setattr(shm_ring, "RingProducer", exploding_producer)
    before = TRANSPORT_DOWNGRADES.labels("arena_setup").value
    dispatcher, worker = _fleet(petastorm_dataset.url)
    try:
        # credits=2 doubles as the credit-window check: a window damaged
        # during the failed negotiation would stall a 2-credit stream
        # forever, not complete it.
        source = ServiceBatchSource(dispatcher.address, credits=2)
        assert _stream_all(source) == _expected_ids(petastorm_dataset)
        assert source.diagnostics["recovery"]["takeovers"] == 0
        metrics = worker.diagnostics_snapshot()["metrics"]
        assert metrics["transport_streams_shm_total"] == 0
        assert metrics["transport_streams_tcp_total"] >= 1
    finally:
        worker.stop()
        dispatcher.stop()
    assert TRANSPORT_DOWNGRADES.labels("arena_setup").value > before


def test_client_attach_failure_nacks_and_downgrades_same_request(
        petastorm_dataset, monkeypatch):
    """The worker's arena is fine but the client cannot attach it: the
    client nacks, the worker closes the offered ring and serves this
    same request over TCP — again no stream error and no lost credit."""

    def exploding_consumer(*args, **kwargs):
        raise shm_ring.ShmAttachError("injected: attach refused")

    monkeypatch.setattr(shm_ring, "RingConsumer", exploding_consumer)
    before = TRANSPORT_DOWNGRADES.labels("client_nack").value
    baseline_shm = shm_ring.live_shm_counts()
    dispatcher, worker = _fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address, credits=2)
        assert _stream_all(source) == _expected_ids(petastorm_dataset)
        assert source.diagnostics["recovery"]["takeovers"] == 0
        metrics = worker.diagnostics_snapshot()["metrics"]
        assert metrics["transport_streams_shm_total"] == 0
        assert metrics["transport_streams_tcp_total"] >= 1
    finally:
        worker.stop()
        dispatcher.stop()
    assert TRANSPORT_DOWNGRADES.labels("client_nack").value > before
    # The nacked ring (and the worker's frame pool) must not leak.
    assert shm_ring.live_shm_counts() == baseline_shm
