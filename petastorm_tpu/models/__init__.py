"""Example/flagship model consumers of the data framework.

The reference ships no model code (SURVEY.md §0: petastorm is an input-data
framework) — these models exist to exercise and demonstrate the TPU delivery
path end-to-end: Parquet → Reader → ``make_jax_dataloader`` → sharded pjit
train step. They are intentionally small, pure-JAX (no flax dependency), and
written SPMD-first: parameters carry explicit ``PartitionSpec`` s so a single
``jax.jit`` over a ``Mesh`` scales them data- and tensor-parallel.
"""

from petastorm_tpu.models.image_classifier import (
    apply_model,
    init_params,
    make_train_step,
    param_partition_specs,
)
from petastorm_tpu.models.tabular_dlrm import (
    apply_dlrm,
    dlrm_partition_specs,
    init_dlrm_params,
    make_dlrm_train_step,
)
from petastorm_tpu.models.moe import (
    apply_moe_model,
    init_moe_params,
    make_moe_train_step,
    moe_param_partition_specs,
)

__all__ = ["init_params", "apply_model", "make_train_step",
           "param_partition_specs", "init_dlrm_params", "apply_dlrm",
           "make_dlrm_train_step", "dlrm_partition_specs",
           "init_moe_params", "apply_moe_model", "make_moe_train_step",
           "moe_param_partition_specs"]
