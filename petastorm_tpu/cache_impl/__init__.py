"""Epoch-aware decoded-batch caching.

The decode-bypass tier of the input pipeline (ISSUE 5 / PAPERS: tf.data
service and cedar both name materialized-output caching as the highest-
leverage optimization once the pipeline is disaggregated): collated numpy
batches — the exact payloads the service workers stream and the JAX
loader's producer collates — are cached under a content fingerprint, in a
memory-budgeted LRU tier with an optional disk tier, so epoch ≥ 2 of a
multi-epoch training run skips Parquet read + decode + collate entirely.

- :mod:`~petastorm_tpu.cache_impl.fingerprint` — content keys: dataset url
  + piece identity + fields/schema + batch/transform config.
- :mod:`~petastorm_tpu.cache_impl.batch_cache` — :class:`BatchCache`, the
  tiered store. Entries hold each batch as serializer frames packed into
  one contiguous buffer, so the service worker's hit path scatter-gathers
  frames straight out of cache memory (``framed_socket.send_framed_frames``)
  with zero re-serialization.
- :mod:`~petastorm_tpu.cache_impl.eviction` — the shared size-budget LRU
  eviction policy for on-disk caches (also behind the seed-parity
  ``LocalDiskCache``).

Cache-directory tracking: every directory a cache *creates* is registered
here and deregistered by its ``cleanup()``; the test suite's leak guard
fails any test that orphans one (the worker-restart leak class).
"""

from __future__ import annotations

import threading

from petastorm_tpu.cache_impl.batch_cache import BatchCache, CacheConfig
from petastorm_tpu.cache_impl.fingerprint import (
    batch_fingerprint,
    predicate_ingredient,
)

__all__ = [
    "BatchCache",
    "CacheConfig",
    "batch_fingerprint",
    "predicate_ingredient",
    "register_cache_dir",
    "deregister_cache_dir",
    "live_cache_dirs",
]

_DIRS_LOCK = threading.Lock()
_LIVE_CACHE_DIRS = set()


def register_cache_dir(path):
    """Record that a cache created ``path`` and has not cleaned it up yet.
    The tier-1 leak guard snapshots this set around every test."""
    with _DIRS_LOCK:
        _LIVE_CACHE_DIRS.add(str(path))


def deregister_cache_dir(path):
    with _DIRS_LOCK:
        _LIVE_CACHE_DIRS.discard(str(path))


def live_cache_dirs():
    """Snapshot of cache-created directories not yet cleaned up."""
    with _DIRS_LOCK:
        return set(_LIVE_CACHE_DIRS)
