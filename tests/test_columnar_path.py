"""Arrow-native columnar hot path (docs/guides/service.md#columnar-hot-path).

Covers the `row_vs_columnar` rewrite's correctness surface:

- the COLUMNAR wire format: eligibility, roundtrip fidelity, pickle
  fallback for exotic dtypes, and the decode.columnar failpoint at the
  serialize boundary;
- vectorized decode_column vs the per-row base loop, per codec family
  (scalar, ndarray, jpeg/png, Decimal-as-string) — the kernels the
  decode.columnar failpoint flips between;
- zero-copy collate aliasing safety: a warm cache hit serves READ-ONLY
  column views (mutation raises instead of corrupting the entry), while
  wire-delivered batches stay writable private buffers;
- the worker's per-stream family resolution fallback rules (degrade to
  the row path, never error);
- service-level digest identity across the family flip, under shuffle
  and a warm cache (the tier-1 slice of the columnar_ab bench gate).
"""

import numpy as np
import pytest

from petastorm_tpu import failpoints
from petastorm_tpu.reader_impl.framed_socket import (
    PAYLOAD_COLUMNAR,
    PAYLOAD_PICKLE,
    _decode_payload,
    _encode_payload,
)


def _roundtrip(payload):
    fmt, frames = _encode_payload(payload)
    return fmt, _decode_payload(fmt, [bytearray(bytes(f)) for f in frames])


def _always_fallback_schedule(calls=100_000):
    """decode.columnar fires "fallback" on EVERY call — the 100%-rate
    arm of the soak's digest gate."""
    return failpoints.FaultSchedule(
        0, points=["decode.columnar"],
        fires={"decode.columnar": {i: "fallback" for i in range(calls)}})


# ---------------------------------------------------------------------------
# COLUMNAR wire format
# ---------------------------------------------------------------------------

def test_columnar_payload_roundtrip_and_eligibility():
    batch = {
        "ids": np.arange(10, dtype=np.int64),
        "img": np.arange(24, dtype=np.uint8).reshape(2, 3, 4),
        "f": np.linspace(0, 1, 10, dtype=np.float32),
        "names": np.array(["a", "bc"], dtype="<U2"),
        "raw": np.array([b"xy", b"z"], dtype="S2"),
        "when": np.array(["2026-08-07"], dtype="datetime64[D]"),
    }
    fmt, out = _roundtrip(batch)
    assert fmt == PAYLOAD_COLUMNAR
    assert sorted(out) == sorted(batch)
    for name in batch:
        assert out[name].dtype == batch[name].dtype, name
        assert out[name].shape == batch[name].shape, name
        assert np.array_equal(out[name], batch[name]), name
        # Wire-delivered frames are private buffers → writable (the
        # established delivery contract; cache views are the read-only
        # exception, tested below).
        assert out[name].flags.writeable, name


def test_columnar_payload_ineligible_falls_back_to_pickle():
    import ml_dtypes

    ragged = {"obj": np.array([np.zeros(2), np.zeros(3)], dtype=object)}
    extension = {"bf16": np.zeros(4, dtype=ml_dtypes.bfloat16)}
    not_arrays = {"x": np.zeros(3), "n": 7}
    empty = {}
    for payload in (ragged, extension, not_arrays, empty):
        fmt, frames = _encode_payload(payload)
        assert fmt == PAYLOAD_PICKLE
        out = _decode_payload(fmt, [bytearray(bytes(f)) for f in frames])
        assert sorted(out) == sorted(payload)


def test_decode_columnar_failpoint_forces_pickle_wire_format():
    """The serialize-boundary site: under a scheduled "fallback" the
    qualifying batch rides PAYLOAD_PICKLE — decoded content identical."""
    batch = {"ids": np.arange(6, dtype=np.int32)}
    schedule = _always_fallback_schedule()
    with failpoints.armed(schedule):
        fmt, frames = _encode_payload(batch)
    assert fmt == PAYLOAD_PICKLE
    out = _decode_payload(fmt, [bytearray(bytes(f)) for f in frames])
    assert np.array_equal(out["ids"], batch["ids"])
    assert "decode.columnar" in failpoints.POINTS


# ---------------------------------------------------------------------------
# vectorized decode_column ≡ per-row decode, per codec family
# ---------------------------------------------------------------------------

def _encoded_cells(field, values):
    return np.array([field.codec.encode(field, v) for v in values],
                    dtype=object)


def _codec_cases():
    from decimal import Decimal

    from petastorm_tpu.schema.codecs import (CompressedImageCodec,
                                             NdarrayCodec, ScalarCodec)
    from petastorm_tpu.schema.unischema import UnischemaField

    rng = np.random.RandomState(7)
    return [
        (UnischemaField("s", np.int64, (), ScalarCodec(np.int64), False),
         [np.int64(v) for v in rng.randint(-5, 5, 8)]),
        (UnischemaField("f", np.float32, (), ScalarCodec(np.float32), False),
         [np.float32(v) for v in rng.rand(8)]),
        (UnischemaField("nd", np.float32, (3, 2), NdarrayCodec(), False),
         [rng.rand(3, 2).astype(np.float32) for _ in range(8)]),
        (UnischemaField("png", np.uint8, (8, 6, 3),
                        CompressedImageCodec("png"), False),
         [rng.randint(0, 255, (8, 6, 3)).astype(np.uint8)
          for _ in range(8)]),
        (UnischemaField("jpg", np.uint8, (16, 16, 3),
                        CompressedImageCodec("jpeg"), False),
         [rng.randint(0, 255, (16, 16, 3)).astype(np.uint8)
          for _ in range(8)]),
        (UnischemaField("dec", Decimal, (), ScalarCodec(Decimal), False),
         [Decimal(f"{i}.{i}5") for i in range(8)]),
        (UnischemaField("txt", str, (), ScalarCodec(str), False),
         [f"row {i}" for i in range(8)]),
    ]


@pytest.mark.parametrize("field,values",
                         _codec_cases(),
                         ids=lambda v: getattr(v, "name", ""))
def test_decode_column_matches_per_row_decode(field, values):
    """The vectorized kernel and the base per-row loop (the
    decode.columnar "fallback" target) must agree bit-for-bit — this is
    the equality the soak's digest gate rests on. JPEG is lossy but
    DETERMINISTIC: both paths run the same imdecode, so equality still
    holds on the decoded bytes."""
    from petastorm_tpu.schema.codecs import DataframeColumnCodec

    cells = _encoded_cells(field, values)
    vectorized = field.codec.decode_column(field, cells)
    rowwise = DataframeColumnCodec.decode_column(field.codec, field, cells)
    assert np.asarray(vectorized).dtype == np.asarray(rowwise).dtype
    assert np.array_equal(np.asarray(vectorized), np.asarray(rowwise))


def test_decode_table_columnar_kernels_match_decode_row():
    """utils.decode_table routes null-free codec columns through
    decode_column; the result must equal the per-row decode_row path."""
    import pyarrow as pa

    from petastorm_tpu.schema.unischema import Unischema
    from petastorm_tpu.utils import decode_row, decode_table

    cases = _codec_cases()
    schema = Unischema("T", [field for field, _ in cases])
    data = {}
    for field, values in cases:
        cells = [field.codec.encode(field, v) for v in values]
        if field.name == "dec":
            cells = [str(c) for c in cells]
        data[field.name] = cells
    table = pa.table(data)
    ref = [decode_row(row, schema) for row in table.to_pylist()]
    got = decode_table(table, schema)
    assert len(ref) == len(got)
    for a, b in zip(ref, got):
        assert sorted(a) == sorted(b)
        for name in a:
            va, vb = np.asarray(a[name]), np.asarray(b[name])
            assert va.dtype == vb.dtype, name
            assert np.array_equal(va, vb), name


def test_predicate_read_with_row_drop_partitions_matches_row_path(
        petastorm_dataset):
    """The vectorized two-phase predicate read now returns Arrow and
    applies shuffle_row_drop_partitions via table.take — same rows as
    the per-row reference for every partition."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.predicates import ColumnPredicate, in_lambda

    def ids(predicate, part):
        got = set()
        with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=False,
                         predicate=predicate,
                         shuffle_row_drop_partitions=part) as reader:
            for row in reader:
                got.add(int(row.id))
        return got

    vectorized = ColumnPredicate("id2", "lt", 3)
    # in_lambda has no pa_mask/do_include_vectorized → per-row fallback.
    rowwise = in_lambda(["id2"], lambda values: values["id2"] < 3)
    for part in (1, 2):
        assert ids(vectorized, part) == ids(rowwise, part)


# ---------------------------------------------------------------------------
# zero-copy collate aliasing safety
# ---------------------------------------------------------------------------

def test_warm_cache_hit_serves_read_only_views_and_survives_mutation():
    from petastorm_tpu.cache_impl.batch_cache import CachedBatch
    from petastorm_tpu.reader_impl.framed_socket import _encode_payload

    batch = {"x": np.arange(8, dtype=np.int64),
             "y": np.ones((4, 2), dtype=np.float32)}
    fmt, frames = _encode_payload(batch)
    assert fmt == PAYLOAD_COLUMNAR
    # Entry buffers are writable (they may be shm FramePool memoryviews).
    entry = CachedBatch(rows=8, fmt=fmt,
                        frames=[bytearray(bytes(f)) for f in frames])
    served = entry.to_dict()
    for name in batch:
        assert np.array_equal(served[name], batch[name])
        assert not served[name].flags.writeable, name
        with pytest.raises(ValueError):
            served[name][0] = 0
    # The entry's buffers are untouched: a second serve is identical.
    again = entry.to_dict()
    for name in batch:
        assert np.array_equal(again[name], batch[name])


def test_wire_delivered_batch_mutation_does_not_corrupt_source():
    """Over the wire every frame is received into private buffers —
    mutating a delivered batch must not reach the sender's copy."""
    batch = {"x": np.arange(8, dtype=np.int64)}
    fmt, frames = _encode_payload(batch)
    received = _decode_payload(fmt, [bytearray(bytes(f)) for f in frames])
    received["x"][:] = -1
    assert np.array_equal(batch["x"], np.arange(8, dtype=np.int64))
    assert np.array_equal(
        _decode_payload(fmt, [bytearray(bytes(f)) for f in frames])["x"],
        np.arange(8, dtype=np.int64))


# ---------------------------------------------------------------------------
# worker family resolution: degrade, never error
# ---------------------------------------------------------------------------

def test_resolve_stream_family_fallback_rules(petastorm_dataset):
    from petastorm_tpu.service.worker import BatchWorker

    def worker(**kwargs):
        kwargs.setdefault("reader_factory", "row")
        return BatchWorker(petastorm_dataset.url, batch_size=8,
                           heartbeat_interval_s=None, **kwargs)

    row = worker()
    # No request / request == constructed → no swap.
    assert row._resolve_stream_family(None, engine=True) == (None, "row")
    assert row._resolve_stream_family("row", engine=True) == (None, "row")
    # The honored swap, both directions.
    assert row._resolve_stream_family("columnar", engine=True) \
        == ("columnar", "columnar")
    col = worker(reader_factory="columnar")
    assert col._resolve_stream_family("row", engine=True) == ("row", "row")
    # Non-engine serving path → fall back to the constructed family.
    assert row._resolve_stream_family("columnar", engine=False) \
        == (None, "row")
    # Batch-family worker: no unischema decode contract to vectorize.
    batch = worker(reader_factory="batch")
    assert batch._resolve_stream_family("columnar", engine=True) \
        == (None, "batch")
    # Row-granularity reader options refuse the columnar swap.
    spec = worker(reader_kwargs={"transform_spec": object()})
    assert spec._resolve_stream_family("columnar", engine=True) \
        == (None, "row")
    ngram = worker(reader_kwargs={"ngram": object()})
    assert ngram._resolve_stream_family("columnar", engine=True) \
        == (None, "row")


# ---------------------------------------------------------------------------
# service-level digest identity across the family flip (tier-1 scenario)
# ---------------------------------------------------------------------------

def _family_run(url, *, reader_family, reader_factory="row",
                batch_cache=None, num_epochs=1, shuffle_seed=11,
                batch_size=7):
    from petastorm_tpu.service import (BatchWorker, Dispatcher,
                                       ServiceBatchSource)
    from petastorm_tpu.service.chaos import StreamDigest

    dispatcher = Dispatcher(port=0, mode="static", num_epochs=num_epochs,
                            shuffle_seed=shuffle_seed).start()
    worker = BatchWorker(url, dispatcher_address=dispatcher.address,
                         batch_size=batch_size, reader_factory=reader_factory,
                         batch_cache=batch_cache,
                         reader_kwargs={"workers_count": 2}).start()
    try:
        source = ServiceBatchSource(dispatcher.address, ordered=True,
                                    reader_family=reader_family)
        digest = StreamDigest()
        rows = 0
        for batch in source():
            digest.update(batch)
            rows += len(next(iter(batch.values())))
        return {"digest": digest.hexdigest(), "rows": rows,
                "metrics": worker.diagnostics_snapshot()["metrics"]}
    finally:
        worker.stop()
        dispatcher.stop()


def test_family_flip_digest_identical_under_shuffle_and_warm_cache(
        petastorm_dataset):
    """The rewrite's acceptance gate: same seed, ordered delivery, two
    epochs over a mem cache (epoch 2 serves cached frames) — the row and
    columnar families must deliver byte-identical streams, and the
    columnar run must actually take the vectorized path. batch_size=8
    against 10-row pieces cuts null-FREE ragged tails from the nullable
    column (rows 28/29): the piece-level object column must re-collate
    dense per batch exactly like the row path's ``_stack_column``."""
    from petastorm_tpu.cache_impl import CacheConfig

    def run(family):
        return _family_run(
            petastorm_dataset.url, reader_family=family, num_epochs=2,
            batch_size=8,
            batch_cache=CacheConfig(mode="mem", mem_mb=64.0).build())

    row, col = run("row"), run("columnar")
    assert row["rows"] == col["rows"] == 2 * len(petastorm_dataset.rows)
    assert row["digest"] == col["digest"]
    assert col["metrics"]["columnar_batches_total"] > 0
    assert col["metrics"]["row_fallback_batches_total"] == 0
    assert row["metrics"]["columnar_batches_total"] == 0


def test_columnar_request_on_batch_worker_degrades_to_row_fallback(
        petastorm_dataset):
    """An unservable columnar request degrades (never errors): the
    batch-family worker serves its constructed path and counts the
    stream's batches as path="row_fallback"."""
    from petastorm_tpu.cache_impl import CacheConfig

    plain = _family_run(petastorm_dataset.url, reader_family=None,
                        reader_factory="batch",
                        batch_cache=CacheConfig(mode="mem",
                                                mem_mb=64.0).build())
    asked = _family_run(petastorm_dataset.url, reader_family="columnar",
                        reader_factory="batch",
                        batch_cache=CacheConfig(mode="mem",
                                                mem_mb=64.0).build())
    assert asked["digest"] == plain["digest"]
    assert asked["metrics"]["row_fallback_batches_total"] > 0
    assert asked["metrics"]["columnar_batches_total"] == 0


def test_columnar_decode_failpoint_stream_digest_identical(
        petastorm_dataset):
    """decode.columnar "fallback" at 100% rate: every columnar decode and
    serialize runs the row path — the delivered stream must still be
    byte-identical to the unperturbed columnar run (the fuzz soak's
    digest gate for this point, in miniature)."""
    clean = _family_run(petastorm_dataset.url, reader_family="columnar")
    schedule = _always_fallback_schedule()
    with failpoints.armed(schedule):
        perturbed = _family_run(petastorm_dataset.url,
                                reader_family="columnar")
    assert perturbed["digest"] == clean["digest"]


# ---------------------------------------------------------------------------
# COL% rendering
# ---------------------------------------------------------------------------

def test_fleet_status_renders_columnar_share():
    from petastorm_tpu.service.cli import render_fleet_status

    status = {"mode": "static", "fencing_epoch": 0, "recovery": {},
              "workers": {"w0": {"alive": True}}, "clients": {}}

    def sample(t, columnar, fallback):
        return {"t": t, "status": status,
                "workers": {"w0": {"metrics": {
                    "rows_sent_total": 100.0 * t,
                    "batches_sent_total": 10.0 * t,
                    "credit_wait_seconds_total": 0.0,
                    "active_streams": 1,
                    "columnar_batches_total": columnar,
                    "row_fallback_batches_total": fallback}}}}

    text = render_fleet_status(sample(0.0, 0.0, 0.0),
                               sample(2.0, 9.0, 1.0))
    assert "COL%" in text
    row = next(line for line in text.splitlines() if line.startswith("w0"))
    assert "90.0" in row
    # Workers that never saw a columnar-requested stream render "--".
    no_col = render_fleet_status(sample(0.0, 0.0, 0.0),
                                 sample(2.0, 0.0, 0.0))
    row = next(line for line in no_col.splitlines()
               if line.startswith("w0"))
    assert "--" in row
