"""``make_jax_dataloader`` — batches from a Reader into TPU HBM.

Pipeline (SURVEY.md §7 stage 5, hard-part #6 "pipelined host→HBM staging"):

    Reader (its own worker pool)            ← Parquet read + decode
      → producer thread: collate to fixed-size numpy batches (batcher.py)
      → bounded host queue (backpressure)
      → consumer: async ``jax.device_put`` kept ``device_prefetch`` batches
        ahead (double buffering — H2D DMA overlaps the caller's compute)
      → yields jax.Array batches (or globally-sharded arrays when a
        ``sharding`` is given — per-shard direct-to-device placement when
        every device is addressable, ``make_array_from_process_local_data``
        on a pod)

With a :class:`~petastorm_tpu.jax_utils.DeviceStage` armed
(``device_stage=``), image fields are staged as RAW uint8 bytes and a
fused JIT kernel performs cast/normalize/crop/flip on the accelerator —
H2D moves bytes, not float32 pixels (``docs/guides/device_decode.md``).

Input-stall instrumentation is built in: time the consumer blocks waiting on
the host queue is "stall", measured against wall time between yields —
``loader.diagnostics['input_stall_pct']`` is the north-star metric
(BASELINE.md: ≤5% stall at v5e-64).

Non-tensor columns (strings, Decimals — object-dtype after collation) cannot
live in HBM; the ``non_tensor_policy`` knob keeps them host-side ("host",
default), drops them ("drop"), or rejects them ("error").
"""

from __future__ import annotations

import contextlib
import itertools
import queue
import sys
import threading
import time

import numpy as np

from petastorm_tpu.jax_utils.batcher import PAD_MASK_KEY, batch_iterator
from petastorm_tpu.utils import resize_bounded_queue
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.metrics import (
    LOADER_BATCHES,
    LOADER_DISPATCH_OVERLAP,
    LOADER_ROWS,
    LOADER_STAGE_SECONDS,
)

_SENTINEL = object()

#: Loader pipeline stages, as histogram label values: ``decode`` (reader
#: pull + collation), ``queue_wait`` (producer blocked on a full host
#: queue), ``wait`` (consumer blocked on input — the stall), ``raw_stage``
#: (staging the raw uint8 bytes batch for the device decode stage),
#: ``device_decode`` (the fused on-device decode/augment kernel dispatch),
#: ``shard_put`` (each per-shard device_put inside a sharded delivery),
#: ``device_put`` (H2D dispatch of ordinary tensors), ``consumer`` (the
#: training step between yields).
_STAGES = ("decode", "queue_wait", "wait", "raw_stage", "device_decode",
           "shard_put", "device_put", "consumer")

#: Stages that are device-dispatch work (the ledger ``device_dispatch_s``
#: sums and the overlap gauge measures). ``shard_put`` is excluded: its
#: observations happen INSIDE the raw_stage/device_put windows (one per
#: target device) — summing it too would double-count.
_DISPATCH_STAGES = ("raw_stage", "device_decode", "device_put")

#: Per-process loader instance ids — the ``loader`` label value, so each
#: loader's series are separable in a scrape and the legacy per-iteration
#: diagnostics can be re-derived as (current - iteration-start baseline).
#: Ids are RECYCLED: a garbage-collected loader's series are removed from
#: the registry and its id returns to the pool (weakref.finalize), so a
#: trainer constructing loaders in a loop does not grow the registry —
#: live cardinality stays at the number of live loaders.
_LOADER_IDS = itertools.count()
_LOADER_ID_POOL = []


def _acquire_loader_id():
    try:
        return _LOADER_ID_POOL.pop()
    except IndexError:
        return str(next(_LOADER_IDS))


def _release_loader_metrics(loader_id):
    """weakref.finalize callback: retire a dead loader's series."""
    LOADER_BATCHES.remove(loader_id)
    LOADER_ROWS.remove(loader_id)
    LOADER_DISPATCH_OVERLAP.remove(loader_id)
    for stage in _STAGES:
        LOADER_STAGE_SECONDS.remove(loader_id, stage)
    _LOADER_ID_POOL.append(loader_id)


def _trace_span(name):
    """``jax.profiler.TraceAnnotation`` when jax is already loaded, else a
    no-op — the loader's pipeline stages show up in profiler traces
    (SURVEY.md §5 tracing note) without forcing a jax import on the
    numpy-only path (``stage_to_device=False``)."""
    jax = sys.modules.get("jax")
    # getattr guard: another thread may be mid-way through `import jax`, in
    # which case sys.modules already holds a partially-initialized module.
    profiler = getattr(jax, "profiler", None) if jax is not None else None
    if profiler is None:
        return contextlib.nullcontext()
    return profiler.TraceAnnotation(name)


def make_jax_dataloader(reader, batch_size,
                        last_batch="drop",
                        max_batches=None,
                        device=None,
                        sharding=None,
                        host_prefetch=4,
                        device_prefetch=2,
                        non_tensor_policy="host",
                        stage_to_device=True,
                        shuffle_buffer_size=0,
                        shuffle_seed=None,
                        stage_in_producer=False,
                        trace_path=None,
                        batch_cache=None,
                        device_stage=None,
                        cache_resume=None,
                        autotune=None):
    """Create a :class:`JaxDataLoader` over ``reader``.

    :param reader: a ``make_reader``/``make_batch_reader`` Reader (row, NGram,
        or column-batch output all supported).
    :param batch_size: rows per emitted batch. With ``sharding``, this is the
        *per-host* batch size; the global array's batch dim is
        ``batch_size * jax.process_count()``.
    :param last_batch: "drop" | "pad" | "keep" (see batcher.py; "pad" adds a
        boolean ``__pad_mask__`` column).
    :param max_batches: stop after N batches (equal-step coordination: pass
        the pre-agreed per-host step count).
    :param device: target ``jax.Device`` (default: first local device).
        Mutually exclusive with ``sharding``.
    :param sharding: a ``jax.sharding.Sharding``; batches are emitted as
        globally-sharded ``jax.Array`` s via
        ``make_array_from_process_local_data``.
    :param host_prefetch: bounded host-queue depth (collated numpy batches).
    :param device_prefetch: how many batches to keep in-flight on device
        (≥2 ⇒ double buffering). HBM cost: every in-flight batch is
        device-resident, so deep prefetch holds up to
        ``device_prefetch × batch_bytes`` of HBM beyond the model's
        working set (2× that under ``stage_in_producer``, which adds a
        device-resident queue of the same depth) — the loader drops its
        own references the moment a batch is consumed, so this bound is
        tight: raise it for jitter absorption only as HBM allows.
    :param non_tensor_policy: "host" | "drop" | "error" for object-dtype
        columns.
    :param stage_to_device: False ⇒ yield plain numpy dicts (no JAX import;
        useful for tests and host-only consumers).
    :param shuffle_buffer_size: > 0 adds a row-level RandomShufflingBuffer on
        top of row-group shuffling (reference ``shuffling_queue_capacity``
        semantics; row readers only).
    :param shuffle_seed: seed for the shuffle buffer.
    :param stage_in_producer: run ``device_put`` dispatch off the consumer's
        critical path, on a dedicated STAGING thread fed by the decode
        thread: decode and H2D dispatch overlap (both release the GIL), so
        the pipeline's per-batch cost is max(decode, dispatch) instead of
        their sum, and the consumer's per-step input cost shrinks to a
        queue get. Best when steps are long enough to hide the slower of
        the two; not supported with ``sharding``. In this mode the device
        queue's depth is bounded by ``device_prefetch`` (not
        ``host_prefetch``): total in-flight device batches stay ≤
        2·``device_prefetch`` + 1 — raise ``device_prefetch`` for deeper
        jitter absorption (decoded host batches additionally buffer up to
        ``host_prefetch`` between the two threads).
    :param trace_path: write a Perfetto-loadable Chrome ``trace_event``
        JSON of per-batch pipeline spans here at the end of each iteration
        (arms the process trace collector; see
        ``docs/guides/diagnostics.md#metrics-and-tracing``). ``None`` (the
        default) records nothing.
    :param batch_cache: a :class:`~petastorm_tpu.cache_impl.BatchCache`
        (or ``None``). The producer consults it before pulling the reader:
        on a hit the whole epoch's collated batch sequence is served from
        cache (the reader — and the Parquet read + decode behind it — is
        not touched, so iterating the loader again replays the epoch even
        though the underlying ``num_epochs=1`` reader is exhausted); on a
        miss the decoded sequence is written through as it streams.
        Shuffle-compatible: with shuffling requested (``shuffle_seed``, a
        shuffle buffer, or a ``shuffle_row_groups`` reader) the entry
        stays canonical and each pass is served through a fresh seed-tree
        batch permutation — order changes per epoch, bytes don't; note
        the row-level shuffle buffer is superseded by batch-granularity
        permutation while the cache is armed, and the shuffled fill pass
        buffers the epoch before its first yield
        (``docs/guides/caching.md#shuffle-compatible-serving``).
    :param cache_resume: a prior ``state_dict()`` of kind
        ``"cache_replay"`` — resumes a shuffled cached pass at its exact
        permuted batch position (requires ``batch_cache`` and the same
        reader construction).
    :param device_stage: a :class:`~petastorm_tpu.jax_utils.DeviceStage`
        (or ``None``). When armed, the loader stages each batch's raw
        uint8 image fields AS BYTES (4x fewer H2D bytes than float32
        pixels) and a fused JIT kernel performs cast/normalize/crop/flip
        ON the device, with the raw buffer donated to the kernel on
        TPU/GPU so in-flight HBM stays bounded. With ``sharding``, the raw
        batch is delivered shard-by-shard directly onto each target device
        and decoded as one global array (``docs/guides/device_decode.md``).
        Requires ``stage_to_device=True``.
    :param autotune: arm the profile-driven online autotuner
        (``docs/guides/pipeline.md``): the loader's pipeline is described
        as an explicit stage graph and a controller thread periodically
        re-plans the runtime knobs — reader-pool ``workers_count``,
        ``host_prefetch``/``device_prefetch``, and (with a
        ``ServiceBatchSource``) ``credits``/``ready_queue_depth``/
        ``transform_placement`` — within declared bounds, from measured
        per-stage profiles. ``True`` uses defaults; a dict may set
        ``interval_s``, ``bounds`` (``{knob: (lo, hi)}``),
        ``hysteresis``, ``placement_hysteresis``, ``tolerance``. The
        default ``None`` builds no graph and starts no thread — static
        behavior is bit-for-bit unchanged.
    """
    return JaxDataLoader(reader, batch_size, last_batch=last_batch,
                         max_batches=max_batches, device=device,
                         sharding=sharding, host_prefetch=host_prefetch,
                         device_prefetch=device_prefetch,
                         non_tensor_policy=non_tensor_policy,
                         stage_to_device=stage_to_device,
                         shuffle_buffer_size=shuffle_buffer_size,
                         shuffle_seed=shuffle_seed,
                         stage_in_producer=stage_in_producer,
                         trace_path=trace_path,
                         batch_cache=batch_cache,
                         device_stage=device_stage,
                         cache_resume=cache_resume,
                         autotune=autotune)


class JaxDataLoader:
    """Iterable/context-manager yielding ``{field: array}`` batches."""

    def __init__(self, reader, batch_size, last_batch="drop", max_batches=None,
                 device=None, sharding=None, host_prefetch=4,
                 device_prefetch=2, non_tensor_policy="host",
                 stage_to_device=True, shuffle_buffer_size=0,
                 shuffle_seed=None, stage_in_producer=False,
                 batch_source=None, trace_path=None, batch_cache=None,
                 device_stage=None, cache_resume=None, autotune=None):
        if device is not None and sharding is not None:
            raise ValueError("device and sharding are mutually exclusive")
        if device_stage is not None and not stage_to_device:
            raise ValueError(
                "device_stage decodes ON the device; it cannot run with "
                "stage_to_device=False (the numpy-only path never touches "
                "a device) — drop the stage or enable device staging")
        if stage_in_producer and sharding is not None:
            raise ValueError(
                "stage_in_producer is not supported with a global sharding "
                "(make_array_from_process_local_data must run on the thread "
                "driving the pjit steps)")
        if non_tensor_policy not in ("host", "drop", "error"):
            raise ValueError("non_tensor_policy must be host|drop|error")
        if device_prefetch < 1:
            raise ValueError("device_prefetch must be >= 1")
        if batch_source is not None:
            if shuffle_buffer_size or shuffle_seed is not None \
                    or last_batch != "drop":
                raise ValueError(
                    "shuffle_buffer_size/shuffle_seed/last_batch are row-"
                    "batching knobs the custom batch_source path does not "
                    "consume; shuffle and shape batches inside the source "
                    "(silently ignoring them would change training data "
                    "order/shape with no error)")
            if sharding is not None and max_batches is None:
                raise ValueError(
                    "a custom batch_source with a global sharding requires "
                    "an explicit max_batches: source batch counts are data-"
                    "dependent per host, so without an agreed step count "
                    "pjit deadlocks the pod (agree via "
                    "jax_utils.sharding.agree_max_batches)")
        if batch_cache is not None and batch_source is not None:
            raise ValueError(
                "batch_cache is the local-reader decode bypass; the "
                "data service's workers own caching on the remote path "
                "(BatchWorker(batch_cache=...)) — arming both here "
                "would cache an opaque stream under a key that cannot "
                "see the remote plan")
        if cache_resume is not None:
            if batch_cache is None:
                raise ValueError(
                    "cache_resume is a batch_cache replay position; it "
                    "needs batch_cache armed (and the same cache "
                    "key ingredients the snapshot was taken under)")
            if cache_resume.get("kind") != "cache_replay":
                raise ValueError(
                    f"cache_resume must be a state_dict() of kind "
                    f"'cache_replay', got {cache_resume.get('kind')!r}")
            ventilator = getattr(reader, "_ventilator", None)
            if getattr(ventilator, "_randomize_item_order", False) \
                    and getattr(reader, "_shard_seed", None) is None:
                raise ValueError(
                    "cache_resume with a shuffle_row_groups reader "
                    "requires shard_seed: without one the fill order is "
                    "not reproducible, so a cold-cache resume would "
                    "refill the entry in a different canonical order and "
                    "then seek the resume position into the WRONG "
                    "sequence (silent duplicate and lost samples)")
        self.reader = reader
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._max_batches = max_batches
        self._device = device
        self._sharding = sharding
        self._host_prefetch = max(1, host_prefetch)
        self._device_prefetch = device_prefetch
        self._non_tensor_policy = non_tensor_policy
        self._stage_to_device = stage_to_device
        self._stage_in_producer = stage_in_producer and stage_to_device
        self._shuffle_buffer_size = shuffle_buffer_size
        self._shuffle_seed = shuffle_seed
        # Custom host-batch pipeline (e.g. sequence packing): a zero-arg
        # callable returning an iterator of {field: ndarray} batches. The
        # staging/prefetch/diagnostics machinery is reused unchanged; the
        # row-batching knobs (batch_size/last_batch/shuffle buffer) are the
        # source's concern, not this class's.
        self._batch_source = batch_source
        self._batch_cache = batch_cache
        self._device_stage = device_stage
        # Production ordinal of the next staged batch — the device stage's
        # augment seed. Monotonic across iterations (epoch 2 draws fresh
        # augments) and assigned in production order on whichever thread
        # stages, so the augment sequence is reproducible across runs and
        # invariant to device_prefetch depth / stage_in_producer placement.
        self._stage_step = 0
        # Cumulative H2D payload bytes this loader staged (raw bytes + ordinary
        # tensors); the per-iteration diagnostics view re-bases like the
        # registry-backed stages.
        self._h2d_bytes = 0
        # A cache fill is valid ONLY from the reader's start position —
        # i.e. the first pass this loader ever pulls from it. Set when
        # that pass begins and never cleared: any later cache miss
        # (abandoned fill, evicted entry, an entry that never fit the
        # memory budget) finds the reader mid-stream or exhausted, and
        # filling from there would commit a truncated/shifted/empty
        # sequence under the full-epoch key. Once set, misses stream
        # uncached (correct, just not accelerated).
        self._cache_fill_attempted = False
        # Shuffle-compatible replay: each iteration of a cache-armed
        # loader is one "cache epoch"; shuffled serves permute the
        # canonical entry by fold_in(seed, cache-epoch) so the order
        # changes per pass while the cached bytes don't. cache_resume
        # re-enters a permuted pass at a batch position.
        self._cache_epoch = 0
        self._cache_skip = 0
        self._cache_pass = None   # live pass info state_dict() snapshots
        self._cache_resume_seed = None
        self._cache_resume_has_seed = False
        if cache_resume is not None:
            self._cache_epoch = int(cache_resume["cache_epoch"])
            self._cache_skip = max(0, int(
                cache_resume.get("batches_yielded", 0)))
            # Checked against the effective permutation seed at serve
            # time: resuming under a different seed would skip a prefix
            # of the WRONG permutation (silent duplicate/lost samples).
            self._cache_resume_seed = cache_resume.get("shuffle_seed")
            self._cache_resume_has_seed = "shuffle_seed" in cache_resume
        if sharding is not None and max_batches is None \
                and batch_source is None:
            # (With a custom batch_source the reader-metadata derivation
            # below would count ROW batches, not source batches — the source
            # owns step agreement; see make_packed_jax_dataloader docs.)
            # SPMD lockstep: under a global sharding every host must dispatch
            # the same number of steps or pjit deadlocks the pod. Derive the
            # global-min batch count from the reader's shard metadata (each
            # host computes the same number locally — no collective).
            from petastorm_tpu.jax_utils.sharding import (
                derive_equal_step_max_batches,
            )

            derived = derive_equal_step_max_batches(reader, batch_size,
                                                    last_batch)
            if derived is not None:
                self._max_batches = derived

        self._queue = None
        self._host_queue = None
        self._producer = None
        self._stager = None
        self._producer_error = None
        self._source_iter = None   # batch_source() iterator for _produce
        self._direct_iter = None   # prefetched source consumed sans producer
        self._stop = threading.Event()
        self._total_rows_yielded = 0  # cumulative, pad-aware (resume support)
        self._yield_count_tracker = None  # tracker the count is relative to
        # Typed metrics behind the diagnostics dict: per-instance children
        # of the registry families (telemetry.metrics), labeled by a
        # process-unique loader id. The legacy per-iteration dict is
        # RE-DERIVED from these on every `diagnostics` read — current
        # child value minus the iteration-start baseline — so a
        # monitoring thread polling mid-epoch sees live numbers (wall_s
        # and input_stall_pct included) while a scraper sees the same
        # series monotonic.
        self._loader_id = _acquire_loader_id()
        self._m_batches = LOADER_BATCHES.labels(self._loader_id)
        self._m_rows = LOADER_ROWS.labels(self._loader_id)
        self._m_stage = {stage: LOADER_STAGE_SECONDS.labels(self._loader_id,
                                                            stage)
                         for stage in _STAGES}
        self._m_overlap = LOADER_DISPATCH_OVERLAP.labels(self._loader_id)
        import weakref

        self._metrics_finalizer = weakref.finalize(
            self, _release_loader_metrics, self._loader_id)
        # Cleanup matters for long-lived processes, not interpreter exit
        # (module globals may already be torn down there).
        self._metrics_finalizer.atexit = False
        self._trace_path = trace_path
        self._iter_start = None   # perf_counter at iteration start
        self._iter_end = None     # set when the iteration finishes
        self._source_diag = None  # batch_source diagnostics snapshot
        self._base = self._metric_baseline()
        # Online autotuner (docs/guides/pipeline.md): the stage graph and
        # controller are built lazily at the first __iter__ so they bind
        # the source/reader objects as iterated. The default (None) builds
        # nothing — static behavior is bit-for-bit today's.
        if autotune is None or autotune is False:
            self._autotune_config = None
        elif autotune is True:
            self._autotune_config = {}
        elif isinstance(autotune, dict):
            allowed = {"interval_s", "bounds", "hysteresis",
                       "placement_hysteresis", "tolerance", "probe_defer",
                       "classify_kwargs", "rewrite_hysteresis", "rewrites",
                       "rewrite_thresholds"}
            unknown = set(autotune) - allowed
            if unknown:
                # A misspelled key would otherwise silently fall back to
                # the default — the user believes they tuned something.
                raise ValueError(
                    f"unknown autotune config key(s) {sorted(unknown)}; "
                    f"allowed: {sorted(allowed)}")
            self._autotune_config = dict(autotune)
        else:
            raise ValueError(
                "autotune must be None, True, or a config dict "
                "(interval_s/bounds/hysteresis/placement_hysteresis/"
                "tolerance/probe_defer/classify_kwargs/"
                "rewrite_hysteresis/rewrites/rewrite_thresholds)")
        self.autotune = None  # the AutotuneController once armed

    # -- diagnostics (derived from the metrics registry) -------------------

    def _metric_baseline(self):
        """Current registry child values — subtracted on read so the
        diagnostics dict stays per-iteration while the registry series
        stay monotonic for scrapers."""
        return {
            "batches": self._m_batches.value,
            "rows": self._m_rows.value,
            "h2d_bytes": self._h2d_bytes,
            "stage": {stage: child.sum
                      for stage, child in self._m_stage.items()},
        }

    @property
    def diagnostics(self):
        """Per-iteration pipeline counters, derived live from the metrics
        registry (``docs/guides/diagnostics.md``): ``batches``/``rows``
        yielded, the per-stage time breakdown (``producer_decode_s``,
        ``producer_queue_wait_s``, ``device_dispatch_s`` with its
        device-stage components ``raw_stage_s``/``device_decode_s``/
        ``shard_put_s``, ``stall_s``, ``consumer_s``), the dispatch
        ledger's ``dispatch_overlap_pct`` and staged ``h2d_bytes``, and
        ``wall_s`` / ``input_stall_pct`` — the
        north-star metric — computed **at read time**, so a monitoring
        thread polling mid-epoch sees this epoch's live stall percentage,
        not the previous iteration's frozen one. ``source`` carries the
        batch_source's own diagnostics when one is plugged in."""
        now = time.perf_counter()
        start, end = self._iter_start, self._iter_end
        wall = 0.0 if start is None else max(0.0, (now if end is None
                                                   else end) - start)
        base = self._base
        stage = {name: max(0.0, child.sum - base["stage"][name])
                 for name, child in self._m_stage.items()}
        stall = stage["wait"]
        # Dispatch ledger: every device-dispatch stage (plain device_put,
        # raw-bytes staging, the fused on-device decode). The overlap gauge
        # reports how much of it rode inside the pipeline's OTHER work —
        # the producer's decode windows or the consumer's step window
        # (stage_in_producer dispatches inside the step wait) — instead of
        # extending the wall; 100 means dispatch is fully hidden. Crediting
        # only decode would misread the paced stage_in_producer regime as
        # 0% overlap while input_stall_pct ≈ 0 shows dispatch extended
        # nothing.
        dispatch = sum(stage[name] for name in _DISPATCH_STAGES)
        overlap_pct = (
            round(100.0 * max(0.0, min(1.0, (stage["decode"]
                                             + stage["consumer"] + dispatch
                                             - wall) / dispatch)), 2)
            if dispatch > 0 else 100.0)
        self._m_overlap.set(overlap_pct)
        out = {
            "batches": int(self._m_batches.value - base["batches"]),
            "rows": int(self._m_rows.value - base["rows"]),
            "stall_s": stall,
            "wall_s": wall,
            "input_stall_pct": (round(100.0 * stall / wall, 2)
                                if wall > 0 else 0.0),
            "max_batches": self._max_batches,
            # per-stage breakdown (stall root-causing):
            "producer_decode_s": stage["decode"],   # reader pull + collation
            "producer_queue_wait_s": stage["queue_wait"],
            "device_dispatch_s": dispatch,
            "raw_stage_s": stage["raw_stage"],
            "device_decode_s": stage["device_decode"],
            "shard_put_s": stage["shard_put"],
            "dispatch_overlap_pct": overlap_pct,
            # H2D payload bytes staged this iteration (raw uint8 bytes when
            # a device stage is armed — the uint8-vs-float32 ledger).
            "h2d_bytes": int(self._h2d_bytes - base["h2d_bytes"]),
            # Time the CONSUMER spends between taking a batch and asking
            # for the next (its step dispatch + device wait) — the other
            # side of the ledger from stall_s: wall ≈ stall_s + consumer_s
            # + loader bookkeeping. Lets a training loop reconcile "low
            # stall but below the step bound" by naming the consumer-side
            # residual instead of leaving it unattributed.
            "consumer_s": stage["consumer"],
        }
        if self._source_diag is not None:
            out["source"] = dict(self._source_diag)
        return out

    def exclude_stall_so_far(self):
        """Zero the per-iteration stall accounting up to this call — e.g.
        to exclude the pipeline-fill stall of the first batch, which every
        architecture pays once (``bench.py``'s realistic-step leg). The
        registry histogram keeps the full history; only the derived
        per-iteration view re-bases."""
        self._base["stage"]["wait"] = self._m_stage["wait"].sum

    def stage_quantiles(self, quantiles=(0.5, 0.99)):
        """Approximate per-batch latency quantiles for each pipeline stage,
        estimated from this loader's registry histograms (lifetime of the
        instance, not just the last iteration) — what the service
        scenario's ``--json-out`` telemetry block reports so BENCH
        artifacts capture distributions, not just means."""
        return {
            stage: {f"p{int(q * 100)}": child.quantile(q)
                    for q in quantiles}
            for stage, child in self._m_stage.items()
        }

    # -- runtime knobs (live-resizable: the autotuner's bindings) ----------

    @property
    def host_prefetch(self):
        """Bounded host-queue depth. Settable live: the bound applies to
        the running iteration's queue immediately (a producer blocked on
        the old, smaller bound is woken)."""
        return self._host_prefetch

    @host_prefetch.setter
    def host_prefetch(self, value):
        value = int(value)
        if value < 1:
            raise ValueError("host_prefetch must be >= 1")
        self._host_prefetch = value
        queue_ = (self._host_queue if self._stage_in_producer
                  else self._queue)
        if queue_ is not None:
            resize_bounded_queue(queue_, value)

    @property
    def device_prefetch(self):
        """In-flight device batches kept ahead. Settable live: the
        consumer's fill loop reads it per batch, so a raise deepens the
        window on the next fill and a shrink drains down naturally."""
        return self._device_prefetch

    @device_prefetch.setter
    def device_prefetch(self, value):
        value = int(value)
        if value < 1:
            raise ValueError("device_prefetch must be >= 1")
        self._device_prefetch = value
        if self._stage_in_producer and self._queue is not None:
            # In producer-staging mode the device queue's bound IS
            # device_prefetch (HBM budget) — resize it live too.
            resize_bounded_queue(self._queue, max(1, value))

    def _ensure_autotune(self):
        """Build (once) and start the autotune controller when armed."""
        if self._autotune_config is None:
            return
        if self.autotune is None:
            from petastorm_tpu.pipeline import (
                AutotuneController,
                Planner,
                build_loader_graph,
            )

            cfg = self._autotune_config
            graph = build_loader_graph(self, bounds=cfg.get("bounds"))
            planner = Planner(
                {name: knob.descriptor()
                 for name, knob in graph.knobs.items()},
                hysteresis=cfg.get("hysteresis", 2),
                placement_hysteresis=cfg.get("placement_hysteresis", 4),
                tolerance=cfg.get("tolerance", 0.05),
                probe_defer=cfg.get("probe_defer", 3),
                classify_kwargs=cfg.get("classify_kwargs"),
                # Graph rewrites (docs/guides/pipeline.md#graph-rewrites):
                # on by default — triggers gate them, so knob-only
                # workloads never probe one; rewrites=False pins the
                # PR 10 knob-only action space.
                rewrite_hysteresis=cfg.get("rewrite_hysteresis", 6),
                rewrites=cfg.get("rewrites", True),
                rewrite_thresholds=cfg.get("rewrite_thresholds"))
            self.autotune = AutotuneController(
                graph, interval_s=cfg.get("interval_s", 0.5),
                planner=planner)
        self.autotune.start()

    # -- producer ---------------------------------------------------------

    def _produce(self):
        try:
            if self._source_iter is not None:
                batches = iter(self._source_iter)
                if self._max_batches is not None:
                    import itertools

                    batches = itertools.islice(batches, self._max_batches)
            else:
                batches = iter(self._reader_batches())
            # With producer-side staging, decode feeds a separate staging
            # thread (see _stage_loop) so decode and H2D dispatch OVERLAP —
            # both release the GIL (pyarrow/cv2; transport writes), so even
            # a single-core host pipelines them instead of paying their sum.
            target = (self._host_queue if self._stage_in_producer
                      else self._queue)
            while True:
                t0 = time.perf_counter()
                with _trace_span("petastorm_tpu.loader.decode"):
                    batch = next(batches, _SENTINEL)
                t1 = time.perf_counter()
                self._m_stage["decode"].observe(t1 - t0)
                if batch is _SENTINEL:
                    break
                if tracing.COLLECTOR.enabled:
                    tracing.COLLECTOR.record_span("loader.decode", t0, t1)
                t0 = time.perf_counter()
                while not self._stop.is_set():
                    try:
                        target.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                # Drop the producer's reference the moment the queue owns
                # the batch: while the producer blocks on a full queue for
                # the NEXT batch, it must not pin a consumed one alive.
                batch = None
                self._m_stage["queue_wait"].observe(
                    time.perf_counter() - t0)
                if self._stop.is_set():
                    return
        except Exception as exc:  # surfaced on the consumer side
            self._producer_error = exc
        finally:
            target = (self._host_queue if self._stage_in_producer
                      else self._queue)
            self._put_sentinel(target)

    def _reader_batches(self):
        """The producer's batch stream off the local reader, with the
        decoded-batch cache in front when one is armed: a hit serves the
        whole epoch's collated sequence out of the cache (the reader is
        never pulled — re-iterating the loader replays the epoch even
        though the exhausted ``num_epochs=1`` reader would yield nothing);
        a miss streams batches through while writing them into an entry
        that is published only on clean exhaustion (an abandoned iteration
        can never be served as a complete epoch).

        Shuffle-compatible serving: when shuffling is requested (a
        shuffle buffer, an explicit ``shuffle_seed``, or a
        ``shuffle_row_groups`` reader), the entry stays canonical (the
        fill pass's decode order, read WITHOUT the shuffle buffer) and
        each pass serves it through a fresh seed-tree permutation at
        batch granularity — order changes per epoch, bytes don't, and
        the cache key is seed/epoch-invariant
        (``docs/guides/caching.md#shuffle-compatible-serving``). The
        shuffled fill pass buffers the epoch before serving (the entry
        IS the buffer), so its first batch arrives after the decode
        completes; warm passes stream immediately."""
        if self._batch_cache is None:
            yield from batch_iterator(
                self.reader, self._batch_size,
                last_batch=self._last_batch,
                max_batches=self._max_batches,
                shuffle_buffer_size=self._shuffle_buffer_size,
                shuffle_seed=self._shuffle_seed)
            return
        key = self._reader_cache_key()
        permute_seed = self._cache_permute_seed()
        if self._cache_resume_has_seed \
                and self._cache_resume_seed != permute_seed:
            raise ValueError(
                f"cache_resume was snapshotted under shuffle_seed="
                f"{self._cache_resume_seed!r} but this loader's effective "
                f"permutation seed is {permute_seed!r}: the resume "
                f"position indexes that seed's permutation, so resuming "
                f"here would silently re-serve some batches and skip "
                f"others — reconstruct the loader (and reader) with the "
                f"snapshot's shuffle configuration")
        cache_epoch = self._cache_epoch
        self._cache_epoch += 1
        skip, self._cache_skip = self._cache_skip, 0
        if permute_seed is not None:
            # Snapshot the pass BEFORE any yield: a state_dict() taken
            # mid-fill resumes at `skip` (nothing yielded yet). ``n`` is
            # filled in once the entry exists — state_dict uses it to
            # roll a COMPLETED pass forward to the next pass's start.
            self._cache_pass = {"cache_epoch": cache_epoch, "base": skip,
                                "seed": permute_seed, "n": None}
        entry, tier = self._batch_cache.get_tiered(key)
        if entry is not None:
            yield from self._serve_entry(entry, tier, permute_seed,
                                         cache_epoch, skip)
            return
        if self._cache_fill_attempted:
            # The reader's start position was already consumed (by a
            # complete OR abandoned earlier pass): what it yields now is a
            # tail of the stream, not an epoch — serve it uncached and
            # never commit it under the epoch key. Not a permuted cache
            # pass either: a state_dict() here has no replayable position.
            self._cache_pass = None
            produced = 0
            for batch in batch_iterator(self.reader, self._batch_size,
                                        last_batch=self._last_batch,
                                        max_batches=self._max_batches):
                produced += 1
                yield batch
            if produced == 0:
                # Miss over an exhausted reader: the epoch WAS cached once
                # (this loader filled it) but no tier holds it now — e.g.
                # a sibling loader's fill LRU-evicted it. The "replay"
                # is an empty epoch; say so instead of letting a
                # range(N)-epoch training loop end early in silence.
                import warnings

                warnings.warn(
                    "batch_cache miss over an exhausted reader: the "
                    "previously cached epoch entry is no longer retained "
                    "(evicted by other fills?), so this iteration yields "
                    "no batches — raise the cache budgets or enable the "
                    "disk tier", RuntimeWarning, stacklevel=2)
            return
        self._cache_fill_attempted = True
        builder = self._batch_cache.begin_fill(key)
        if permute_seed is not None:
            # Shuffled fill: buffer the canonical epoch into the entry
            # (no yields — the builder already holds every frame), then
            # serve it through this pass's permutation so epoch 1 is
            # shuffled too. The fill reads WITHOUT the shuffle buffer:
            # the entry must be canonical or two jobs with different
            # seeds could not share it.
            for batch in batch_iterator(self.reader, self._batch_size,
                                        last_batch=self._last_batch,
                                        max_batches=self._max_batches):
                if self._stop.is_set():
                    return  # abandoned fill: the builder never commits
                builder.add_batch(batch)
            entry = builder.commit()
            if not self._batch_cache.retained(key):
                import warnings

                warnings.warn(
                    "batch_cache could not retain this epoch's entry "
                    "(larger than the memory budget and no disk tier kept "
                    "it); re-iterating this exhausted reader will yield "
                    "no batches — raise mem_budget_bytes or enable the "
                    "disk tier", RuntimeWarning, stacklevel=2)
            yield from self._serve_entry(entry, None, permute_seed,
                                         cache_epoch, skip)
            return
        for batch in batch_iterator(self.reader, self._batch_size,
                                    last_batch=self._last_batch,
                                    max_batches=self._max_batches):
            builder.add_batch(batch)
            yield batch
        builder.commit()
        if not self._batch_cache.retained(key):
            # Committed but kept by no tier (the epoch outgrew every
            # budget): the replay contract cannot be honored — the next
            # iteration finds a miss over an exhausted reader and yields
            # an EMPTY epoch. Say so now, while the user can still raise
            # the budget, instead of ending training N-1 epochs early in
            # silence.
            import warnings

            warnings.warn(
                "batch_cache could not retain this epoch's entry (larger "
                "than the memory budget and no disk tier kept it); "
                "re-iterating this exhausted reader will yield no batches "
                "— raise mem_budget_bytes or enable the disk tier",
                RuntimeWarning, stacklevel=2)

    def _cache_permute_seed(self):
        """The serve-time permutation seed, or ``None`` when replays must
        be byte-exact (no shuffling requested — the pre-shuffle replay
        contract). Shuffling is requested by any of the loader's shuffle
        knobs or a ``shuffle_row_groups`` reader; the seed prefers the
        explicit ``shuffle_seed``, then the reader's ``shard_seed``, then
        0 (a fixed default — the determinism lint bans unseeded draws)."""
        ventilator = getattr(self.reader, "_ventilator", None)
        reader_shuffled = bool(getattr(ventilator, "_randomize_item_order",
                                       False))
        if not (self._shuffle_buffer_size or self._shuffle_seed is not None
                or reader_shuffled):
            return None
        if self._shuffle_seed is not None:
            return int(self._shuffle_seed)
        shard_seed = getattr(self.reader, "_shard_seed", None)
        return int(shard_seed) if shard_seed is not None else 0

    def _serve_entry(self, entry, tier, permute_seed, cache_epoch, skip):
        """Serve a whole-epoch cache entry, permuted when shuffling is
        requested: position ``i`` of the pass is the entry's
        ``order[i]``-th canonical batch, where ``order`` derives only
        from ``fold_in(seed, cache-epoch)`` — each pass reshuffles, every
        process replays the same orders, and ``skip`` (a resume position)
        indexes the PERMUTED stream so a restore continues mid-pass
        bit-exactly."""
        from petastorm_tpu.service.seedtree import fold_in, permutation

        if permute_seed is None:
            order = range(entry.num_batches)
        else:
            order = permutation(
                fold_in(int(permute_seed), ("cache-epoch", cache_epoch)),
                entry.num_batches)
            self._batch_cache.note_permuted_serve(tier or "mem")
            if self._cache_pass is not None:
                self._cache_pass["n"] = entry.num_batches
        for position, source in enumerate(order):
            if position < skip:
                continue
            yield entry.batch_at(source).to_dict()

    def _reader_cache_key(self):
        """Content fingerprint of everything that shapes this loader's
        batch sequence: the reader's resolved piece plan (path + row-group
        identity, so a re-materialized dataset misses), its schema view,
        transform, predicate, pass count and resume position, plus this
        loader's batching knobs. Deliberately EXCLUDES every shuffle
        ingredient (seed, flags, buffer size) — order is composed at
        serve time from the seed tree, so one canonical fill serves any
        seed and every epoch (``batch_fingerprint`` enforces the
        exclusion). Under ``shuffle_row_groups`` the canonical order is
        the fill pass's decode order: set ``shard_seed`` for a
        reproducible fill, or construct the reader unshuffled and let
        serve-time permutation do the shuffling."""
        from petastorm_tpu.cache_impl import batch_fingerprint

        reader = self.reader
        pieces = [(piece.path, piece.row_group)
                  for piece in getattr(reader, "_pieces", [])]
        return batch_fingerprint(
            reader._dataset_path_signature(), pieces, self._batch_size,
            fields=sorted(reader.schema.fields),
            transform=getattr(reader, "_transform_spec", None),
            factory=type(reader).__name__ + "/"
            + type(reader._results_queue_reader).__name__,
            extra={"last_batch": self._last_batch,
                   "max_batches": self._max_batches,
                   # num_epochs is CONTENT-shaping (how many passes of
                   # batches one entry holds), not serve order — it stays
                   # in the key, and keeping the PR 5 spelling means old
                   # disk entries are found and version-evicted instead
                   # of lingering as orphaned files.
                   "num_epochs": reader.num_epochs,
                   "predicate": repr(getattr(reader, "_predicate", None)),
                   "resume": repr(getattr(reader, "_resume_state", None))})

    def _stage_loop(self):
        """Staging thread (producer-side staging only): host batches →
        ``device_put`` dispatch → the device queue. Runs concurrently with
        the decode thread, so per-batch pipeline cost is
        max(decode, dispatch), not their sum."""
        try:
            while not self._stop.is_set():
                try:
                    batch = self._host_queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                if batch is _SENTINEL:
                    break
                t0 = time.perf_counter()
                with _trace_span("petastorm_tpu.loader.device_put"):
                    # _stage observes the dispatch-stage histograms itself
                    # (device_put / raw_stage / device_decode).
                    batch = self._stage(batch)
                t1 = time.perf_counter()
                if tracing.COLLECTOR.enabled:
                    tracing.COLLECTOR.record_span("loader.device_put",
                                                  t0, t1)
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                # The batch is DEVICE-resident here: a lingering reference
                # while this thread blocks on the bounded device queue
                # would hold one extra batch of HBM beyond the
                # device_prefetch budget.
                batch = None
        except Exception as exc:  # surfaced on the consumer side
            self._producer_error = exc
        finally:
            self._put_sentinel(self._queue)

    def _put_sentinel(self, q):
        # The sentinel MUST land or the downstream blocks forever; retry in
        # a stop-checking loop (the consumer may legitimately pause far
        # longer than any fixed timeout — e.g. first-step XLA compile).
        while True:
            try:
                q.put(_SENTINEL, timeout=0.1)
                break
            except queue.Full:
                if self._stop.is_set():
                    break

    # -- consumer ---------------------------------------------------------

    def __iter__(self):
        # A previous iteration's threads may still be running (producer
        # pulling the non-thread-safe reader, stager mid-device_put); BOTH
        # must be stopped and joined before the queues are reassigned — a
        # surviving old thread would inject stale batches and a premature
        # sentinel into the new iteration's queues. Each is checked
        # independently: the producer can exit quickly while the stager is
        # still inside a long dispatch.
        stale = [("producer", self._producer), ("stager", self._stager)]
        if any(t is not None and t.is_alive() for _, t in stale):
            self.stop()
            for name, t in stale:
                if t is None:
                    continue
                t.join(timeout=30)
                if t.is_alive():
                    raise RuntimeError(
                        f"Previous iteration's {name} thread did not stop "
                        "within 30s (blocked on reader I/O or a device "
                        "call?); cannot safely re-iterate")
        # A previous DIRECT iteration has no loader threads, but its source
        # iterator may still own live reader threads (the service drain) —
        # close it before a new iteration resets the source's bookkeeping
        # under them. Also keeps an abandoned first iteration's later
        # finalization from touching the new iteration's source.
        if self._source_iter is not None:
            close = getattr(self._source_iter, "close", None)
            if callable(close):
                close()
        # With producer-side staging the device queue holds DEVICE-resident
        # batches, so its depth is bounded by the device budget
        # (device_prefetch), not the host budget — otherwise device-resident
        # batches grow to host_prefetch + device_prefetch and can OOM a
        # model that fit with consumer-side staging. Total in-flight device
        # batches stay <= 2 * device_prefetch (+1 in the stager's hand);
        # decoded host batches additionally buffer up to host_prefetch
        # between the decode and staging threads (the overlap window).
        # A batch_source whose iterator declares itself ``prefetched`` (the
        # data service's multiplexed drain: its own reader threads feeding a
        # bounded ready-queue) is consumed DIRECTLY on the iterating thread:
        # the producer thread would be pure plumbing between two bounded
        # queues — one extra thread wakeup per batch on the hot path, with
        # no extra buffering to show for it. Prefetch depth and
        # backpressure stay the source's (ready-queue + credit window).
        self._source_iter = None
        self._direct_iter = None
        direct = False
        if self._batch_source is not None:
            self._source_iter = self._batch_source()
            direct = (not self._stage_in_producer
                      and getattr(self._source_iter, "prefetched", False))
        if direct:
            batches = iter(self._source_iter)
            if self._max_batches is not None:
                import itertools

                batches = itertools.islice(batches, self._max_batches)
            self._direct_iter = batches
            self._queue = None
            self._host_queue = None
        else:
            maxsize = (max(1, self._device_prefetch)
                       if self._stage_in_producer else self._host_prefetch)
            self._queue = queue.Queue(maxsize=maxsize)
            self._host_queue = (queue.Queue(maxsize=self._host_prefetch)
                                if self._stage_in_producer else None)
        self._stop.clear()
        self._producer_error = None
        # Yielded-row accounting is relative to the reader's delivery
        # tracker; reader.reset() installs a fresh tracker (counts restart
        # at zero), so the yielded counter must restart with it.
        tracker = getattr(self.reader, "_delivery_tracker", None)
        if tracker is not self._yield_count_tracker:
            self._yield_count_tracker = tracker
            self._total_rows_yielded = 0
        # Diagnostics are per-iteration: stall/wall must describe one pass or
        # input_stall_pct (the north-star metric) is meaningless. The
        # registry series are monotonic; the per-iteration view re-bases on
        # this baseline.
        self._base = self._metric_baseline()
        self._iter_start = time.perf_counter()
        self._iter_end = None
        if self._trace_path is not None:
            # Scoped arming: the first armer clears the buffer (each
            # iteration exports a fresh trace — without the clear, epoch
            # N's file would replay epochs 1..N-1 and the bounded buffer
            # would eventually freeze on the earliest spans); a second
            # trace-armed loader (mid-epoch eval) joins the running trace
            # instead of wiping it.
            tracing.COLLECTOR.acquire()
        if self._direct_iter is None:
            self._producer = threading.Thread(target=self._produce,
                                              daemon=True,
                                              name="jax-loader-producer")
            self._producer.start()
            if self._stage_in_producer:
                self._stager = threading.Thread(target=self._stage_loop,
                                                daemon=True,
                                                name="jax-loader-stager")
                self._stager.start()
        else:
            self._producer = None
            self._stager = None
        self._ensure_autotune()
        return self._iterate()

    def _iterate(self):
        inflight = []       # device batches dispatched ahead (double buffer)
        inflight_bids = []  # their trace batch ids (direct source path)
        done = False
        direct = self._direct_iter
        collector = tracing.COLLECTOR
        # Captured so the finally tears down THIS iteration's source even
        # if a newer iteration has since replaced the attribute.
        source_iter = self._source_iter
        # Seed the source's delivery/recovery counters at iteration START
        # (the finally refreshes them at the end): a consumer polling
        # diagnostics mid-epoch — a stall dashboard, the chaos harness —
        # must see the "source" stage without waiting for the pass to end.
        self._snapshot_source_diagnostics()
        self._iter_start = time.perf_counter()
        try:
            while True:
                # Keep device_prefetch batches in flight.
                while not done and len(inflight) < self._device_prefetch:
                    t0 = time.perf_counter()
                    with _trace_span("petastorm_tpu.loader.wait"):
                        # Direct path: pull the prefetched source here
                        # (its reader threads are the producers); an error
                        # raises inline — no sentinel relay needed.
                        host_batch = (next(direct, _SENTINEL)
                                      if direct is not None
                                      else self._queue.get())
                    t1 = time.perf_counter()
                    self._m_stage["wait"].observe(t1 - t0)
                    if host_batch is _SENTINEL:
                        done = True
                        if self._producer_error is not None:
                            raise self._producer_error
                        break
                    # Direct-source batches carry the worker-minted batch
                    # id (the source sets last_bid as it yields, on this
                    # same thread) — the key that joins loader spans to
                    # the batch's worker/client lifecycle in a trace.
                    bid = (getattr(self._batch_source, "last_bid", None)
                           if direct is not None else None)
                    if collector.enabled:
                        collector.record_span("loader.wait", t0, t1,
                                              bid=bid)
                    if self._stage_in_producer:
                        inflight.append(host_batch)  # already on device
                    else:
                        t0 = time.perf_counter()
                        with _trace_span("petastorm_tpu.loader.device_put"):
                            # _stage observes the dispatch-stage histograms
                            # itself (device_put/raw_stage/device_decode).
                            inflight.append(self._stage(host_batch))
                        t1 = time.perf_counter()
                        if collector.enabled:
                            collector.record_span("loader.device_put",
                                                  t0, t1, bid=bid)
                    # Release the host copy now that the device owns one:
                    # keeping it across further fill iterations would pin
                    # up to device_prefetch extra host batches.
                    host_batch = None
                    inflight_bids.append(bid)
                if not inflight:
                    return
                batch = inflight.pop(0)
                bid = inflight_bids.pop(0) if inflight_bids else None
                self._m_batches.inc()
                rows_in_batch = self._batch_rows(batch)
                self._m_rows.inc(rows_in_batch)
                if PAD_MASK_KEY in batch:
                    # Count only real rows toward resume accounting (the
                    # device pull happens at most once, on the padded final
                    # batch of a stream).
                    rows_in_batch = int(np.asarray(
                        batch[PAD_MASK_KEY]).sum())
                self._total_rows_yielded += rows_in_batch
                t_yield = time.perf_counter()
                yield batch
                t_back = time.perf_counter()
                # Drop the loader's reference to the consumed batch BEFORE
                # dispatching the next fill: if the consumer's step donated
                # (or discarded) these buffers, a lingering reference here
                # would pin one extra batch of HBM per deep-prefetch slot.
                batch = None
                self._m_stage["consumer"].observe(t_back - t_yield)
                if collector.enabled:
                    collector.record_span("loader.consumer", t_yield,
                                          t_back, bid=bid)
        finally:
            self._iter_end = time.perf_counter()
            # A batch_source with its own delivery counters (e.g. the data
            # service's per-worker stall / ready-queue / credit numbers)
            # lands in the stage breakdown, so one diagnostics dict
            # root-causes a stall across the whole delivery path.
            self._snapshot_source_diagnostics()
            # Reading diagnostics refreshes the dispatch-overlap gauge, so
            # a scrape-only consumer (metrics server armed, dict never
            # read) still sees the iteration's final overlap, not the
            # gauge's 0.0 birth value.
            self.diagnostics
            if self._trace_path is not None:
                collector.export(self._trace_path)
                # Balance the __iter__ acquire: collection stops when the
                # LAST trace-armed consumer finishes, not when the first
                # one does.
                collector.release()
            # Generator abandoned (break) or exhausted: stop the producer so
            # it doesn't keep decoding the rest of the dataset forever. On
            # the direct path, closing the source iterator is what tears
            # down its reader threads and sockets (a no-op if a newer
            # iteration's __iter__ already closed it).
            if direct is not None and source_iter is not None:
                close = getattr(source_iter, "close", None)
                if callable(close):
                    close()
            self.stop()

    def _snapshot_source_diagnostics(self):
        """Copy the batch_source's diagnostics dict (if it has one) into
        the ``diagnostics["source"]`` stage slot."""
        source_diag = (getattr(self._batch_source, "diagnostics", None)
                       if self._batch_source is not None else None)
        if isinstance(source_diag, dict):
            self._source_diag = dict(source_diag)

    @staticmethod
    def _batch_rows(batch):
        for name, col in batch.items():
            if name == PAD_MASK_KEY:
                continue
            try:
                return int(np.asarray(col.shape[0]).item()) \
                    if hasattr(col, "shape") else len(col)
            except TypeError:
                continue
        return 0

    def _stage(self, host_batch):
        """Numpy batch dict → device (or pass through when staging is off).

        With a :class:`DeviceStage` armed the image fields take the raw
        path instead: stage the uint8 BYTES (timed as ``raw_stage``; 4x
        fewer H2D bytes than float32 pixels), then dispatch the fused
        on-device decode/augment kernel (timed as ``device_decode``) which
        the stage donates its raw input to — the loader drops its own raw
        references immediately, so in-flight HBM is the decoded outputs
        plus at most one raw batch.
        """
        if not self._stage_to_device:
            return host_batch
        import jax

        from petastorm_tpu.jax_utils.sharding import (
            local_data_to_global_array,
        )

        raw = {}
        if self._device_stage is not None:
            raw, host_batch = self._device_stage.split(host_batch)
        out, tensors = {}, {}
        # All dispatch timing lives HERE (not in the callers): the
        # ``device_put`` stage is the plain-tensor put time only, so the
        # dispatch ledger (device_put + raw_stage + device_decode) never
        # double-counts.
        put_s = 0.0
        for name, col in host_batch.items():
            arr = np.asarray(col)
            if arr.dtype == object or arr.dtype.kind in ("U", "S", "M", "m"):
                if self._non_tensor_policy == "error":
                    raise TypeError(
                        f"Column {name!r} has non-tensor dtype {arr.dtype}; "
                        f"set non_tensor_policy='host' or 'drop', select "
                        f"numeric schema_fields, or add a TransformSpec")
                if self._non_tensor_policy == "drop":
                    continue
                out[name] = arr  # host-side passthrough
                continue
            if self._sharding is not None:
                self._h2d_bytes += arr.nbytes
                t0 = time.perf_counter()
                out[name] = local_data_to_global_array(
                    self._sharding, arr,
                    observe_shard_put=self._m_stage["shard_put"].observe)
                put_s += time.perf_counter() - t0
            else:
                tensors[name] = arr
        if tensors:
            # One device_put for the whole batch pytree: one dispatch, and the
            # runtime can batch the transfers.
            device = self._device or jax.local_devices()[0]
            self._h2d_bytes += sum(a.nbytes for a in tensors.values())
            t0 = time.perf_counter()
            out.update(jax.device_put(tensors, device))
            put_s += time.perf_counter() - t0
        self._m_stage["device_put"].observe(put_s)
        if raw:
            step = self._stage_step
            self._stage_step += 1
            observe_shard = self._m_stage["shard_put"].observe
            t0 = time.perf_counter()
            with _trace_span("petastorm_tpu.loader.raw_stage"):
                if self._sharding is not None:
                    raw_dev = {
                        name: local_data_to_global_array(
                            self._sharding, arr,
                            observe_shard_put=observe_shard)
                        for name, arr in raw.items()}
                else:
                    device = self._device or jax.local_devices()[0]
                    raw_dev = jax.device_put(raw, device)
            self._m_stage["raw_stage"].observe(time.perf_counter() - t0)
            raw_bytes = sum(a.nbytes for a in raw.values())
            self._h2d_bytes += raw_bytes
            self._device_stage.h2d_bytes += raw_bytes
            raw = None  # the kernel owns (and may donate) the raw buffers
            t0 = time.perf_counter()
            with _trace_span("petastorm_tpu.loader.device_decode"):
                out.update(self._device_stage.apply(raw_dev, step))
            raw_dev = None  # donated to the kernel — drop ours immediately
            self._m_stage["device_decode"].observe(time.perf_counter() - t0)
        return out

    # -- checkpoint / resume ----------------------------------------------

    def state_dict(self):
        """Input-pipeline checkpoint aligned to what this loader has YIELDED.

        The producer thread pulls rows from the reader ahead of the training
        loop (host queue + device prefetch + shuffle buffer), so the reader's
        own ``state_dict()`` would over-count by whatever is buffered. This
        method subtracts the buffered rows (recorded-by-reader minus
        yielded-by-loader) so buffered rows are re-read on resume
        (at-least-once). Call it between steps from the training thread, then
        pass the result as ``resume_state=`` to the reader factory feeding a
        fresh loader.
        """
        if self._batch_source is not None:
            # A source that knows how to checkpoint itself (e.g. the data
            # service's ServiceBatchSource tracks completed splits) owns the
            # snapshot: delegate. Sources accepting ``yielded_batches`` get
            # this loader's yielded-batch count so batches still buffered in
            # the prefetch queues stay un-checkpointed and are re-delivered
            # on resume (at-least-once, the same contract as the reader
            # path's buffered-row re-read).
            source_state = getattr(self._batch_source, "state_dict", None)
            if callable(source_state):
                import inspect

                try:
                    params = inspect.signature(source_state).parameters
                except (TypeError, ValueError):  # builtins, C callables
                    params = {}
                accepts_yielded = "yielded_batches" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
                if accepts_yielded:
                    return source_state(yielded_batches=int(
                        self._m_batches.value - self._base["batches"]))
                return source_state()
            raise ValueError(
                "state_dict is not supported with a custom batch_source "
                "that has no state_dict() of its own (e.g. the packed "
                "loader): yielded-row accounting cannot attribute repacked "
                "batches to reader deliveries. Checkpoint at an epoch "
                "boundary with the reader's state_dict(), or give the "
                "source a state_dict()")
        if self._batch_cache is not None and self._cache_pass is not None:
            # A shuffled cache pass (fill or replay): the resumable
            # position is a batch index into the pass's PERMUTED stream —
            # yielded batches only, so anything still in the prefetch
            # queues is re-served on resume (and nothing twice: the
            # resume skips exactly the yielded prefix of the same
            # deterministic permutation). Pass the dict back as
            # ``JaxDataLoader(cache_resume=...)`` with the same reader
            # construction and cache; a cold cache on resume re-fills
            # canonically and then seeks, so the restore works from a
            # fresh process too.
            pass_info = self._cache_pass
            yielded = pass_info["base"] + int(
                self._m_batches.value - self._base["batches"])
            cache_epoch = pass_info["cache_epoch"]
            n = pass_info.get("n")
            if n is not None and yielded >= n:
                # The pass is fully consumed: snapshot the NEXT pass's
                # start, not position n of this one — resuming "at the
                # end of pass k" must serve pass k+1, not an empty (or,
                # cold, a re-decoded-for-nothing) remainder of pass k.
                cache_epoch, yielded = cache_epoch + 1, 0
            return {
                "version": 1,
                "kind": "cache_replay",
                "cache_epoch": cache_epoch,
                "batches_yielded": yielded,
                "shuffle_seed": pass_info["seed"],
            }
        tracker = getattr(self.reader, "_delivery_tracker", None)
        if tracker is None or not hasattr(self.reader, "state_dict"):
            raise TypeError(
                "state_dict requires a petastorm_tpu Reader (got "
                f"{type(self.reader).__name__})")
        if self._shuffle_buffer_size:
            raise ValueError(
                "state_dict is not supported with shuffle_buffer_size > 0: "
                "the shuffle buffer reorders rows, so buffered rows cannot "
                "be attributed to recent deliveries (an old row may still "
                "be held while newer row groups drained). Shuffle with "
                "shuffle_row_groups/shard_seed instead, or checkpoint at "
                "an epoch boundary with the reader's state_dict()")
        return self.reader.state_dict(yielded_rows=self._total_rows_yielded)

    # -- lifecycle --------------------------------------------------------

    def stop(self):
        """Teardown-only: signals the threads and DISCARDS one queued batch
        per queue to unblock a producer/stager waiting on a full queue.
        Never call it to pause a stream you intend to keep consuming — the
        discarded batches are gone (resume accounting stays correct: the
        at-least-once contract re-reads buffered-but-unyielded rows)."""
        self._stop.set()
        if self.autotune is not None:
            self.autotune.stop()
        for q in (self._queue, self._host_queue):
            if q is not None:
                try:  # unblock a producer/stager waiting on a full queue
                    q.get_nowait()
                except queue.Empty:
                    pass

    def join(self):
        if self._producer is not None:
            self._producer.join(timeout=30)
        if self._stager is not None:
            self._stager.join(timeout=30)
        if self.autotune is not None:
            self.autotune.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()
        # reader is None when a custom batch_source owns the pipeline (e.g.
        # the data service's ServiceBatchSource — no local reader exists).
        if self.reader is not None:
            self.reader.stop()
            self.reader.join()
