"""Reader core: the parallel, shuffling, shardable Parquet row-group reader.

Reference parity: ``petastorm/reader.py`` + the two worker modules —
SURVEY.md §2.1, call stacks §3.1/3.2.
"""

from petastorm_tpu.reader.reader import (  # noqa: F401
    Reader,
    make_batch_reader,
    make_reader,
)
