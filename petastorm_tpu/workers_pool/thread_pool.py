"""Thread-based worker pool.

Reference parity: ``petastorm/workers_pool/thread_pool.py::ThreadPool``.
The default pool: pyarrow Parquet decode and cv2 release the GIL, so threads
give real parallelism for the hot loops (SURVEY.md §2.2).
"""

from __future__ import annotations

import queue
import sys
import threading
import time
import traceback

from petastorm_tpu.workers_pool import (
    DEFAULT_TIMEOUT_S,
    EmptyResultError,
    TimeoutWaitingForResultError,
    VentilatedItemProcessedMessage,
)
from petastorm_tpu.telemetry.metrics import (
    POOL_ITEMS_PROCESSED,
    POOL_ITEMS_VENTILATED,
    POOL_RESULTS_QUEUE_DEPTH,
)
from petastorm_tpu.workers_pool.worker_base import EOFSentinel


class WorkerException(Exception):
    """Wraps an exception raised inside a worker, carrying its traceback."""

    def __init__(self, exc, formatted_traceback):
        self.exc = exc
        self.formatted_traceback = formatted_traceback
        super().__init__(f"Worker raised {exc!r}\n{formatted_traceback}")


class _RetireSentinel:
    """``resize()`` shrink marker: exactly one worker thread exits on it
    (unlike ``EOFSentinel`` it is minted per-retirement, never broadcast)."""


class ThreadPool:
    #: This pool can attribute completion markers to their work item (the
    #: marker is created in-process with the item's kwargs in hand) — the
    #: capability the streaming piece engine requires.
    supports_item_done_hook = True

    def __init__(self, workers_count, results_queue_size=50, profiling_enabled=False):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._ventilator_queue = queue.Queue()
        self._threads = []
        self._workers = []
        self._ventilator = None
        self._stop_event = threading.Event()
        # resize() support: start() records how workers are built so grow
        # can spawn identical ones, and a monotonic id keeps thread names
        # unique across grow/shrink cycles.
        self._worker_class = None
        self._worker_setup_args = None
        self._next_worker_id = workers_count
        self._resize_lock = threading.Lock()
        self._ventilated_items = 0
        self._completed_items = 0
        self._results_pending = 0  # real RESULT payloads in the queue
        self._counter_lock = threading.Lock()
        #: Optional ``hook(item_kwargs)`` invoked on the consumer thread as
        #: :meth:`get_results` drains an item's completion marker — i.e.
        #: strictly AFTER every payload that item published was returned
        #: (payloads and marker ride the same FIFO queue).
        self.item_done_hook = None
        #: Optional ``fn(payload) -> payload`` applied to every published
        #: :class:`PiecePayload` ON THE WORKER THREAD, before it enters the
        #: results queue — how the stage-fusion rewrite moves collation/
        #: transform/serialization into the pool task
        #: (``Reader.set_publish_transform``).
        self.publish_transform = None

    @property
    def workers_count(self):
        return self._workers_count

    @property
    def diagnostics(self):
        """Live pool counters (reference ``Reader.diagnostics`` parity:
        ventilated/processed items and results-queue depth — SURVEY.md §5)."""
        with self._counter_lock:
            ventilated, completed = self._ventilated_items, self._completed_items
            pending = self._results_pending
        return {
            "items_ventilated": ventilated,
            "items_processed": completed,
            "items_in_flight": ventilated - completed,
            "results_queue_size": pending,
            "workers_count": self._workers_count,
        }

    def _publish_result(self, item):
        # Worker-facing publish: counts real payloads so results_qsize /
        # diagnostics report result depth, not bookkeeping-message depth
        # (the raw queue also carries DONE markers and exceptions).
        transform = self.publish_transform
        if transform is not None:
            from petastorm_tpu.reader_impl.delivery_tracker import (
                apply_publish_transform,
            )

            # Runs on the pool worker thread — that is the point: the
            # fused task pays collate/transform/serialize here, in
            # parallel across workers, instead of on the single
            # stream-serving thread.
            item = apply_publish_transform(transform, item)
        with self._counter_lock:
            self._results_pending += 1
        POOL_RESULTS_QUEUE_DEPTH.inc()
        self._results_queue.put(item)

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._threads:
            raise RuntimeError("ThreadPool already started")
        self._worker_class = worker_class
        self._worker_setup_args = worker_setup_args
        self._next_worker_id = self._workers_count
        for worker_id in range(self._workers_count):
            self._spawn_worker(worker_id)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _spawn_worker(self, worker_id):
        worker = self._worker_class(worker_id, self._publish_result,
                                    self._worker_setup_args)
        self._workers.append(worker)
        thread = threading.Thread(
            target=self._worker_loop, args=(worker,), daemon=True,
            name=f"petastorm-tpu-worker-{worker_id}",
        )
        self._threads.append(thread)
        thread.start()

    def resize(self, workers_count):
        """Live-resize the decode parallelism (the autotuner's
        ``workers_count`` knob — ``docs/guides/pipeline.md``).

        Grow spawns additional worker threads identical to the ones
        ``start()`` built; shrink enqueues one retire sentinel per
        surplus worker — each is honored by exactly one worker AFTER the
        work items already queued ahead of it (FIFO), so no ventilated
        item is dropped and in-flight accounting stays exact. Before
        ``start()`` this just adjusts the constructed count.
        """
        workers_count = int(workers_count)
        if workers_count < 1:
            raise ValueError("workers_count must be >= 1")
        with self._resize_lock:
            if self._stop_event.is_set():
                return
            if not self._threads:
                self._workers_count = workers_count  # pre-start resize
                return
            delta = workers_count - self._workers_count
            if delta > 0:
                for _ in range(delta):
                    self._spawn_worker(self._next_worker_id)
                    self._next_worker_id += 1
            else:
                for _ in range(-delta):
                    self._ventilator_queue.put(_RetireSentinel())
            self._workers_count = workers_count

    def _worker_loop(self, worker):
        while not self._stop_event.is_set():
            try:
                item = self._ventilator_queue.get(timeout=0.05)
            except queue.Empty:
                continue
            if isinstance(item, (EOFSentinel, _RetireSentinel)):
                break
            args, kwargs = item
            try:
                worker.process(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 - forwarded to the consumer
                tb = "".join(traceback.format_exception(*sys.exc_info()))
                self._results_queue.put(WorkerException(exc, tb))
            finally:
                # Count failed items as processed too — otherwise the
                # ventilator's in-flight window leaks and the pool deadlocks.
                # The marker carries the item's kwargs so a consumer-side
                # item_done_hook can attribute the completion.
                self._results_queue.put(
                    VentilatedItemProcessedMessage(kwargs or None))

    def ventilate(self, *args, **kwargs):
        with self._counter_lock:
            self._ventilated_items += 1
        POOL_ITEMS_VENTILATED.inc()
        self._ventilator_queue.put((args, kwargs))

    def get_results(self, timeout=DEFAULT_TIMEOUT_S):
        """Return the next published payload.

        Raises :class:`EmptyResultError` when ventilation is finished and all
        results have been consumed; re-raises worker exceptions. ``timeout``
        bounds the whole call (deadline), not each internal wait.
        """

        deadline = time.monotonic() + timeout
        while True:
            self._raise_on_ventilator_error()
            if self._results_queue.empty() and self._all_done():
                raise EmptyResultError()
            try:
                wait = min(0.5, max(0.001, deadline - time.monotonic()))
                result = self._results_queue.get(timeout=wait)
            except queue.Empty:
                if self._all_done():
                    raise EmptyResultError() from None
                if time.monotonic() >= deadline:
                    raise TimeoutWaitingForResultError(
                        f"No results for {timeout}s; "
                        f"ventilated={self._ventilated_items} "
                        f"completed={self._completed_items}"
                    ) from None
                continue
            if isinstance(result, VentilatedItemProcessedMessage):
                with self._counter_lock:
                    self._completed_items += 1
                POOL_ITEMS_PROCESSED.inc()
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                hook = self.item_done_hook
                if hook is not None and result.item is not None:
                    hook(result.item)
                continue
            if isinstance(result, WorkerException):
                raise result
            with self._counter_lock:
                self._results_pending -= 1
            POOL_RESULTS_QUEUE_DEPTH.dec()
            return result

    def _raise_on_ventilator_error(self):
        error = getattr(self._ventilator, "error", None) if self._ventilator else None
        if error is not None:
            raise RuntimeError(f"Ventilation failed: {error!r}") from error

    def _all_done(self):
        with self._counter_lock:
            counts_settled = self._ventilated_items == self._completed_items
        ventilation_over = self._ventilator is None or self._ventilator.completed()
        return counts_settled and ventilation_over and self._ventilator_queue.empty()

    def results_qsize(self):
        """Real RESULT payloads awaiting :meth:`get_results` (the raw queue
        also holds DONE bookkeeping markers, which don't count — same
        semantics as ``ProcessPool.results_qsize``)."""
        with self._counter_lock:
            return self._results_pending

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()
        for _ in self._threads:
            self._ventilator_queue.put(EOFSentinel())

    def join(self):
        deadline = time.monotonic() + 30
        while any(t.is_alive() for t in self._threads):
            # Drain the bounded results queue so workers blocked in put()
            # can observe the stop event and exit.
            try:
                while True:
                    self._results_queue.get_nowait()
            except queue.Empty:
                with self._counter_lock:
                    POOL_RESULTS_QUEUE_DEPTH.dec(self._results_pending)
                    self._results_pending = 0
            if time.monotonic() > deadline:  # pragma: no cover - stuck worker
                break
            time.sleep(0.01)
        for thread in self._threads:
            thread.join(timeout=1)
        for worker in self._workers:
            worker.shutdown()
        self._threads = []
