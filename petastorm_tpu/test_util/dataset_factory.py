"""Synthetic dataset factory — the fixture nearly every behavioral test reads.

Reference parity: ``petastorm/tests/test_common.py`` (``TestSchema``,
``create_test_dataset``, ``create_test_scalar_dataset``) — SURVEY.md §2.7.
Differences: materialization is pyarrow-native (no Spark) and the schema is
arrow-typed.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np

from petastorm_tpu.etl.metadata import materialize_rows, write_rows
from petastorm_tpu.schema.codecs import (
    CompressedImageCodec,
    CompressedNdarrayCodec,
    NdarrayCodec,
    ScalarCodec,
)
from petastorm_tpu.schema.unischema import Unischema, UnischemaField

TestSchema = Unischema("TestSchema", [
    UnischemaField("id", np.int64, (), ScalarCodec(), False),
    UnischemaField("id2", np.int32, (), ScalarCodec(), False),
    UnischemaField("partition_key", str, (), ScalarCodec(), False),
    UnischemaField("python_primitive_uint8", np.uint8, (), ScalarCodec(), False),
    UnischemaField("image_png", np.uint8, (16, 32, 3), CompressedImageCodec("png"), False),
    UnischemaField("matrix", np.float32, (4, 8), NdarrayCodec(), False),
    UnischemaField("matrix_nullable", np.float64, (2, 3), CompressedNdarrayCodec(), True),
    UnischemaField("decimal", Decimal, (), ScalarCodec(), False),
    UnischemaField("string_value", str, (), ScalarCodec(), False),
    UnischemaField("sensor_name", str, (), ScalarCodec(), False),
    UnischemaField("timestamp_s", np.int64, (), ScalarCodec(), False),
])


def make_test_row(index, rng=None):
    rng = rng or np.random.RandomState(index)
    return {
        "id": index,
        "id2": index % 5,
        "partition_key": f"p_{index % 4}",
        "python_primitive_uint8" : np.uint8(index % 255),
        "image_png": rng.randint(0, 255, (16, 32, 3), dtype=np.uint8),
        "matrix": rng.rand(4, 8).astype(np.float32),
        "matrix_nullable": (rng.rand(2, 3).astype(np.float64)
                            if index % 3 else None),
        "decimal": Decimal(f"{index}.{index % 10}"),
        "string_value": f"string_{index}",
        "sensor_name": f"sensor_{index % 2}",
        "timestamp_s": 1_000_000 + index,
    }


def create_test_dataset(dataset_url, rows_count=30, rows_per_row_group=10,
                        rows_per_file=None, **write_kwargs):
    """Write a petastorm-format synthetic dataset; returns the source rows."""
    rows = [make_test_row(i) for i in range(rows_count)]
    materialize_rows(dataset_url, TestSchema, rows,
                     rows_per_row_group=rows_per_row_group,
                     rows_per_file=rows_per_file, **write_kwargs)
    return rows


#: Ragged-in-Parquet token layout (docs/guides/llm.md#datasets): static
#: [TOKEN_MAX_LEN] arrays on disk, the true sequence length as data — the
#: packing stage trims to ``length`` before first-fit placement.
TOKEN_MAX_LEN = 48

TokenSchema = Unischema("TokenSchema", [
    UnischemaField("id", np.int64, (), ScalarCodec(), False),
    UnischemaField("tokens", np.int32, (TOKEN_MAX_LEN,), NdarrayCodec(),
                   False),
    UnischemaField("length", np.int32, (), ScalarCodec(), False),
])


def make_token_row(index, max_len=TOKEN_MAX_LEN, skew=2.5):
    """One deterministic variable-length 'tokenized document': lengths
    are short-heavy (mean ≈ ``max_len / (1 + skew)`` — many short, few
    near-max, the padding waste packing exists to eliminate; ``skew=1``
    is uniform), tokens derived from the index so every byte is
    reproducible."""
    rng = np.random.RandomState(977 + index)
    length = max(1, min(max_len,
                        int(round(max_len * (1.0 - rng.power(skew))))))
    tokens = np.zeros(max_len, dtype=np.int32)
    tokens[:length] = (np.arange(length, dtype=np.int32) * 7919
                       + index * 31 + 1) % 50000
    return {"id": index, "tokens": tokens, "length": np.int32(length)}


def create_test_token_dataset(dataset_url, rows_count=60,
                              rows_per_row_group=10, max_len=TOKEN_MAX_LEN,
                              skew=2.5, **write_kwargs):
    """Write a petastorm-format variable-length token dataset (the LLM
    sequence-packing workload's fixture); returns the source rows."""
    if max_len == TOKEN_MAX_LEN:
        schema = TokenSchema
    else:
        schema = Unischema("TokenSchema", [
            UnischemaField("id", np.int64, (), ScalarCodec(), False),
            UnischemaField("tokens", np.int32, (max_len,), NdarrayCodec(),
                           False),
            UnischemaField("length", np.int32, (), ScalarCodec(), False),
        ])
    rows = [make_token_row(i, max_len=max_len, skew=skew)
            for i in range(rows_count)]
    materialize_rows(dataset_url, schema, rows,
                     rows_per_row_group=rows_per_row_group, **write_kwargs)
    return rows


#: Predicate-selective layout (the filter-hoisting rewrite's fixture —
#: docs/guides/pipeline.md#graph-rewrites): a cheap scalar ``keep``
#: column drives row selection while ``payload`` makes every NON-hoisted
#: decode expensive enough to measure — dropping a row after decode costs
#: real work, dropping it in the two-phase predicate read costs none.
def _selective_schema(payload_shape):
    return Unischema("SelectiveSchema", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("keep", np.int32, (), ScalarCodec(), False),
        UnischemaField("payload", np.uint8, tuple(payload_shape),
                       CompressedImageCodec("png"), False),
    ])


SelectiveSchema = _selective_schema((64, 64, 3))


def make_selective_row(index, keep_every=4, payload_shape=(64, 64, 3)):
    """One deterministic row: ``keep`` is 1 for every ``keep_every``-th
    row (selectivity = 1/keep_every), payload derived from the index so
    every byte is reproducible."""
    rng = np.random.RandomState(1789 + index)
    return {
        "id": index,
        "keep": np.int32(1 if index % keep_every == 0 else 0),
        "payload": rng.randint(0, 255, payload_shape, dtype=np.uint8),
    }


def create_test_selective_dataset(dataset_url, rows_count=120,
                                  rows_per_row_group=20, keep_every=4,
                                  payload_shape=(64, 64, 3),
                                  **write_kwargs):
    """Write a predicate-selective petastorm dataset: a majority of rows
    (``1 - 1/keep_every``) fail ``keep == 1``, and the payload is a real
    png decode per row, so a hoisted predicate skips most of the decode
    work a client-side filter pays for. Returns the source rows. Pair
    with ``ColumnPredicate('keep', 'eq', 1)``
    (:mod:`petastorm_tpu.predicates`)."""
    schema = _selective_schema(payload_shape)
    rows = [make_selective_row(i, keep_every=keep_every,
                               payload_shape=tuple(payload_shape))
            for i in range(rows_count)]
    materialize_rows(dataset_url, schema, rows,
                     rows_per_row_group=rows_per_row_group, **write_kwargs)
    return rows


ScalarSchema = Unischema("ScalarSchema", [
    UnischemaField("id", np.int64, (), None, False),
    UnischemaField("float_col", np.float64, (), None, False),
    UnischemaField("int_col", np.int32, (), None, False),
    UnischemaField("string_col", str, (), None, False),
])


def create_test_scalar_dataset(dataset_url, rows_count=30,
                               rows_per_row_group=10, **write_kwargs):
    """Plain-Parquet dataset (no petastorm metadata) for make_batch_reader."""
    rows = [{
        "id": i,
        "float_col": i * 1.5,
        "int_col": np.int32(i * 2),
        "string_col": f"value_{i}",
    } for i in range(rows_count)]
    write_rows(dataset_url, ScalarSchema, rows,
               rows_per_row_group=rows_per_row_group, **write_kwargs)
    return rows
