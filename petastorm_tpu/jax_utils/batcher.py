"""Collation: reader output → fixed-size numpy batches.

The reference leaves fixed-size batching to the frameworks (``tf.data.batch``,
torch collate — ``petastorm/pytorch.py::decimal_friendly_collate``). For SPMD
consumers batch cardinality is correctness, not convenience: every host must
dispatch the same number of steps per epoch or the pjit program deadlocks
(SURVEY.md §7 hard-part #2). So the batcher makes the last-batch policy
explicit:

- ``last_batch="drop"`` — drop the final partial batch (default; matches what
  ``tf.data`` calls ``drop_remainder=True``);
- ``last_batch="pad"`` — wrap-pad the final partial batch to full size and
  attach a boolean ``PAD_MASK_KEY`` column (True = real row) so losses can be
  masked;
- ``last_batch="keep"`` — yield the ragged final batch (non-SPMD use only).

Rows arrive either as schema namedtuples (``make_reader``), NGram dicts
``{offset: namedtuple}`` (collated to ``[B, T, ...]``), or column-batch
namedtuples of record-batch length (``make_batch_reader`` — re-sliced to the
requested batch size).
"""

from __future__ import annotations

import numpy as np

#: Name of the boolean mask column attached when ``last_batch="pad"``.
PAD_MASK_KEY = "__pad_mask__"

_LAST_BATCH_POLICIES = ("drop", "pad", "keep")


def _stack_column(values):
    """Stack per-row values into one [B, ...] numpy array.

    Numeric/array values stack densely; strings/Decimals/objects — and
    nullable columns where any row is None — become an object array (the
    loader keeps those host-side).
    """
    first = values[0]
    if isinstance(first, np.ndarray) and first.dtype != object:
        # Dense only when every row is a same-shaped array (a nullable field
        # can mix ndarrays with None).
        if all(isinstance(v, np.ndarray) and v.shape == first.shape
               and v.dtype == first.dtype for v in values):
            return np.stack(values)
    elif isinstance(first, (int, float, bool, np.generic)) and \
            all(v is not None for v in values):
        return np.asarray(values)
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def collate_rows(rows, fields=None):
    """Collate a list of namedtuple/dict rows into ``{field: [B, ...]}``."""
    if not rows:
        return {}
    first = rows[0]
    if isinstance(first, dict):
        names = fields or list(first)
        get = lambda row, name: row[name]  # noqa: E731
    else:
        names = fields or list(first._fields)
        get = getattr
    return {name: _stack_column([get(row, name) for row in rows])
            for name in names}


def collate_ngram_rows(rows):
    """Collate NGram rows ``{offset: namedtuple}`` into ``[B, T, ...]`` arrays.

    Offsets are sorted to form the time axis. A field present at *every*
    timestep becomes ``{name: [B, T, ...]}``; a field present at only some
    timesteps keeps per-step identity as ``{f"{name}@{offset}": [B, ...]}``
    (NGram field sets may legitimately differ per offset — reference
    ``petastorm/ngram.py`` semantics, SURVEY.md §2.1).
    """
    if not rows:
        return {}
    offsets = sorted(rows[0])
    fields_at = {off: set(rows[0][off]._fields) for off in offsets}
    common = set.intersection(*fields_at.values()) if offsets else set()

    out = {}
    for name in sorted(common):
        # [B, T, ...]: stack rows then timesteps.
        per_row = [
            np.stack([np.asarray(getattr(row[off], name)) for off in offsets])
            for row in rows
        ]
        out[name] = _stack_column(per_row)
    for off in offsets:
        for name in sorted(fields_at[off] - common):
            out[f"{name}@{off}"] = _stack_column(
                [np.asarray(getattr(row[off], name)) for row in rows])
    return out


def _pad_batch(batch, batch_size):
    """Wrap-pad every column to ``batch_size`` rows and attach PAD_MASK_KEY."""
    short = next(iter(batch.values())).shape[0] if batch else 0
    reps = -(-batch_size // max(short, 1))
    padded = {}
    for name, col in batch.items():
        tiled = np.concatenate([col] * reps)[:batch_size]
        padded[name] = tiled
    mask = np.zeros(batch_size, dtype=bool)
    mask[:short] = True
    padded[PAD_MASK_KEY] = mask
    return padded


def batch_iterator(reader, batch_size, last_batch="drop", max_batches=None,
                   shuffle_buffer_size=0, shuffle_seed=None):
    """Yield ``{field: [batch_size, ...]}`` dicts from a Reader.

    Handles all three reader output shapes (rows, NGram windows, column
    batches). ``max_batches`` truncates the stream (used by the loader's
    equal-step coordination and by benchmarks). ``shuffle_buffer_size`` > 0
    decorrelates rows within row groups through a ``RandomShufflingBuffer``
    (the reference's ``shuffling_queue_capacity`` — row readers only).
    """
    if last_batch not in _LAST_BATCH_POLICIES:
        raise ValueError(
            f"last_batch must be one of {_LAST_BATCH_POLICIES}, "
            f"got {last_batch!r}")
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")

    produced = 0
    if getattr(reader, "batched_output", False):
        if shuffle_buffer_size:
            raise ValueError(
                "shuffle_buffer_size requires a row reader (make_reader); "
                "column-batch readers shuffle at row-group granularity via "
                "shuffle_row_groups")
        source = _rebatch_column_batches(reader, batch_size)
    else:
        source = _batch_rows(reader, batch_size, shuffle_buffer_size,
                             shuffle_seed)

    # The limit check precedes the source pull: pulling first would decode a
    # full batch past the limit only to discard it (and with max_batches=0 —
    # the empty-shard lockstep case — would decode a batch before yielding
    # nothing at all).
    while max_batches is None or produced < max_batches:
        try:
            batch, full = next(source)
        except StopIteration:
            return
        if not full:
            if last_batch == "drop":
                return
            if last_batch == "pad":
                batch = _pad_batch(batch, batch_size)
        produced += 1
        yield batch


def _batch_rows(reader, batch_size, shuffle_buffer_size=0, shuffle_seed=None):
    """Row reader → (collated batch dict, is_full) pairs."""
    buf = []
    ngram = getattr(reader, "ngram", None) is not None
    collate = collate_ngram_rows if ngram else collate_rows

    if shuffle_buffer_size:
        from petastorm_tpu.reader_impl.shuffling_buffer import (
            RandomShufflingBuffer,
        )

        sbuf = RandomShufflingBuffer(
            shuffle_buffer_size,
            min_after_retrieve=shuffle_buffer_size // 2,
            extra_capacity=max(shuffle_buffer_size, 1000),
            random_seed=shuffle_seed)

        def rows():
            for row in reader:
                sbuf.add_many([row])
                while not sbuf.can_add() and sbuf.can_retrieve():
                    yield sbuf.retrieve()
            sbuf.finish()
            while sbuf.can_retrieve():
                yield sbuf.retrieve()

        source = rows()
    else:
        source = reader

    for row in source:
        buf.append(row)
        if len(buf) == batch_size:
            yield collate(buf), True
            buf = []
    if buf:
        yield collate(buf), False


def _rebatch_column_batches(reader, batch_size):
    """Column-batch reader → fixed-size (batch dict, is_full) pairs.

    Record batches arrive at row-group/record-batch granularity; slice and
    stitch them into exact ``batch_size`` chunks, carrying remainders across
    input batches.
    """
    pending = {}   # field -> list of leftover column chunks
    pending_rows = 0
    names = None

    def emit(n):
        nonlocal pending, pending_rows
        out, rest = {}, {}
        for name in names:
            joined = (pending[name][0] if len(pending[name]) == 1
                      else np.concatenate(pending[name]))
            out[name] = joined[:n]
            rest[name] = [joined[n:]] if joined.shape[0] > n else []
        pending = rest
        pending_rows -= n
        return out

    for col_batch in reader:
        batch_dict = col_batch._asdict() if hasattr(col_batch, "_asdict") \
            else dict(col_batch)
        if names is None:
            names = list(batch_dict)
            pending = {name: [] for name in names}
        rows_in = len(next(iter(batch_dict.values())))
        for name in names:
            pending[name].append(np.asarray(batch_dict[name]))
        pending_rows += rows_in
        while pending_rows >= batch_size:
            yield emit(batch_size), True
    if pending_rows:
        yield emit(pending_rows), False
