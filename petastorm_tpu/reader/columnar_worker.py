"""Columnar decode worker: one row group → dict of decoded ``[N, ...]`` arrays.

This is the TPU-native fast path (``make_columnar_reader``) with no upstream
counterpart: the reference forces a choice between per-row codec decode
(``petastorm/py_dict_reader_worker.py`` — python object per row, namedtuple
assembly, the measured hot path) and codec-less column batches
(``petastorm/arrow_reader_worker.py`` — ``make_batch_reader`` leaves codec
columns encoded). Here codec columns are decoded **vectorized**
(``DataframeColumnCodec.decode_column``: imdecode/frombuffer straight into
preallocated ``[N, *shape]`` arrays) so a row group becomes a dict of dense
column arrays with zero per-row python objects — the shape
``make_jax_dataloader`` batches from with pure slicing.

Worker output/batcher contract matches ``ArrowReaderWorker`` (column-batch
namedtuples, ``batched_output=True``); predicates and
``shuffle_row_drop_partitions`` are applied on the encoded arrow table before
any decode work, and ``TransformSpec.func`` operates on the decoded
``{field: [N, ...]}`` dict (columnar semantics — vectorize your transform).
"""

from __future__ import annotations

import time
from collections import OrderedDict

import numpy as np
import pyarrow as pa

from petastorm_tpu import failpoints as _failpoints
from petastorm_tpu.reader_impl.delivery_tracker import PiecePayload, item_key
from petastorm_tpu.schema.codecs import DataframeColumnCodec
from petastorm_tpu.schema.transform import transform_schema
from petastorm_tpu.telemetry.metrics import COLUMNAR_KERNEL_SECONDS
from petastorm_tpu.workers_pool.worker_base import WorkerBase


class ColumnarDecodeWorker(WorkerBase):
    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        (self._filesystem, self._pieces, self._schema, self._read_schema,
         self._ngram, self._cache, self._transform_spec) = args
        if self._ngram is not None:
            raise NotImplementedError(
                "NGram is not supported by make_columnar_reader; use "
                "make_reader (windows are inherently row-wise)")

    def process(self, piece_index, worker_predicate=None,
                shuffle_row_drop_partition=(0, 1)):
        piece = self._pieces[piece_index]
        cache_key = (piece.path, piece.row_group, repr(worker_predicate),
                     tuple(sorted(self._read_schema.fields)),
                     shuffle_row_drop_partition, repr(self._transform_spec),
                     "columnar")
        batch = self._cache.get(
            cache_key,
            lambda: self._load_batch(piece, worker_predicate,
                                     shuffle_row_drop_partition),
        )
        if batch and len(next(iter(batch.values()))) > 0:
            self.publish_func(PiecePayload(
                item_key(piece_index, shuffle_row_drop_partition[0]), batch))

    def _load_batch(self, piece, worker_predicate, shuffle_row_drop_partition):
        columns = sorted(self._read_schema.fields)
        if worker_predicate is not None:
            predicate_fields = sorted(worker_predicate.get_fields())
            unknown = [f for f in predicate_fields
                       if f not in self._schema.fields]
            if unknown:
                raise ValueError(f"Predicate fields not in schema: {unknown}")
            all_columns = sorted(set(columns) | set(predicate_fields))
            table = piece.read(self._filesystem, columns=all_columns)
            mask = self._predicate_mask(table, worker_predicate,
                                        predicate_fields)
            table = table.filter(pa.array(mask)).select(columns)
        else:
            table = piece.read(self._filesystem, columns=columns)

        table = self._drop_partition(table, shuffle_row_drop_partition)

        # The columnar decode boundary: the decode.columnar failpoint's
        # "fallback" action forces this batch through the base-class
        # per-row decode loop — the exact row path the vectorized kernels
        # are proven equal to, so the soak's digest gate holds across it.
        fp = _failpoints.ACTIVE
        rowwise = fp is not None and fp.fire("decode.columnar") == "fallback"
        batch = OrderedDict()
        for name in columns:
            field = self._read_schema.fields[name]
            cells = _column_cells(table.column(name))
            if field.codec is not None:
                if rowwise:
                    batch[name] = DataframeColumnCodec.decode_column(
                        field.codec, field, cells)
                else:
                    t0 = time.perf_counter()
                    batch[name] = field.codec.decode_column(field, cells)
                    COLUMNAR_KERNEL_SECONDS.observe(
                        time.perf_counter() - t0)
            else:
                batch[name] = cells

        if self._transform_spec is not None:
            if self._transform_spec.func:
                batch = self._transform_spec.func(batch)
            result_schema = transform_schema(self._read_schema,
                                             self._transform_spec)
            missing = [c for c in result_schema.fields if c not in batch]
            if missing:
                raise ValueError(
                    f"TransformSpec output is missing declared fields: "
                    f"{missing}")
            batch = OrderedDict((c, batch[c]) for c in result_schema.fields)
        return batch

    def _predicate_mask(self, table, worker_predicate, predicate_fields):
        """Decode only the predicate fields → bool mask (vectorized when the
        predicate supports it, row-wise otherwise).

        Predicate fields are decoded (they may be codec columns) but the
        payload columns are not touched until the mask is known — the
        columnar analogue of ``py_dict_worker``'s two-phase read."""
        decoded = {}
        for name in predicate_fields:
            # Predicate fields may lie outside the requested schema view.
            field = (self._read_schema.fields.get(name)
                     or self._schema.fields.get(name))
            cells = _column_cells(table.column(name))
            if field is not None and field.codec is not None:
                decoded[name] = field.codec.decode_column(field, cells)
            else:
                decoded[name] = cells
        from petastorm_tpu.predicates import evaluate_predicate_mask

        return evaluate_predicate_mask(worker_predicate, decoded,
                                       table.num_rows)

    def _drop_partition(self, table, shuffle_row_drop_partition):
        this_partition, num_partitions = shuffle_row_drop_partition
        if num_partitions <= 1:
            return table
        indices = np.arange(this_partition, table.num_rows, num_partitions)
        return table.take(pa.array(indices))


def _column_cells(column):
    """Materialize an arrow column for codec decode.

    Null-free columns go through ``to_numpy`` (cheap, dense). Columns WITH
    nulls must become object arrays holding None — ``to_numpy`` would
    materialize int-with-null as float64 NaN, which silently corrupts under a
    later integer astype (row-path semantics are None per null cell)."""
    if column.null_count:
        out = np.empty(len(column), dtype=object)
        for i, value in enumerate(column.to_pylist()):
            out[i] = value
        return out
    return column.to_numpy(zero_copy_only=False)


class ColumnarResultsQueueReader:
    """Consumer-side: decoded column dict → namedtuple of column arrays."""

    def __init__(self):
        self.delivery_tracker = None  # set by Reader for resumable iteration
        #: Work-item tag of the most recently returned column batch.
        self.last_item_key = None

    @property
    def batched_output(self):
        return True

    def read_next(self, pool, schema, ngram, timeout=None):
        kwargs = {} if timeout is None else {"timeout": timeout}
        batch = pool.get_results(**kwargs)  # raises EmptyResultError at end
        self.last_item_key = None
        if isinstance(batch, PiecePayload):
            self.last_item_key = batch.item_key
            if self.delivery_tracker is not None:
                num_rows = len(next(iter(batch.payload.values()), ()))
                self.delivery_tracker.record(batch.item_key, num_rows)
            batch = batch.payload
        return schema.make_namedtuple(**batch)
