"""Delivery tracking for resumable reader iteration.

The reference has no checkpoint/resume for readers (SURVEY.md §5: "no
iterator state save" — flagged there as the rebuild opportunity). On a TPU
pod, model state checkpoints through orbax; without input-pipeline state a
restart replays or skips data. This module is the accounting half of
``Reader.state_dict()`` / ``make_reader(..., resume_state=...)``:

- Workers tag each published payload with the identity of the ventilated
  work item that produced it (``(piece_index, drop_partition)`` — one row
  group, one drop partition).
- The consumer-side results-queue readers record the tag **when the payload
  is handed to the consumer** (not when the worker finishes — a payload
  still sitting in a queue at checkpoint time must be re-read on resume).
- ``DeliveryTracker`` keeps ``{item_key: times_delivered}``; resume
  re-ventilates each item ``num_epochs - times_delivered`` more times.

Semantics: **at-least-once at row-group granularity.** Rows from a row group
that was partially consumed (or decoded but not yet consumed) at checkpoint
time are seen again after resume; fully-delivered row groups are never
re-read. Work items whose rows were all filtered by a predicate publish
nothing, so they re-run on resume and re-filter to nothing — harmless.
"""

from __future__ import annotations

import threading


def item_key(piece_index, drop_partition):
    """Stable JSON-friendly identity of one ventilated work item."""
    return f"{piece_index}:{drop_partition}"


class PiecePayload:
    """A worker's published payload tagged with its work-item identity.

    Used for pickle-serialized payload types (row lists, column dicts);
    ``pa.Table`` payloads carry the tag in their schema metadata instead so
    the Arrow-IPC serializer keeps working on plain tables.
    """

    __slots__ = ("item_key", "payload")

    def __init__(self, item_key, payload):
        self.item_key = item_key
        self.payload = payload

    def __reduce__(self):  # keep pickling cheap and explicit
        return (PiecePayload, (self.item_key, self.payload))


class FusedBatch:
    """One wire-ready batch a fused pool task produced: serialized frames
    plus (when cache placement wants pre-transform bytes) the
    pre-transform serialization of the same rows."""

    __slots__ = ("rows", "fmt", "frames", "pre_fmt", "pre_frames")

    def __init__(self, rows, fmt, frames, pre_fmt=None, pre_frames=None):
        self.rows = rows
        self.fmt = fmt
        self.frames = frames
        self.pre_fmt = pre_fmt
        self.pre_frames = pre_frames


class FusedPiecePayload(PiecePayload):
    """A whole piece's batches, fully collated/transformed/serialized
    INSIDE the pool worker task (the stage-fusion graph rewrite —
    ``docs/guides/pipeline.md#graph-rewrites``): ``payload`` is a list of
    :class:`FusedBatch`. The consumer-side results-queue readers hand the
    payload through whole instead of splitting it into rows — the per-row
    hand-off (queue hops, namedtuple construction, stream-thread
    collation) this fusion exists to eliminate."""

    __slots__ = ()

    def __reduce__(self):
        return (FusedPiecePayload, (self.item_key, self.payload))


def apply_publish_transform(transform, item):
    """The pools' shared publish-hook application: a ``publish_transform``
    (the stage-fusion rewrite's injection point) applies to
    :class:`PiecePayload` publishes only — bookkeeping messages, worker
    exceptions, and table payloads pass through untouched. One helper so
    the thread and dummy pools cannot silently diverge."""
    if transform is not None and isinstance(item, PiecePayload):
        return transform(item)
    return item


#: Schema-metadata key carrying the work-item tag on ``pa.Table`` payloads.
TABLE_ITEM_KEY = b"petastorm_tpu.delivery_item.v1"


def tag_table(table, key):
    """Return ``table`` with the work-item tag merged into schema metadata."""
    metadata = dict(table.schema.metadata or {})
    metadata[TABLE_ITEM_KEY] = key.encode("utf-8")
    return table.replace_schema_metadata(metadata)


def read_table_tag(table):
    """Extract the work-item tag from a table (None when untagged)."""
    metadata = table.schema.metadata or {}
    raw = metadata.get(TABLE_ITEM_KEY)
    return raw.decode("utf-8") if raw is not None else None


class DeliveryTracker:
    """Thread-safe ``{item_key: times_delivered}`` counter with a rollback log.

    ``record`` is called from whatever thread iterates the reader (e.g. the
    JAX loader's producer thread); ``state_dict`` from the checkpointing
    thread — hence the lock.

    The ordered ``(key, num_rows)`` log supports downstream-buffer
    compensation: a consumer that buffers rows past the reader interface
    (``JaxDataLoader``'s host queue + device prefetch) checkpoints via
    ``counts_rolled_back_to(yielded_rows)``, which un-counts the newest
    deliveries until only the rows actually surfaced remain — buffered rows
    re-read on resume (at-least-once). Valid only while rows flow FIFO from
    the reader through the consumer; a reordering stage (the loader's
    row-level ``shuffle_buffer_size``) can hold an OLD row while newer
    deliveries drain, which tail-rollback cannot reach — the loader
    therefore refuses to checkpoint in that configuration.
    """

    #: Rollback log cap. Rollback depth is bounded by the loader's buffered
    #: rows (a handful of batches), which can never span this many distinct
    #: deliveries; the cap keeps memory O(1) over long runs.
    MAX_LOG_ENTRIES = 100_000

    def __init__(self, preload=None):
        self._lock = threading.Lock()
        self._counts = dict(preload or {})
        self._log = []  # ordered (key, num_rows) of this run's deliveries
        self._total_rows = 0

    def record(self, key, num_rows=1):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._log.append((key, num_rows))
            if len(self._log) > self.MAX_LOG_ENTRIES:
                del self._log[:len(self._log) // 2]
            self._total_rows += num_rows

    def counts(self):
        with self._lock:
            return dict(self._counts)

    def total_rows_recorded(self):
        """Rows delivered through the reader interface during this run
        (excludes preloaded prior-run counts)."""
        with self._lock:
            return self._total_rows

    def counts_rolled_back_to(self, yielded_rows):
        """Counts as if only the first ``yielded_rows`` delivered rows had
        happened: the newest deliveries are un-counted (whole deliveries at
        a time) until the remaining recorded rows are <= ``yielded_rows``.

        Computed atomically under the tracker lock — the consumer may keep
        recording concurrently; deliveries recorded after the caller read
        its yielded-row count land at the log tail and are rolled back
        first, which only widens the re-read window (conservative).
        Partially-consumed deliveries roll back entirely (at-least-once).
        """
        with self._lock:
            counts = dict(self._counts)
            remaining = self._total_rows
            for key, num_rows in reversed(self._log):
                if remaining <= yielded_rows:
                    break
                counts[key] = counts.get(key, 0) - 1
                if counts[key] <= 0:
                    counts.pop(key)
                remaining -= num_rows
            if remaining > yielded_rows:
                # The rollback log was truncated (MAX_LOG_ENTRIES) past the
                # point this snapshot needs: the counts would over-report
                # deliveries and a resume would SKIP buffered-but-unyielded
                # rows, silently breaking at-least-once. Refuse to produce a
                # lossy checkpoint.
                raise RuntimeError(
                    "delivery log exhausted while rolling back to "
                    f"{yielded_rows} yielded rows ({remaining} still "
                    "recorded): snapshot taken too long after the rows were "
                    "buffered (log capped at "
                    f"{self.MAX_LOG_ENTRIES} entries); checkpoint earlier "
                    "or raise MAX_LOG_ENTRIES")
            return counts
