"""The dispatcher: control plane of the disaggregated data service.

Owns the split plan and nothing else — no sample bytes ever flow through it
(tf.data service's design split, arxiv 2210.14826 §3): workers register
their address and the dataset's row-group count; clients ask it which pieces
to stream from which workers. State is a few dicts under one lock; every
request is a single framed message with a single framed reply, so the
dispatcher stays trivially cheap even with many clients polling.

Fault tolerance (``docs/guides/service.md#failure-model-and-recovery``):

- **Durability** — with ``journal_dir`` set, every control-plane mutation
  is appended to a JSONL WAL (:mod:`petastorm_tpu.service.journal`) with
  periodic compacted snapshots; a restarted dispatcher replays it and
  resumes with byte-identical assignments, so a dispatcher crash never
  strands the fleet or loses epoch state.
- **Liveness** — workers and clients heartbeat; a worker that misses its
  ``lease_timeout_s`` lease is evicted (its splits re-assigned through the
  existing takeover path) and re-admitted when it re-registers.
- **Fencing** — a monotonically increasing ``fencing_epoch`` bumps on every
  event that invalidates outstanding assignments (journal replay, worker
  eviction, reported failure). Assignment-changing requests carry the
  client's last-synced epoch; a stale one is rejected with
  ``stale_fencing`` so a pre-restart client resyncs instead of acting on a
  superseded plan (no double-delivery, no skipped splits).

Request vocabulary (header ``type``):

- ``register_worker`` ``{worker_id, host, port, num_pieces[, re_register]}``
  → ``ok``
- ``worker_heartbeat`` ``{worker_id}`` → ``ok`` (lease renewed) or
  ``unknown_worker`` (the worker must re-register — dispatcher restarted
  without a journal, or the lease already expired)
- ``client_heartbeat`` ``{client_id}`` → ``ok`` with the current
  ``fencing_epoch`` + recovery counters (clients detect restarts/evictions
  from the epoch moving past the one they synced at)
- ``list_workers`` → ``workers`` (alive worker addresses + service config)
- ``get_assignment`` ``{client_id, client_index, num_clients, epoch}``
  (static mode) → ``assignment``: this client's row-group shard partitioned
  across live workers
- ``report_failure`` ``{client_id, worker_id, pieces[, fencing_epoch]}`` →
  ``assignment`` (the dead worker's pieces re-partitioned across survivors)
  or ``stale_fencing``
- ``next_split`` ``{client_id}`` (fcfs mode) → ``split`` or
  ``end_of_stream`` (dispatcher-owned epoch tracking: the shared queue
  refills until ``num_epochs`` is exhausted)
- ``dynamic_plan`` ``{client_id, client_index, num_clients, epoch}``
  (dynamic mode) → ``plan``: this client's shard split into per-worker
  piece deques, every piece stamped with an ownership ``generation``
- ``dynamic_sync`` ``{client_id, epoch, done, owned, stealable, rates,
  failed_steals}`` (dynamic mode) → ``deltas``: the work-stealing
  rebalance loop — the client reports progress and per-worker backlog,
  the dispatcher journals steals away from drained/straggler-bound
  workers and replies with the moves (``docs/guides/service.md#sharding-modes``)
- ``status`` → full control-plane snapshot (workers, clients, queue depth,
  fencing epoch, recovery counters, journal stats)
- ``worker_diagnostics`` → one fan-out to every live worker's
  ``diagnostics`` endpoint, aggregated — a trainer (or an operator's
  one-liner) reads the whole fleet's reader/flow-control state through the
  single address it already knows
- ``ping`` → ``pong``
"""

from __future__ import annotations

import threading
import time
from collections import deque

from petastorm_tpu.reader_impl.framed_socket import (
    FramedReader,
    FramedServer,
    send_framed,
)
from petastorm_tpu.service.seedtree import piece_order
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    DISPATCHER_BACKLOG_PIECES,
    DISPATCHER_FENCING_EPOCH,
    DISPATCHER_GENERATION,
    DISPATCHER_RECOVERY_EVENTS,
    DISPATCHER_REQUESTS,
    DISPATCHER_STEALS,
    DISPATCHER_WORKERS,
)

logger = service_logger(__name__)

MODES = ("static", "fcfs", "dynamic")

#: Dynamic mode: a worker whose delivery rate falls below this fraction of
#: the fleet median (while it still holds stealable backlog) is treated as
#: a straggler even before any peer's deque drains.
STRAGGLER_RATE_FACTOR = 0.5


def plan_steals(pending, stealable, rates,
                straggler_factor=STRAGGLER_RATE_FACTOR):
    """Work-stealing planner (pure — unit-testable without sockets).

    :param pending: ``{worker_id: not-done piece count}`` over live workers.
    :param stealable: ``{worker_id: [pieces]}`` the client reports as not
        yet started (queued beyond the engine's in-flight window) — the
        only pieces a steal may touch; the revoke handshake still guards
        the race where one starts between report and revoke.
    :param rates: ``{worker_id: rows_per_s}`` from the client's PR 4
        delivery counters (may be empty early in an epoch).
    :returns: ``[(piece, from_worker, to_worker), ...]`` — steals are taken
        from the donor's TAIL (farthest from being served).

    Two triggers, in priority order:

    - **drain**: a worker with zero pending pieces receives from the most
      backlogged donor (classic work stealing);
    - **straggler**: no deque has drained yet, but a donor's rate is below
      ``straggler_factor`` × the fleet median — pieces move to a
      median-or-faster worker with materially less backlog.

    Move sizing: with measured rates for both sides, backlog is split
    **proportionally to rate** — a 10× faster receiver takes ~10/11 of the
    joint backlog in ONE sync, instead of the geometric half-then-quarter
    convergence of midpoint splitting (each extra round leaves the
    straggler decoding pieces it should never have kept, and a started
    piece is no longer stealable — rounds are not free). Without rates the
    midpoint is the only defensible split. Either way the move is bounded
    by what is actually stealable and the donor keeps at least one piece.
    """
    pending = dict(pending)
    stealable = {wid: list(ps) for wid, ps in stealable.items()}
    moves = []
    while True:
        donors = [wid for wid, ps in stealable.items()
                  if ps and pending.get(wid, 0) > 1]
        if not donors:
            return moves
        donor = max(donors, key=lambda w: (pending[w], w))
        receivers = [wid for wid in pending
                     if wid != donor and pending[wid] == 0]
        if not receivers:
            working = sorted(r for wid, r in rates.items()
                             if pending.get(wid, 0) > 0)
            median = working[len(working) // 2] if working else None
            donor_rate = rates.get(donor)
            if median and donor_rate is not None \
                    and donor_rate < straggler_factor * median:
                receivers = [
                    wid for wid in pending
                    if wid != donor and rates.get(wid, 0.0) >= median
                    # "materially less backlog" — waived while the donor
                    # has delivered nothing at all (equal backlogs say
                    # nothing when only one side is moving).
                    and (pending[wid] < pending[donor] - 1
                         or not donor_rate)]
        if not receivers:
            return moves
        recv = min(receivers,
                   key=lambda w: (pending[w], -rates.get(w, 0.0), w))
        donor_rate, recv_rate = rates.get(donor), rates.get(recv)
        if donor_rate and recv_rate:
            joint = pending[donor] + pending[recv]
            keep = max(1, round(joint * donor_rate
                                / (donor_rate + recv_rate)))
            count = pending[donor] - keep
            if count < 1:
                # The proportional share says the donor keeps everything:
                # the "receiver" is a drained straggler near the epoch
                # tail, and bouncing a piece back to it would serialize
                # the wall behind its slowness. Leave it idle.
                return moves
            working = sorted(r for wid, r in rates.items()
                             if pending.get(wid, 0) > 0)
            tail_median = working[len(working) // 2] if working else None
            if tail_median and recv_rate < straggler_factor * tail_median:
                # The receiver is itself a straggler (it drained because
                # it was shed, not because it is fast). Early-epoch EMAs
                # lie in exactly the direction that over-hands work back
                # (the donor's first window includes warmup), and every
                # piece handed back serves at the slow rate or must be
                # re-stolen. So: a small share (<=2) is not worth the
                # revoke/extend round trip near the tail — leave it idle;
                # a large share moves as a 2-piece PROBE, and only a
                # receiver that chews it and re-drains with a matured
                # rate graduates to full proportional hand-backs.
                if count <= 2:
                    return moves
                count = 2
        elif not donor_rate and recv_rate and pending[donor] >= 4:
            # The donor has delivered NOTHING while the receiver is
            # demonstrably moving — no rate to apportion by, so shed the
            # backlog down to a 1-piece floor (the piece being served) in
            # ONE sync; if the donor was merely slow to start, later
            # syncs' measured rates hand work back proportionally.
            # Halving instead costs a round per factor of 2, and every
            # round the straggler promotes another piece past the send
            # boundary where it stops being stealable.
            count = pending[donor] - 1
        else:
            count = max(1, (pending[donor] - pending[recv]) // 2)
        count = min(count, len(stealable[donor]))
        for _ in range(count):
            piece = stealable[donor].pop()
            moves.append((piece, donor, recv))
            pending[donor] -= 1
            pending[recv] += 1

#: Default worker-lease budget; a worker missing heartbeats this long is
#: evicted and its splits become takeover candidates.
DEFAULT_LEASE_TIMEOUT_S = 30.0

#: Cap on the per-probe ``timeout`` header of ``worker_diagnostics``: a
#: misbehaving client must not pin the probe pool's threads for minutes
#: against an unreachable worker.
PROBE_TIMEOUT_CAP_S = 30.0


class Dispatcher:
    """Split-assignment server; start with :meth:`start`, stop with
    :meth:`stop` (context manager supported).

    :param journal_dir: directory for the crash-recovery journal (WAL +
        snapshots). ``None`` keeps state in memory only (a restart loses
        it — the pre-journal behavior).
    :param lease_timeout_s: evict a worker whose last heartbeat (or
        registration) is older than this. ``None`` disables lease expiry.
    :param journal_compact_every: WAL records between snapshot compactions.
    :param journal_fsync: fsync the WAL per append (durable against OS
        crash; the default survives process crashes).
    :param max_frame_bytes: per-connection receive frame cap (control
        messages are tiny; the default module cap is data-plane-sized).
    :param shuffle_seed: seed-tree deterministic shuffling
        (:mod:`petastorm_tpu.service.seedtree`). Every client-epoch's
        piece order derives from ``fold_in(fold_in(seed, epoch), piece)``
        — a pure function of the seed, the epoch, and the piece identity,
        so the order is invariant to worker count, steal history, join
        timing, and kill/resume. ``None`` = no shuffling (ascending piece
        order, equally deterministic). Static and dynamic modes; fcfs
        ignores it (its queue is inherently racy).
    """

    def __init__(self, host="127.0.0.1", port=0, mode="static", num_epochs=1,
                 journal_dir=None, lease_timeout_s=DEFAULT_LEASE_TIMEOUT_S,
                 journal_compact_every=256, journal_fsync=False,
                 max_frame_bytes=None, shuffle_seed=None):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if num_epochs is not None and num_epochs <= 0:
            raise ValueError("num_epochs must be a positive integer or None")
        self.mode = mode
        self.num_epochs = num_epochs
        self.shuffle_seed = (int(shuffle_seed)
                             if shuffle_seed is not None else None)
        self.journal_dir = journal_dir
        # 0 and None both disable lease expiry (the CLI's documented
        # contract); a literal 0 would otherwise expire every lease the
        # instant it was granted.
        self.lease_timeout_s = lease_timeout_s or None
        self._max_frame_bytes = max_frame_bytes
        self._lock = threading.Lock()
        self._workers = {}   # worker_id -> {address, num_pieces, alive}
        self._clients = {}   # client_id -> {epoch, client_index, num_clients}
        # client_id -> {"epoch", "watermarks": {piece: next ordinal}} —
        # delivery watermarks riding client heartbeats, journaled so a
        # restarted dispatcher (and `status`) knows how far each piece
        # got. Observability + recovery audit; the client's own copy is
        # what re-grants actually use (it is never behind this one).
        self._client_watermarks = {}
        self._num_pieces = None
        # fcfs shared queue: lazily built once the piece count is known.
        self._fcfs_queue = None
        self._fcfs_epoch = 0
        # dynamic mode: per-client ownership state for the epoch in flight
        # (client_id -> {"epoch", "owner": {piece: [wid, gen]}, "done",
        # "steals": {wid: {"in", "out"}}}) and the
        # global ownership-generation counter every grant/steal bumps —
        # the fencing token clients dedup batches by.
        self._dyn = {}
        # Dirty marker for the per-worker backlog/steal gauges: the
        # aggregation walks every client's owner map, so it runs only
        # after a request that actually mutated dynamic state — not on
        # every heartbeat/ping of a large fleet.
        self._dyn_dirty = True
        self._generation = 0
        # runtime-only liveness clocks (never persisted: wall-clock leases
        # restart from "now" after a recovery — a restored worker gets a
        # full lease to re-appear before it is declared dead).
        self._worker_leases = {}       # worker_id -> monotonic expiry
        self._client_heartbeats = {}   # client_id -> monotonic last-seen
        self._fencing_epoch = 0
        self._recovery = {
            "journal_replays": 0,
            "fencing_bumps": 0,
            "evictions": 0,           # lease expiries
            "failures_reported": 0,   # client-reported worker deaths
            "re_registrations": 0,
            "stale_fencing_rejections": 0,
        }
        self._journal = None
        if journal_dir is not None:
            from petastorm_tpu.service.journal import Journal

            self._journal = Journal(journal_dir,
                                    compact_every=journal_compact_every,
                                    fsync=journal_fsync)
        self._lease_thread = None
        self._server = FramedServer(self._serve_connection, host=host,
                                    port=port, name="service-dispatcher")

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._journal is not None:
            self._recover()
        self._server.start()
        if self.lease_timeout_s is not None:
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True,
                name="service-dispatcher-leases")
            self._lease_thread.start()
        return self

    @property
    def address(self):
        """``(host, port)`` clients and workers connect to."""
        return self._server.address

    def stop(self):
        self._server.stop()
        # Drain handler threads BEFORE closing the journal: an in-flight
        # mutation must finish its append (or fail its request), never
        # write into a closed-then-resurrected WAL.
        self._server.join(timeout=5)
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=5)
        if self._journal is not None:
            self._journal.close()

    def drop_connections(self):
        """Abruptly drop every open connection without stopping the server
        (fault injection: a network blip between control-plane peers)."""
        self._server.close_connections()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()

    # -- durability --------------------------------------------------------

    def state_snapshot(self):
        """The dispatcher's full persistable state (what the journal's
        compacted snapshot holds) — JSON-round-trippable, so a restart test
        can assert byte-identical restoration."""
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self):
        return {
            "mode": self.mode,
            "num_epochs": self.num_epochs,
            "shuffle_seed": self.shuffle_seed,
            "num_pieces": self._num_pieces,
            "workers": {wid: dict(w) for wid, w in self._workers.items()},
            "clients": {cid: dict(c) for cid, c in self._clients.items()},
            "client_watermarks": {
                cid: {"epoch": entry["epoch"],
                      "watermarks": {str(p): n for p, n
                                     in entry["watermarks"].items()}}
                for cid, entry in self._client_watermarks.items()},
            "fcfs_epoch": self._fcfs_epoch,
            "fcfs_queue": (list(self._fcfs_queue)
                           if self._fcfs_queue is not None else None),
            "fencing_epoch": self._fencing_epoch,
            "recovery": dict(self._recovery),
            "generation": self._generation,
            # owner maps keyed by int piece → serialized as triplet lists
            # (JSON object keys must be strings).
            "dyn": {
                cid: {
                    "epoch": state["epoch"],
                    "owner": [[piece, wid, gen] for piece, (wid, gen)
                              in sorted(state["owner"].items())],
                    "done": sorted(state["done"]),
                    "steals": {wid: dict(counts) for wid, counts
                               in state["steals"].items()},
                }
                for cid, state in self._dyn.items()},
        }

    def _recover(self):
        """Rebuild state from the journal (snapshot + WAL replay), then
        record the recovery itself: the fencing epoch bumps so every
        outstanding pre-crash assignment must resync, and the replay is
        journaled so ``journal_replays`` survives the *next* restart."""
        state, records = self._journal.load()
        if state is None and not records:
            # Fresh journal: seed it with the initial state so every later
            # recovery (and the mode-compatibility check) has a snapshot
            # to anchor on.
            with self._lock:
                self._journal.snapshot(self._state_dict_locked())
            return
        with self._lock:
            if state is not None:
                self._install_state_locked(state)
            for record in records:
                self._apply_record_locked(record)
            now = time.monotonic()
            lease = self.lease_timeout_s or 0.0
            for wid, worker in self._workers.items():
                if worker["alive"]:
                    self._worker_leases[wid] = now + lease
            self._recovery["journal_replays"] += 1
            self._journal.append({"op": "replayed"})
            self._bump_fencing_locked("journal_replay")
            self._sync_telemetry_locked()
        logger.warning(
            "dispatcher recovered from journal %s: %d workers, %d clients, "
            "%d WAL records replayed", self.journal_dir,
            len(self._workers), len(self._clients), len(records),
            fencing_epoch=self._fencing_epoch)

    def _install_state_locked(self, state):
        if state.get("mode") != self.mode:
            raise ValueError(
                f"journal at {self.journal_dir!r} was written by a "
                f"{state.get('mode')!r}-mode dispatcher; this one runs "
                f"{self.mode!r} — refusing to mix split-plan semantics")
        if state.get("shuffle_seed") != self.shuffle_seed:
            raise ValueError(
                f"journal at {self.journal_dir!r} was written under "
                f"shuffle_seed={state.get('shuffle_seed')!r}; this "
                f"dispatcher runs {self.shuffle_seed!r} — restarting with "
                f"a different seed would silently change the piece order "
                f"mid-run and break the determinism contract")
        self._num_pieces = state.get("num_pieces")
        self._client_watermarks = {
            cid: {"epoch": int(entry.get("epoch", 0)),
                  "watermarks": {int(p): int(n) for p, n
                                 in (entry.get("watermarks")
                                     or {}).items()}}
            for cid, entry in (state.get("client_watermarks")
                               or {}).items()}
        self._workers = {wid: dict(w)
                         for wid, w in state.get("workers", {}).items()}
        self._clients = {cid: dict(c)
                         for cid, c in state.get("clients", {}).items()}
        self._fcfs_epoch = int(state.get("fcfs_epoch", 0))
        queue = state.get("fcfs_queue")
        self._fcfs_queue = deque(queue) if queue is not None else None
        self._fencing_epoch = int(state.get("fencing_epoch", 0))
        recovered = state.get("recovery", {})
        for key in self._recovery:
            self._recovery[key] = int(recovered.get(key, 0))
        self._generation = int(state.get("generation", 0))
        self._dyn = {}
        self._dyn_dirty = True
        for cid, dyn in (state.get("dyn") or {}).items():
            self._dyn[cid] = {
                "epoch": int(dyn["epoch"]),
                "owner": {int(piece): [wid, int(gen)]
                          for piece, wid, gen in dyn.get("owner", [])},
                "done": set(int(p) for p in dyn.get("done", [])),
                "steals": {wid: {"in": int(counts.get("in", 0)),
                                 "out": int(counts.get("out", 0))}
                           for wid, counts
                           in dyn.get("steals", {}).items()},
            }

    def _apply_record_locked(self, record):
        """Replay one WAL record through the same mutations the live
        handlers perform (minus journaling — the record IS the journal)."""
        op = record.get("op")
        if op == "register_worker":
            self._install_worker_locked(
                record["worker_id"],
                [record["host"], int(record["port"])],
                int(record["num_pieces"]),
                re_register=bool(record.get("re_register")))
        elif op == "worker_dead":
            self._mark_worker_dead_locked(record["worker_id"],
                                          record.get("reason", "reported"))
        elif op == "client":
            self._clients[record["client_id"]] = {
                "epoch": int(record["epoch"]),
                "client_index": int(record["client_index"]),
                "num_clients": int(record["num_clients"]),
            }
        elif op == "next_split":
            self._replay_next_split_locked(int(record["piece"]),
                                           int(record["epoch"]))
        elif op == "dynamic_plan":
            self._install_dynamic_plan_locked(
                record["client_id"], int(record["epoch"]),
                {int(p): [wid, int(gen)]
                 for p, wid, gen in record["owner"]},
                int(record["generation"]))
        elif op == "steal":
            self._apply_steal_locked(
                record["client_id"], int(record["piece"]),
                record["from"], record["to"], int(record["generation"]))
        elif op == "steal_failed":
            self._apply_steal_failed_locked(
                record["client_id"], int(record["piece"]),
                record["worker_id"], int(record["generation"]))
        elif op == "dynamic_done":
            state = self._dyn.get(record["client_id"])
            if state is not None:
                state["done"].update(int(p) for p in record["pieces"])
        elif op == "watermarks":
            self._client_watermarks[record["client_id"]] = {
                "epoch": int(record.get("epoch", 0)),
                "watermarks": {int(p): int(n) for p, n
                               in (record.get("watermarks")
                                   or {}).items()},
            }
        elif op == "fencing":
            self._fencing_epoch = int(record["fencing_epoch"])
            self._recovery["fencing_bumps"] += 1
        elif op == "replayed":
            self._recovery["journal_replays"] += 1
        else:
            logger.warning("journal: skipping unknown record op %r", op)

    def _replay_next_split_locked(self, piece, epoch):
        if self._fcfs_queue is None:
            self._fcfs_queue = deque(range(self._num_pieces or 0))
        if epoch > self._fcfs_epoch:
            self._fcfs_epoch = epoch
            self._fcfs_queue = deque(range(self._num_pieces or 0))
        if self._fcfs_queue and self._fcfs_queue[0] == piece:
            self._fcfs_queue.popleft()
        else:  # defensive: a hand-edited journal must not corrupt the queue
            try:
                self._fcfs_queue.remove(piece)
            except ValueError:
                pass

    def _journal_locked(self, record):
        if self._journal is None:
            return
        self._journal.append(record)
        self._journal.maybe_compact(self._state_dict_locked)

    def _bump_fencing_locked(self, reason):
        self._fencing_epoch += 1
        self._recovery["fencing_bumps"] += 1
        self._journal_locked({"op": "fencing",
                              "fencing_epoch": self._fencing_epoch,
                              "reason": reason})
        logger.info("fencing epoch bumped",
                    fencing_epoch=self._fencing_epoch, reason=reason)

    # -- liveness ----------------------------------------------------------

    def _lease_loop(self):
        interval = max(0.05, (self.lease_timeout_s or 1.0) / 4.0)
        while not self._server.stopped.wait(interval):
            now = time.monotonic()
            with self._lock:
                expired = [
                    wid for wid, worker in self._workers.items()
                    if worker["alive"]
                    and self._worker_leases.get(wid, now) <= now]
                for wid in expired:
                    logger.warning(
                        "worker missed its %.1fs lease — evicting (its "
                        "splits re-assign via the takeover path)",
                        self.lease_timeout_s, worker_id=wid,
                        fencing_epoch=self._fencing_epoch)
                    self._mark_worker_dead_locked(wid, "lease_expired")
                    self._journal_locked({"op": "worker_dead",
                                          "worker_id": wid,
                                          "reason": "lease_expired"})
                if expired:
                    self._bump_fencing_locked("lease_expiry")
                    self._sync_telemetry_locked()

    def _mark_worker_dead_locked(self, worker_id, reason):
        worker = self._workers.get(worker_id)
        if worker is None or not worker["alive"]:
            return False
        worker["alive"] = False
        self._worker_leases.pop(worker_id, None)
        if reason == "lease_expired":
            self._recovery["evictions"] += 1
        else:
            self._recovery["failures_reported"] += 1
        return True

    def _install_worker_locked(self, worker_id, address, num_pieces,
                               re_register=False):
        known = worker_id in self._workers
        self._num_pieces = num_pieces
        self._workers[worker_id] = {
            "address": list(address),
            "num_pieces": num_pieces,
            "alive": True,
        }
        if known or re_register:
            self._recovery["re_registrations"] += 1
        self._worker_leases[worker_id] = (
            time.monotonic() + (self.lease_timeout_s or 0.0))
        return known

    # -- dynamic-mode mutations (shared by live handlers and WAL replay) ---

    def _install_dynamic_plan_locked(self, client_id, epoch, owner,
                                     generation):
        self._dyn_dirty = True
        self._dyn[client_id] = {
            "epoch": epoch,
            "owner": dict(owner),
            "done": set(),
            "steals": {},
        }
        self._generation = max(self._generation, generation)

    def _steal_counts_locked(self, state, worker_id):
        return state["steals"].setdefault(worker_id, {"in": 0, "out": 0})

    def _apply_steal_locked(self, client_id, piece, from_wid, to_wid,
                            generation):
        state = self._dyn.get(client_id)
        if state is None:
            return
        self._dyn_dirty = True
        state["owner"][piece] = [to_wid, generation]
        self._generation = max(self._generation, generation)
        self._steal_counts_locked(state, from_wid)["out"] += 1
        self._steal_counts_locked(state, to_wid)["in"] += 1

    def _apply_steal_failed_locked(self, client_id, piece, kept_wid,
                                   generation):
        """A steal the client could not apply (the donor had already sent
        a batch of the piece, or its stream was mid-takeover): ownership
        reverts to where the piece actually stayed."""
        state = self._dyn.get(client_id)
        if state is None:
            return
        self._dyn_dirty = True
        state["owner"][piece] = [kept_wid, generation]
        self._generation = max(self._generation, generation)

    # -- serving -----------------------------------------------------------

    def _serve_connection(self, sock):
        reader = FramedReader(sock, max_frame_bytes=self._max_frame_bytes)
        while not self._server.stopped.is_set():
            header, _ = reader.recv()
            try:
                reply = self._handle(header)
            except Exception as exc:  # reply instead of killing the conn
                logger.exception("dispatcher request %r failed", header)
                reply = {"type": "error", "error": str(exc)}
            # A handler may return (header, payload) when the reply carries
            # non-JSON data (worker_diagnostics aggregates arbitrary
            # Reader.diagnostics values).
            if isinstance(reply, tuple):
                send_framed(sock, reply[0], reply[1])
            else:
                send_framed(sock, reply)

    def _handle(self, header):
        kind = header.get("type")
        handler = getattr(self, f"_handle_{kind}", None)
        if handler is None:
            DISPATCHER_REQUESTS.labels("unknown").inc()
            return {"type": "error", "error": f"unknown request {kind!r}"}
        DISPATCHER_REQUESTS.labels(kind).inc()
        try:
            return handler(header)
        finally:
            # Control-plane rates are a few requests/second at most, so
            # re-deriving the scrapeable gauges (fencing epoch, worker
            # liveness, recovery counters) after every request keeps them
            # exact without littering each mutation site.
            with self._lock:
                self._sync_telemetry_locked()

    def _sync_telemetry_locked(self):
        """Mirror control-plane state into the registry gauges (recovery
        values are journaled and can jump on replay — gauges, not
        counters, are the honest type for them)."""
        DISPATCHER_FENCING_EPOCH.set(self._fencing_epoch)
        alive = sum(1 for w in self._workers.values() if w["alive"])
        DISPATCHER_WORKERS.labels("alive").set(alive)
        DISPATCHER_WORKERS.labels("dead").set(len(self._workers) - alive)
        for event, count in self._recovery.items():
            DISPATCHER_RECOVERY_EVENTS.labels(event).set(count)
        if self.mode == "dynamic":
            DISPATCHER_GENERATION.set(self._generation)
            if not self._dyn_dirty:
                # The aggregation below is O(clients × pieces): skip it
                # unless this request mutated dynamic state — a scrape
                # between mutations reads gauges that are still exact.
                return
            self._dyn_dirty = False
            per_worker = self._dynamic_per_worker_locked()
            for wid in set(self._workers) | set(per_worker):
                entry = per_worker.get(wid)
                DISPATCHER_BACKLOG_PIECES.labels(wid).set(
                    entry["backlog"] if entry else 0)
            for wid, entry in per_worker.items():
                DISPATCHER_STEALS.labels(wid, "in").set(entry["steals_in"])
                DISPATCHER_STEALS.labels(wid, "out").set(
                    entry["steals_out"])

    def _dynamic_per_worker_locked(self):
        """Per-worker backlog/steal aggregation over every client's plan —
        the ONE definition of "backlog" shared by the ``status`` reply and
        the scrapeable gauges (they must never disagree)."""
        per_worker = {}

        def entry(wid):
            return per_worker.setdefault(
                wid, {"backlog": 0, "steals_in": 0, "steals_out": 0})

        for state in self._dyn.values():
            for piece, (wid, _gen) in state["owner"].items():
                e = entry(wid)
                if piece not in state["done"]:
                    e["backlog"] += 1
            for wid, counts in state["steals"].items():
                e = entry(wid)
                e["steals_in"] += counts["in"]
                e["steals_out"] += counts["out"]
        return per_worker

    def _dynamic_status_locked(self):
        """Per-worker steal/backlog aggregation for ``status`` (and the
        ``STEALS`` column of ``status --watch``)."""
        return {
            "generation": self._generation,
            "per_worker": self._dynamic_per_worker_locked(),
            "clients": {
                cid: {"epoch": state["epoch"],
                      "pieces_done": len(state["done"]),
                      "pieces_total": len(state["owner"])}
                for cid, state in self._dyn.items()},
        }

    # -- handlers ----------------------------------------------------------

    def _handle_ping(self, header):
        return {"type": "pong"}

    def _handle_register_worker(self, header):
        worker_id = header["worker_id"]
        num_pieces = int(header["num_pieces"])
        re_register = bool(header.get("re_register"))
        with self._lock:
            if self._num_pieces is not None \
                    and self._num_pieces != num_pieces:
                return {"type": "error", "error": (
                    f"worker {worker_id!r} enumerated {num_pieces} row-group "
                    f"pieces but the service plan has {self._num_pieces} — "
                    f"all workers must read the same dataset with the same "
                    f"planning config")}
            self._install_worker_locked(
                worker_id, [header["host"], int(header["port"])],
                num_pieces, re_register=re_register)
            self._journal_locked({
                "op": "register_worker", "worker_id": worker_id,
                "host": header["host"], "port": int(header["port"]),
                "num_pieces": num_pieces, "re_register": re_register})
            fencing = self._fencing_epoch
        logger.info("worker %sregistered at %s:%s (%d pieces)",
                    "re-" if re_register else "",
                    header["host"], header["port"], num_pieces,
                    worker_id=worker_id, fencing_epoch=fencing)
        return {"type": "ok", "fencing_epoch": fencing}

    def _handle_worker_heartbeat(self, header):
        worker_id = header["worker_id"]
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None or not worker["alive"]:
                # Unknown (restart without a journal) or evicted: the
                # worker re-registers with its old worker_id and rejoins.
                return {"type": "unknown_worker",
                        "fencing_epoch": self._fencing_epoch}
            self._worker_leases[worker_id] = (
                time.monotonic() + (self.lease_timeout_s or 0.0))
            return {"type": "ok", "fencing_epoch": self._fencing_epoch}

    def _handle_client_heartbeat(self, header):
        client_id = header.get("client_id")
        with self._lock:
            known = client_id in self._clients
            self._client_heartbeats[client_id] = time.monotonic()
            if "watermarks" in header:
                # Delivery watermarks ride the heartbeat into the live
                # `status` view on every change, but they are JOURNALED
                # only at piece granularity (epoch moved, or the set of
                # mid-flight pieces changed): ordinals tick per batch, so
                # journaling every change would put a WAL append (plus an
                # fsync under --journal-fsync) on virtually every
                # heartbeat under the global lock — the exact per-tick
                # hot-path cost PR 7's dirty-flag work removed. The
                # journaled view is informational (status after a
                # restart); re-grant `starts` always come from the
                # client's own watermarks, so coarseness costs nothing.
                entry = {
                    "epoch": int(header.get("epoch", 0)),
                    "watermarks": {int(p): int(n) for p, n
                                   in (header.get("watermarks")
                                       or {}).items()},
                }
                prev = self._client_watermarks.get(client_id)
                if prev != entry:
                    self._client_watermarks[client_id] = entry
                    if (prev is None
                            or prev["epoch"] != entry["epoch"]
                            or set(prev["watermarks"])
                            != set(entry["watermarks"])):
                        self._journal_locked({
                            "op": "watermarks", "client_id": client_id,
                            "epoch": entry["epoch"],
                            "watermarks": {str(p): n for p, n
                                           in entry["watermarks"].items()}})
            return {
                "type": "ok",
                "known": known,
                "fencing_epoch": self._fencing_epoch,
                "recovery": dict(self._recovery),
            }

    def _alive_workers(self):
        return {wid: w for wid, w in self._workers.items() if w["alive"]}

    def _handle_list_workers(self, header):
        with self._lock:
            return {
                "type": "workers",
                "workers": {wid: w["address"]
                            for wid, w in self._alive_workers().items()},
                "mode": self.mode,
                "num_epochs": self.num_epochs,
                "num_pieces": self._num_pieces,
                "shuffle_seed": self.shuffle_seed,
                "fencing_epoch": self._fencing_epoch,
            }

    @staticmethod
    def _partition(pieces, worker_ids):
        """Round-robin a piece list across workers; empty shares dropped."""
        assignments = {wid: list(pieces[i::len(worker_ids)])
                       for i, wid in enumerate(worker_ids)}
        return {wid: ps for wid, ps in assignments.items() if ps}

    def _handle_get_assignment(self, header):
        if self.mode != "static":
            return {"type": "error", "error":
                    "get_assignment is a static-mode request; fcfs clients "
                    "use next_split, dynamic clients use dynamic_plan"}
        client_index = int(header["client_index"])
        num_clients = int(header["num_clients"])
        if not 0 <= client_index < num_clients:
            return {"type": "error", "error":
                    f"client_index {client_index} out of range "
                    f"[0, {num_clients})"}
        with self._lock:
            if self._num_pieces is None:
                return {"type": "error",
                        "error": "no workers have registered yet"}
            alive = self._alive_workers()
            if not alive:
                return {"type": "error", "error": "no live workers"}
            # Partition the ASCENDING piece list (epoch-invariant), then
            # order each worker's share by the epoch's seed-tree keys.
            # Sticky piece→worker assignment is what keeps the workers'
            # decoded-batch caches warm across shuffled epochs (epoch 1's
            # fill lives in the worker that serves the piece forever
            # after); per-share canonical ordering keeps an ordered
            # client's reorder buffer shallow — the canonical next piece
            # is always at the head of some live stream's remaining work.
            epoch_number = int(header.get("epoch", 0))
            client_pieces = list(
                range(self._num_pieces))[client_index::num_clients]
            worker_ids = sorted(alive)
            assignments = {
                wid: piece_order(self.shuffle_seed, epoch_number, pieces)
                for wid, pieces in self._partition(client_pieces,
                                                   worker_ids).items()}
            self._clients[header["client_id"]] = {
                "epoch": int(header.get("epoch", 0)),
                "client_index": client_index,
                "num_clients": num_clients,
            }
            self._client_heartbeats[header["client_id"]] = time.monotonic()
            self._journal_locked({
                "op": "client", "client_id": header["client_id"],
                "epoch": int(header.get("epoch", 0)),
                "client_index": client_index, "num_clients": num_clients})
            return {
                "type": "assignment",
                "epoch": int(header.get("epoch", 0)),
                "fencing_epoch": self._fencing_epoch,
                "assignments": assignments,
                "workers": {wid: alive[wid]["address"]
                            for wid in assignments},
            }

    def _handle_report_failure(self, header):
        worker_id = header["worker_id"]
        pieces = [int(p) for p in header.get("pieces", [])]
        token = header.get("fencing_epoch")
        with self._lock:
            if token is not None and int(token) < self._fencing_epoch:
                # The client is acting on a plan the fencing epoch has
                # since invalidated (dispatcher restart, eviction it has
                # not seen): make it resync before any takeover — acting
                # on the stale report could evict a worker that already
                # re-registered, or re-partition splits the client no
                # longer owns.
                self._recovery["stale_fencing_rejections"] += 1
                logger.warning(
                    "rejecting stale report_failure (token %s)", token,
                    client_id=header.get("client_id"),
                    fencing_epoch=self._fencing_epoch)
                return {"type": "stale_fencing",
                        "fencing_epoch": self._fencing_epoch}
            if self._mark_worker_dead_locked(worker_id, "reported"):
                self._journal_locked({"op": "worker_dead",
                                      "worker_id": worker_id,
                                      "reason": "reported"})
                self._bump_fencing_locked("report_failure")
            alive = self._alive_workers()
            if not alive:
                return {"type": "error", "error": (
                    f"worker {worker_id!r} reported dead and no live workers "
                    f"remain — the service cannot make progress")}
            worker_ids = sorted(alive)
            assignments = self._partition(pieces, worker_ids)
            logger.warning(
                "worker reported failed; reassigning %d pieces across %d "
                "survivors", len(pieces), len(worker_ids),
                worker_id=worker_id, client_id=header.get("client_id"),
                fencing_epoch=self._fencing_epoch)
            if self.mode == "dynamic":
                # Takeover reassignments are steals from the dead worker:
                # journaled, generation-stamped, so a replayed dispatcher
                # and the client's dedup agree on who serves what.
                client_id = header.get("client_id")
                pairs = {}
                for wid, ws_pieces in assignments.items():
                    pairs[wid] = []
                    for piece in ws_pieces:
                        self._generation += 1
                        self._apply_steal_locked(client_id, piece,
                                                 worker_id, wid,
                                                 self._generation)
                        self._journal_locked({
                            "op": "steal", "client_id": client_id,
                            "piece": piece, "from": worker_id, "to": wid,
                            "generation": self._generation})
                        pairs[wid].append([piece, self._generation])
                return {
                    "type": "assignment",
                    "fencing_epoch": self._fencing_epoch,
                    "generation": self._generation,
                    "assignments": pairs,
                    "workers": {wid: alive[wid]["address"]
                                for wid in pairs},
                }
            return {
                "type": "assignment",
                "fencing_epoch": self._fencing_epoch,
                "assignments": assignments,
                "workers": {wid: alive[wid]["address"]
                            for wid in assignments},
            }

    def _handle_next_split(self, header):
        if self.mode != "fcfs":
            return {"type": "error", "error":
                    "next_split is an fcfs-mode request; static clients use "
                    "get_assignment"}
        with self._lock:
            if self._num_pieces is None:
                return {"type": "error",
                        "error": "no workers have registered yet"}
            if self._fcfs_queue is None:
                self._fcfs_queue = deque(range(self._num_pieces))
            if not self._fcfs_queue:
                # Epoch boundary: refill while epochs remain (None = forever).
                if self.num_epochs is not None \
                        and self._fcfs_epoch + 1 >= self.num_epochs:
                    return {"type": "end_of_stream",
                            "epochs_completed": self._fcfs_epoch + 1}
                self._fcfs_epoch += 1
                self._fcfs_queue.extend(range(self._num_pieces))
            piece = self._fcfs_queue.popleft()
            self._journal_locked({"op": "next_split", "piece": piece,
                                  "epoch": self._fcfs_epoch})
            return {"type": "split", "piece": piece,
                    "epoch": self._fcfs_epoch}

    # -- dynamic mode ------------------------------------------------------

    def _handle_dynamic_plan(self, header):
        """Initial per-worker piece deques for one client epoch: the
        client's static shard round-robined across live workers, every
        piece stamped with a fresh ownership generation. Requesting a plan
        for a new epoch replaces the client's previous epoch state."""
        if self.mode != "dynamic":
            return {"type": "error", "error":
                    "dynamic_plan is a dynamic-mode request"}
        client_index = int(header["client_index"])
        num_clients = int(header["num_clients"])
        epoch = int(header.get("epoch", 0))
        if not 0 <= client_index < num_clients:
            return {"type": "error", "error":
                    f"client_index {client_index} out of range "
                    f"[0, {num_clients})"}
        client_id = header["client_id"]
        with self._lock:
            if self._num_pieces is None:
                return {"type": "error",
                        "error": "no workers have registered yet"}
            alive = self._alive_workers()
            if not alive:
                return {"type": "error", "error": "no live workers"}
            # Sticky initial deques + per-deque canonical order, like the
            # static path: cache warmth survives shuffled epochs (steals
            # may still move pieces — the shared disk tier covers those).
            client_pieces = list(
                range(self._num_pieces))[client_index::num_clients]
            worker_ids = sorted(alive)
            assignments = {
                wid: piece_order(self.shuffle_seed, epoch, pieces)
                for wid, pieces in self._partition(client_pieces,
                                                   worker_ids).items()}
            self._generation += 1
            generation = self._generation
            owner = {piece: [wid, generation]
                     for wid, pieces in assignments.items()
                     for piece in pieces}
            self._install_dynamic_plan_locked(client_id, epoch, owner,
                                              generation)
            self._clients[client_id] = {
                "epoch": epoch,
                "client_index": client_index,
                "num_clients": num_clients,
            }
            self._client_heartbeats[client_id] = time.monotonic()
            self._journal_locked({
                "op": "client", "client_id": client_id, "epoch": epoch,
                "client_index": client_index, "num_clients": num_clients})
            self._journal_locked({
                "op": "dynamic_plan", "client_id": client_id,
                "epoch": epoch,
                "owner": [[piece, wid, gen] for piece, (wid, gen)
                          in sorted(owner.items())],
                "generation": generation})
            return {
                "type": "plan",
                "epoch": epoch,
                "generation": generation,
                "fencing_epoch": self._fencing_epoch,
                "assignments": {
                    wid: [[piece, generation] for piece in pieces]
                    for wid, pieces in assignments.items()},
                "workers": {wid: alive[wid]["address"]
                            for wid in assignments},
            }

    def _handle_dynamic_sync(self, header):
        """The rebalance loop's heartbeat: fold the client's progress
        report into the ownership state, reconcile any divergence (a steal
        journaled pre-crash that the client never saw comes back as a
        corrective delta), and plan fresh steals away from drained or
        straggling workers. Idempotent by construction — the client
        reports absolute state (full done set, full ownership view), so a
        lost reply or a replayed request converges instead of corrupting.
        """
        if self.mode != "dynamic":
            return {"type": "error", "error":
                    "dynamic_sync is a dynamic-mode request"}
        client_id = header["client_id"]
        epoch = int(header.get("epoch", 0))
        done = set(int(p) for p in header.get("done", []))
        owned = {wid: set(int(p) for p in pieces)
                 for wid, pieces in (header.get("owned") or {}).items()}
        stealable = {wid: [int(p) for p in pieces]
                     for wid, pieces in
                     (header.get("stealable") or {}).items()}
        rates = {wid: float(r)
                 for wid, r in (header.get("rates") or {}).items()}
        failed = [(int(p), wid, int(gen), int(failed_gen))
                  for p, wid, gen, failed_gen
                  in header.get("failed_steals", [])]
        with self._lock:
            state = self._dyn.get(client_id)
            if state is None or state["epoch"] != epoch:
                # Restarted without a journal (or a plan this dispatcher
                # never saw): the client must re-plan — its streams keep
                # flowing meanwhile, exactly like static's resync path.
                return {"type": "unknown_plan",
                        "fencing_epoch": self._fencing_epoch}
            for piece, kept_wid, kept_gen, failed_gen in failed:
                # The revert is valid only against the exact assignment
                # the failed steal created: a report can be retried across
                # a sync failure and land AFTER a takeover or re-plan
                # stamped the piece with a newer generation — applying it
                # then would clobber the newer (journaled) owner and pin
                # the piece on a dead worker for the rest of the epoch.
                cur = state["owner"].get(piece)
                if cur is None or int(cur[1]) != failed_gen:
                    continue  # stale report: a newer grant superseded it
                self._apply_steal_failed_locked(client_id, piece, kept_wid,
                                                kept_gen)
                self._journal_locked({
                    "op": "steal_failed", "client_id": client_id,
                    "piece": piece, "worker_id": kept_wid,
                    "generation": kept_gen})
            fresh_done = done - state["done"]
            if fresh_done:
                self._dyn_dirty = True
                state["done"].update(fresh_done)
                self._journal_locked({
                    "op": "dynamic_done", "client_id": client_id,
                    "pieces": sorted(fresh_done)})
            alive = self._alive_workers()
            # Reconcile: a piece the dispatcher's (journal-restored) state
            # places on a different worker than the client's live view is
            # re-issued as a corrective steal — the client applies it
            # through the same revoke-then-extend handshake, so exactly-
            # once holds across a dispatcher crash mid-steal.
            client_owner = {piece: wid for wid, pieces in owned.items()
                            for piece in pieces}
            deltas = []
            for piece, (wid, gen) in sorted(state["owner"].items()):
                if piece in state["done"] or wid not in alive:
                    continue
                seen = client_owner.get(piece)
                if seen is not None and seen != wid:
                    deltas.append({"piece": piece, "from": seen,
                                   "to": wid, "generation": gen})
            # Plan fresh steals over ALL live workers — not just those the
            # client reported grants on: a worker that registered
            # mid-epoch has no stream yet (owned is empty for it) but is
            # exactly the drained receiver work-stealing exists to feed;
            # its address ships in the reply so the client can open one.
            pending = {wid: 0 for wid in alive}
            for piece, (wid, gen) in state["owner"].items():
                if piece not in state["done"] and wid in pending:
                    pending[wid] += 1
            moves = plan_steals(pending, {
                wid: [p for p in pieces
                      if p not in state["done"]
                      and state["owner"].get(p, (None,))[0] == wid]
                for wid, pieces in stealable.items() if wid in pending},
                rates)
            for piece, from_wid, to_wid in moves:
                self._generation += 1
                self._apply_steal_locked(client_id, piece, from_wid,
                                         to_wid, self._generation)
                self._journal_locked({
                    "op": "steal", "client_id": client_id, "piece": piece,
                    "from": from_wid, "to": to_wid,
                    "generation": self._generation})
                deltas.append({"piece": piece, "from": from_wid,
                               "to": to_wid,
                               "generation": self._generation})
            if moves:
                logger.info(
                    "work stealing: moved %d piece(s) (%s)", len(moves),
                    "; ".join(f"{p}:{f}->{t}" for p, f, t in moves[:8]),
                    client_id=client_id,
                    fencing_epoch=self._fencing_epoch)
            referenced = ({d["to"] for d in deltas}
                          | {d["from"] for d in deltas})
            return {
                "type": "deltas",
                "steals": deltas,
                "generation": self._generation,
                "fencing_epoch": self._fencing_epoch,
                # Steal targets may be workers the client has no stream to
                # yet (a worker that joined mid-epoch): ship addresses so
                # the grant can open one.
                "workers": {wid: alive[wid]["address"]
                            for wid in referenced if wid in alive},
            }

    def _handle_worker_diagnostics(self, header):
        """Diagnostics passthrough: fan the ``diagnostics`` request out to
        every live worker CONCURRENTLY and aggregate — no sample bytes, a
        few small framed messages, and the aggregate's latency is one
        worker round trip (max, not sum — a fleet with dead workers must
        not cost ``timeout`` each, serially). An unreachable worker is
        reported in place rather than failing the aggregate."""
        from concurrent.futures import ThreadPoolExecutor

        from petastorm_tpu.reader_impl.framed_socket import FramedConnection

        timeout = self._probe_timeout(header)
        with self._lock:
            workers = {wid: tuple(w["address"])
                       for wid, w in self._alive_workers().items()}

        def probe(address):
            try:
                with FramedConnection.connect(address,
                                              timeout=timeout) as conn:
                    _, payload = conn.request({"type": "diagnostics"})
                return payload
            except (ConnectionError, OSError) as exc:
                return {"error": f"unreachable: {exc}"}

        out = {}
        if workers:
            with ThreadPoolExecutor(
                    max_workers=min(16, len(workers))) as pool:
                for wid, payload in zip(
                        workers, pool.map(probe, workers.values())):
                    out[wid] = payload
        return {"type": "diagnostics", "workers": sorted(workers)}, out

    @staticmethod
    def _probe_timeout(header):
        """Clamp the client-supplied per-probe timeout to a sane range: a
        misbehaving client must not pin probe threads for minutes."""
        try:
            timeout = float(header.get("timeout", 5.0))
        except (TypeError, ValueError):
            return 5.0
        return min(max(timeout, 0.1), PROBE_TIMEOUT_CAP_S)

    def _handle_status(self, header):
        now = time.monotonic()
        with self._lock:
            return {
                "type": "status",
                "mode": self.mode,
                "num_epochs": self.num_epochs,
                "num_pieces": self._num_pieces,
                "shuffle_seed": self.shuffle_seed,
                "fencing_epoch": self._fencing_epoch,
                "client_watermarks": {
                    cid: {"epoch": entry["epoch"],
                          "watermarks": {str(p): n for p, n
                                         in entry["watermarks"].items()}}
                    for cid, entry in self._client_watermarks.items()},
                "recovery": dict(self._recovery),
                "journal": (self._journal.stats
                            if self._journal is not None else None),
                "workers": {
                    wid: {"address": w["address"],
                          "alive": w["alive"],
                          "lease_expires_in_s": (
                              round(self._worker_leases[wid] - now, 3)
                              if wid in self._worker_leases else None)}
                    for wid, w in self._workers.items()},
                "clients": {cid: dict(c) for cid, c in self._clients.items()},
                "fcfs_epoch": self._fcfs_epoch,
                "fcfs_remaining": (len(self._fcfs_queue)
                                   if self._fcfs_queue is not None else None),
                "dynamic": (self._dynamic_status_locked()
                            if self.mode == "dynamic" else None),
            }
