"""DLRM tabular model: forward/step correctness and sharded execution over
the 8-device virtual CPU mesh, plus end-to-end Parquet → batch reader →
loader → sharded train step (BASELINE.md config #3's model consumer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.tabular_dlrm import (
    apply_dlrm,
    dlrm_partition_specs,
    init_dlrm_params,
    make_dlrm_train_step,
)

NUM_DENSE, NUM_SPARSE = 4, 8


def _params():
    return init_dlrm_params(jax.random.PRNGKey(0), NUM_DENSE, NUM_SPARSE,
                            vocab_size=32, embed_dim=8)


def _batch(rows=16, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(rows, NUM_DENSE).astype(np.float32),
            rng.randint(0, 10_000, (rows, NUM_SPARSE)).astype(np.int64),
            rng.randint(0, 2, rows).astype(np.int32),
            np.ones(rows, bool))


def test_forward_shapes_and_dtype():
    dense, sparse, _, _ = _batch()
    logits = apply_dlrm(_params(), jnp.asarray(dense), jnp.asarray(sparse))
    assert logits.shape == (16,)
    assert logits.dtype == jnp.float32


def test_train_step_reduces_loss():
    params = _params()
    step = jax.jit(make_dlrm_train_step(0.1))
    dense, sparse, labels, mask = (jnp.asarray(a) for a in _batch())
    losses = []
    for _ in range(10):
        params, loss = step(params, dense, sparse, labels, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pad_mask_zeroes_gradient():
    params = _params()
    step = make_dlrm_train_step(0.1)
    dense, sparse, labels, _ = (jnp.asarray(a) for a in _batch())
    none_masked = jnp.zeros(16, bool)
    new_params, _ = step(params, dense, sparse, labels, none_masked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        params, new_params)


def test_sharded_step_matches_unsharded():
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "model"))
    params = _params()
    specs = dlrm_partition_specs()
    sharded_params = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
    dense, sparse, labels, mask = _batch()
    batch_shard = NamedSharding(mesh, P("data"))
    args = (jax.device_put(dense, batch_shard),
            jax.device_put(sparse, batch_shard),
            jax.device_put(labels, batch_shard),
            jax.device_put(mask, batch_shard))

    step = make_dlrm_train_step(0.1)
    ref_params, ref_loss = step(params, *(jnp.asarray(a)
                                          for a in (dense, sparse, labels,
                                                    mask)))
    out_shardings = (jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs), NamedSharding(mesh, P()))
    sharded_step = jax.jit(step, out_shardings=out_shardings)
    got_params, got_loss = sharded_step(sharded_params, *args)
    assert np.isclose(float(got_loss), float(ref_loss), rtol=1e-3)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3),
        ref_params, got_params)


def test_end_to_end_from_parquet(tmp_path):
    """Criteo-shaped Parquet → make_batch_reader → loader → sharded step."""
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.benchmark.scenarios import make_tabular_dataset
    from petastorm_tpu.jax_utils import make_jax_dataloader

    url = f"file://{tmp_path}/criteo"
    make_tabular_dataset(url, rows=512, dense_cols=NUM_DENSE,
                         sparse_cols=NUM_SPARSE, days=4)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8, 1), ("data", "model"))
    params = _params()
    step = jax.jit(make_dlrm_train_step(0.05))

    reader = make_batch_reader(url, num_epochs=1, shuffle_row_groups=False)
    with make_jax_dataloader(reader, batch_size=64, last_batch="drop",
                             sharding=NamedSharding(mesh, P("data"))) as loader:
        steps = 0
        for batch in loader:
            dense = jnp.stack([batch[f"dense_{i}"]
                               for i in range(NUM_DENSE)], axis=1)
            sparse = jnp.stack([batch[f"cat_{i}"]
                                for i in range(NUM_SPARSE)], axis=1)
            mask = jnp.ones(dense.shape[0], bool)
            params, loss = step(params, dense, sparse, batch["label"], mask)
            steps += 1
        assert steps == 512 // 64
        assert np.isfinite(float(loss))
