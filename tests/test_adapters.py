"""TF + Torch adapter tests, mostly off ReaderMock (no Parquet), plus
end-to-end reads of the conftest datasets.

Reference analogue: ``petastorm/tests/{test_tf_utils,test_pytorch_dataloader}``
— SURVEY.md §4 ("ReaderMock lets adapter tests run without Parquet").
"""

from decimal import Decimal

import numpy as np
import pytest

from petastorm_tpu.schema.codecs import ScalarCodec
from petastorm_tpu.schema.unischema import Unischema, UnischemaField
from petastorm_tpu.test_util.reader_mock import ReaderMock

AdapterSchema = Unischema("AdapterSchema", [
    UnischemaField("id", np.int64, (), ScalarCodec(), False),
    UnischemaField("mat", np.float32, (2, 3), None, False),
    UnischemaField("counts", np.uint16, (4,), None, False),
    UnischemaField("name", str, (), ScalarCodec(), False),
    UnischemaField("price", Decimal, (), ScalarCodec(), False),
])


def _row(i):
    return {"id": np.int64(i),
            "mat": np.full((2, 3), i, dtype=np.float32),
            "counts": np.full(4, i, dtype=np.uint16),
            "name": f"row_{i}",
            "price": Decimal(f"{i}.5")}


def _mock(rows=10):
    return ReaderMock(AdapterSchema, _row, num_rows=rows)


# ---------------- TF ------------------------------------------------------

def test_tf_dtype_promotions():
    import tensorflow as tf

    from petastorm_tpu.tf_utils import _schema_to_tf_dtypes

    dtypes = _schema_to_tf_dtypes(AdapterSchema)
    assert dtypes["id"] == tf.int64
    assert dtypes["mat"] == tf.float32
    assert dtypes["counts"] == tf.int32      # uint16 promotes
    assert dtypes["name"] == tf.string
    assert dtypes["price"] == tf.string      # Decimal → string


def test_make_petastorm_dataset_rows():
    from petastorm_tpu.tf_utils import make_petastorm_dataset

    dataset = make_petastorm_dataset(_mock(6))
    rows = list(dataset)
    assert len(rows) == 6
    first = rows[0]
    assert first.mat.shape == (2, 3)
    assert first.counts.dtype.name == "int32"
    assert first.price.numpy().decode() == "0.5"
    assert first.name.numpy().decode() == "row_0"
    ids = sorted(int(r.id.numpy()) for r in rows)
    assert ids == list(range(6))


def test_make_petastorm_dataset_batches_then_rebatch():
    import tensorflow as tf

    from petastorm_tpu.tf_utils import make_petastorm_dataset

    dataset = make_petastorm_dataset(_mock(9)).batch(3)
    batches = list(dataset)
    assert len(batches) == 3
    assert batches[0].mat.shape == (3, 2, 3)
    assert isinstance(batches[0], tuple)
    total = tf.concat([b.id for b in batches], axis=0)
    assert sorted(total.numpy().tolist()) == list(range(9))


def test_tf_tensors_shuffling():
    from petastorm_tpu.tf_utils import tf_tensors

    it = tf_tensors(_mock(20), shuffling_queue_capacity=10)
    ids = [int(row.id.numpy()) for row in it]
    assert sorted(ids) == list(range(20))


def test_tf_dataset_end_to_end(petastorm_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.tf_utils import make_petastorm_dataset

    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         schema_fields=["id", "matrix"], num_epochs=1,
                         shuffle_row_groups=False)
    with reader:
        rows = list(make_petastorm_dataset(reader))
    assert len(rows) == 30
    assert rows[0].matrix.shape == (4, 8)


def test_tf_dataset_over_columnar_reader(petastorm_dataset):
    """The TPU fast-path reader feeds the TF adapter too (batched elements)."""
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.tf_utils import make_petastorm_dataset

    reader = make_columnar_reader(petastorm_dataset.url,
                                  reader_pool_type="dummy",
                                  schema_fields=["id", "matrix"],
                                  num_epochs=1, shuffle_row_groups=False)
    with reader:
        total = 0
        for batch in make_petastorm_dataset(reader):
            total += int(batch.id.shape[0])
            assert batch.matrix.shape[1:] == (4, 8)
    assert total == 30


def test_batched_dataloader_over_columnar_reader(petastorm_dataset):
    """The TPU fast-path reader feeds the torch BatchedDataLoader too."""
    import torch

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.pytorch import BatchedDataLoader

    reader = make_columnar_reader(petastorm_dataset.url,
                                  reader_pool_type="dummy",
                                  schema_fields=["id", "matrix"],
                                  num_epochs=1, shuffle_row_groups=False)
    with BatchedDataLoader(reader, batch_size=8) as loader:
        ids = []
        for batch in loader:
            assert torch.is_tensor(batch["matrix"])
            ids.extend(int(v) for v in batch["id"])
    # 30 rows -> 3 full batches of 8 plus the trailing partial batch of 6
    assert sorted(ids) == list(range(30))


def test_tf_dataset_ngram(petastorm_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.ngram import NGram

    ngram = NGram({0: ["^id$", "^matrix$"], 1: ["^id$"]},
                  delta_threshold=10, timestamp_field="timestamp_s")
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         schema_fields=ngram, num_epochs=1,
                         shuffle_row_groups=False)
    from petastorm_tpu.tf_utils import make_petastorm_dataset

    with reader:
        windows = list(make_petastorm_dataset(reader))
    assert windows, "expected at least one ngram window"
    w = windows[0]
    assert set(w.keys()) == {0, 1}
    # per-offset steps are namedtuples (reference structure)
    assert int(w[1].id.numpy()) == int(w[0].id.numpy()) + 1
    assert w[0].matrix.shape == (4, 8)


# ---------------- Torch ---------------------------------------------------

def test_sanitize_pytorch_types_promotions():
    from petastorm_tpu.pytorch import _sanitize_pytorch_types

    row = {"a": np.uint16(3), "b": np.arange(4, dtype=np.uint32),
           "c": np.float32(1.5), "d": "s"}
    out = _sanitize_pytorch_types(row)
    assert out["a"].dtype == np.int32
    assert out["b"].dtype == np.int64
    assert out["c"].dtype == np.float32
    assert out["d"] == "s"


def test_decimal_friendly_collate_structures():
    import torch

    from petastorm_tpu.pytorch import decimal_friendly_collate

    batch = [{"x": np.float32(1.0), "d": Decimal("1.5"), "s": "a"},
             {"x": np.float32(2.0), "d": Decimal("2.5"), "s": "b"}]
    out = decimal_friendly_collate(batch)
    assert torch.is_tensor(out["x"]) and out["x"].shape == (2,)
    assert out["d"] == ["1.5", "2.5"]
    assert out["s"] == ["a", "b"]


def test_torch_dataloader_rows():
    import torch

    from petastorm_tpu.pytorch import DataLoader

    with DataLoader(_mock(10), batch_size=4) as loader:
        batches = list(loader)
    assert len(batches) == 3  # 4+4+2
    assert torch.is_tensor(batches[0]["mat"])
    assert batches[0]["mat"].shape == (4, 2, 3)
    assert batches[0]["counts"].dtype == torch.int32
    assert batches[0]["price"] == ["0.5", "1.5", "2.5", "3.5"]
    ids = [int(v) for b in batches for v in b["id"]]
    assert sorted(ids) == list(range(10))


def test_torch_dataloader_shuffling_exactly_once():
    from petastorm_tpu.pytorch import DataLoader

    with DataLoader(_mock(40), batch_size=8,
                    shuffling_queue_capacity=16,
                    shuffling_queue_seed=1) as loader:
        ids = [int(v) for b in loader for v in b["id"]]
    assert sorted(ids) == list(range(40))
    assert ids != list(range(40))


def test_torch_dataloader_rejects_batch_reader():
    from petastorm_tpu.pytorch import BatchedDataLoader, DataLoader

    batch_mock = ReaderMock(AdapterSchema, _row, num_rows=4,
                            batched_output=True)
    with pytest.raises(ValueError, match="row reader"):
        DataLoader(batch_mock)
    with pytest.raises(ValueError, match="batch reader"):
        BatchedDataLoader(_mock(4))


def test_batched_dataloader_end_to_end(scalar_dataset):
    import torch

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.pytorch import BatchedDataLoader
    from petastorm_tpu.schema.transform import TransformSpec

    # string_col can't be a tensor; drop it worker-side
    spec = TransformSpec(removed_fields=["string_col"])
    reader = make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                               num_epochs=1, shuffle_row_groups=False,
                               transform_spec=spec)
    with BatchedDataLoader(reader, batch_size=7) as loader:
        batches = list(loader)
    assert all(torch.is_tensor(b["id"]) for b in batches)
    ids = [int(v) for b in batches for v in b["id"]]
    assert sorted(ids) == list(range(30))
    assert batches[0]["id"].shape == (7,)


def test_batched_dataloader_yields_from_infinite_reader(scalar_dataset):
    # Regression: the drain loop used to wait for buffer.can_add() to go
    # False, which never happens for the noop buffer — with num_epochs=None
    # the loader accumulated forever and never yielded a batch.
    import threading

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.pytorch import BatchedDataLoader
    from petastorm_tpu.schema.transform import TransformSpec

    spec = TransformSpec(removed_fields=["string_col"])
    reader = make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                               num_epochs=None, shuffle_row_groups=False,
                               transform_spec=spec)
    got = []

    def grab():
        with BatchedDataLoader(reader, batch_size=7) as loader:
            it = iter(loader)
            for _ in range(5):
                got.append(next(it))

    t = threading.Thread(target=grab, daemon=True)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive(), "BatchedDataLoader hung on an infinite reader"
    assert len(got) == 5 and all(b["id"].shape == (7,) for b in got)


def test_batched_dataloader_shuffled(scalar_dataset):
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.pytorch import BatchedDataLoader
    from petastorm_tpu.schema.transform import TransformSpec

    spec = TransformSpec(removed_fields=["string_col"])
    reader = make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                               num_epochs=1, shuffle_row_groups=False,
                               transform_spec=spec)
    with BatchedDataLoader(reader, batch_size=6, shuffling_queue_capacity=12,
                           shuffling_queue_seed=3) as loader:
        ids = [int(v) for b in loader for v in b["id"]]
    assert sorted(ids) == list(range(30))
    assert ids != list(range(30))


def test_inmem_batched_dataloader_multi_epoch():
    from petastorm_tpu.pytorch import InMemBatchedDataLoader

    loader = InMemBatchedDataLoader(_mock(8), batch_size=4, num_epochs=3,
                                    shuffle=True, random_seed=0)
    # strings/Decimals can't go in the tensor cache — use numeric-only mock
    NumSchema = Unischema("NumSchema", [
        UnischemaField("id", np.int64, (), None, False),
        UnischemaField("vec", np.float32, (2,), None, False),
    ])
    loader = InMemBatchedDataLoader(
        ReaderMock(NumSchema,
                   lambda i: {"id": np.int64(i),
                              "vec": np.full(2, i, np.float32)},
                   num_rows=8),
        batch_size=4, num_epochs=3, shuffle=True, random_seed=0)
    with loader:
        batches = list(loader)
    assert len(batches) == 6  # 2 per epoch x 3 epochs
    per_epoch = [sorted(int(v) for b in batches[i:i + 2] for v in b["id"])
                 for i in range(0, 6, 2)]
    assert all(e == list(range(8)) for e in per_epoch)


def test_batched_random_shuffling_buffer_vectorized():
    import torch

    from petastorm_tpu.reader_impl.pytorch_shuffling_buffer import (
        BatchedRandomShufflingBuffer,
    )

    buf = BatchedRandomShufflingBuffer(20, min_after_retrieve=5,
                                       batch_size=4, random_seed=0)
    buf.add_many({"x": torch.arange(30)})
    seen = []
    while buf.can_retrieve():
        seen.extend(buf.retrieve()["x"].tolist())
    buf.finish()
    while buf.can_retrieve():
        seen.extend(buf.retrieve()["x"].tolist())
    assert sorted(seen) == list(range(30))
    assert seen != list(range(30))
