"""Row decode loop + small shared helpers.

Reference parity: ``petastorm/utils.py`` (``decode_row``, ``DecodeFieldError``;
``add_to_dataset_metadata`` lives in ``petastorm_tpu/etl/metadata.py`` because
the metadata engine here is pyarrow-native).
"""

from __future__ import annotations

import numpy as np


class DecodeFieldError(RuntimeError):
    pass


def decode_table(table, schema):
    """Columnar decode of a whole ``pa.Table`` into a list of row dicts.

    Same result as ``[decode_row(r, schema) for r in table.to_pylist()]`` but
    decodes column-at-a-time: numeric scalar columns convert through one
    ``to_numpy`` call (C loop) instead of per-cell ``np.dtype(...).type(v)``,
    and only one dict per row is built. This is the no-predicate hot path of
    ``PyDictReaderWorker`` (reference hot-loop analysis: SURVEY.md §3.2).
    """
    names, cols = [], []
    for name in table.column_names:
        field = schema.fields.get(name)
        if field is None:
            continue
        names.append(name)
        cols.append(_decode_column(table.column(name), field))
    if not names:
        return []
    return [dict(zip(names, vals)) for vals in zip(*cols)]


def _decode_column(col, field):
    from petastorm_tpu.schema.codecs import ScalarCodec

    try:
        if field.codec is not None:
            if isinstance(field.codec, ScalarCodec):
                fast = _fast_numeric_column(col, field)
                if fast is not None:
                    return fast
            decode = field.codec.decode
            return [None if v is None else decode(field, v)
                    for v in col.to_pylist()]
        if field.shape:
            dtype = np.dtype(field.numpy_dtype)
            return [None if v is None else np.asarray(v, dtype=dtype)
                    for v in col.to_pylist()]
        fast = _fast_numeric_column(col, field)
        if fast is not None:
            return fast
        codec = ScalarCodec()
        return [None if v is None else codec.decode(field, v)
                for v in col.to_pylist()]
    except Exception as exc:
        raise DecodeFieldError(
            f"Decoding field {field.name!r} failed: {exc}") from exc


def _fast_numeric_column(col, field):
    """Whole-column numeric conversion; None when the dtype needs the
    per-cell path (strings, Decimal, datetime, nulls present)."""
    try:
        dtype = np.dtype(field.numpy_dtype)  # Decimal etc. raise TypeError
    except TypeError:
        return None
    if dtype.kind not in "biuf" or col.null_count:
        return None
    arr = col.to_numpy(zero_copy_only=False).astype(dtype, copy=False)
    return list(arr)


def decode_row(row, schema):
    """Decode all fields of one storage-row dict into numpy-land values.

    Reference parity: ``petastorm/utils.py::decode_row``. Fields with a codec
    are decoded by it; codec-less tensor fields (plain-Parquet list columns)
    are converted to ndarrays; scalars pass through with dtype normalization.
    """
    decoded_row = {}
    for field_name, value in row.items():
        field = schema.fields.get(field_name)
        if field is None:
            continue
        try:
            if value is None:
                decoded_row[field_name] = None
            elif field.codec is not None:
                decoded_row[field_name] = field.codec.decode(field, value)
            elif field.shape:
                decoded_row[field_name] = np.asarray(
                    value, dtype=np.dtype(field.numpy_dtype)
                )
            else:
                from petastorm_tpu.schema.codecs import ScalarCodec

                decoded_row[field_name] = ScalarCodec().decode(field, value)
        except Exception as exc:
            raise DecodeFieldError(
                f"Decoding field {field_name!r} failed: {exc}"
            ) from exc
    return decoded_row


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a fresh child process and return its
    result.

    Reference parity: ``petastorm/utils.py::run_in_subprocess`` — used to
    isolate code that must not pollute the parent (e.g. libhdfs forks, CUDA
    context in the reference's world; on a TPU host, anything that would
    initialize a second JAX runtime). ``func`` must be picklable
    (module-level).
    """
    import multiprocessing

    ctx = multiprocessing.get_context("spawn")
    with ctx.Pool(1) as pool:
        return pool.apply(func, args, kwargs)
