"""NGram window training — BASELINE.md config #4 end-to-end.

Timestamped frames (video/lidar stand-in) → ``NGram`` windows through
``make_reader`` → ``make_jax_dataloader`` collates to ``[B, T, ...]`` →
the sequence encoder trains on them (dense or Pallas-flash attention on one
device; pass a mesh for ring/Ulysses sequence parallelism).

Run: ``python -m examples.sequence.train_sequence``.
"""

from __future__ import annotations

import numpy as np

WINDOW = 5


def generate_frames_dataset(dataset_url, frames=1024):
    """Write the timestamped-frame dataset (NdarrayCodec frames)."""
    from petastorm_tpu.benchmark.scenarios import make_ngram_dataset

    return make_ngram_dataset(dataset_url, frames=frames,
                              frame_shape=(8, 8, 1))


def train_sequence(dataset_url, batch_size=16, steps=8, attn_impl="dense"):
    """Train the encoder on NGram windows; returns the final loss."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)
    from petastorm_tpu.ngram import NGram

    ngram = NGram({i: ["ts", "frame", "ego_speed"] for i in range(WINDOW)},
                  delta_threshold=1, timestamp_field="ts")
    reader = make_reader(dataset_url, schema_fields=ngram, num_epochs=None,
                         shuffle_row_groups=True, shard_seed=0)

    feature_dim = 8 * 8 * 1 + 1  # flattened frame + ego_speed per timestep
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=feature_dim,
                             d_model=32, num_heads=4, num_classes=4)
    step = jax.jit(make_seq_train_step(0.05, num_heads=4,
                                       attn_impl=attn_impl))

    loss = float("nan")
    with make_jax_dataloader(reader, batch_size, max_batches=steps,
                             stage_to_device=False) as loader:
        for batch in loader:
            # [B, T, 8, 8, 1] frames + [B, T] speed -> [B, T, F] features
            frames = jnp.asarray(batch["frame"])
            speed = jnp.asarray(batch["ego_speed"])
            b, t = frames.shape[:2]
            windows = jnp.concatenate(
                [frames.reshape(b, t, -1), speed[..., None]], axis=-1)
            # Synthetic label: the window's mean speed quartile.
            labels = jnp.clip((speed.mean(axis=1) * 4).astype(jnp.int32),
                              0, 3)
            mask = jnp.ones(b, bool)
            params, loss = step(params, windows, labels, mask)
    return float(loss)


def generate_ragged_dataset(dataset_url, rows=256, max_len=24):
    """Variable-length sequences stored PADDED with a ``length`` column —
    the standard ragged-sequence layout (shapes in Parquet must be static;
    the true length rides along as data)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("RaggedSeq", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("seq", np.float32, (max_len, 6), NdarrayCodec(),
                       False),
        UnischemaField("length", np.int32, (), ScalarCodec(), False),
        UnischemaField("label", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(7)

    def rows_gen():
        for i in range(rows):
            n = int(rng.randint(4, max_len + 1))
            seq = np.zeros((max_len, 6), np.float32)
            seq[:n] = rng.randn(n, 6)
            yield {"id": i, "seq": seq, "length": np.int32(n),
                   "label": np.int32(i % 3)}

    materialize_rows(dataset_url, schema, rows_gen(), rows_per_row_group=64)
    return dataset_url


def train_ragged_causal(dataset_url, batch_size=16, steps=8, mesh=None,
                        attn_impl=None):
    """Decoder-style (causal) training on ragged sequences: the ``length``
    column flows into the model so padded positions neither attend nor pool.
    ``attn_impl`` defaults to the Pallas flash kernel single-device and to
    the K/V-ppermute ring when a ``mesh`` is given (sequence parallelism
    over long windows)."""
    if attn_impl is None:
        attn_impl = "ring" if mesh is not None else "flash"
    import jax
    import jax.numpy as jnp

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)

    reader = make_columnar_reader(dataset_url, num_epochs=None,
                                  shuffle_row_groups=True,
                                  schema_fields=["seq", "length", "label"])
    params = init_seq_params(jax.random.PRNGKey(1), feature_dim=6,
                             d_model=32, num_heads=4, num_classes=3)
    step = jax.jit(make_seq_train_step(0.05, num_heads=4, mesh=mesh,
                                       attn_impl=attn_impl, causal=True))
    loss = float("nan")
    with make_jax_dataloader(reader, batch_size, max_batches=steps,
                             stage_to_device=False) as loader:
        for batch in loader:
            windows = jnp.asarray(batch["seq"])
            lengths = jnp.asarray(batch["length"])
            labels = jnp.asarray(batch["label"]).astype(jnp.int32)
            mask = jnp.ones(windows.shape[0], bool)
            params, loss = step(params, windows, labels, mask, lengths)
    return float(loss)


def train_packed_causal(dataset_url, slot_len=48, slots=4, steps=6,
                        attn_impl="flash"):
    """Next-step prediction over PACKED documents — the packing story
    end-to-end: ragged docs → ``pack_ragged`` → causal attention with
    ``segment_ids`` so packed neighbours never attend to each other, and
    the next-step loss stops at segment boundaries.

    Returns ``(final_loss, packed_utilization, padded_utilization)`` —
    utilization = fraction of attention slots holding real tokens; packing
    exists to push it toward 1.0 where padding leaves it at
    ``mean(length)/max_len``.
    """
    import jax
    import jax.numpy as jnp

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import (PACK_POSITION_KEY,
                                         PACK_SEGMENT_KEY,
                                         make_packed_jax_dataloader,
                                         packed_valid_mask)
    from petastorm_tpu.models.sequence_model import attention_reference
    from petastorm_tpu.ops import flash_attention

    feature_dim, d_model, heads = 6, 32, 4
    rng = jax.random.PRNGKey(2)
    keys = jax.random.split(rng, 6)
    s = lambda fan: 1.0 / np.sqrt(fan)  # noqa: E731
    params = {
        "emb": jax.random.normal(keys[0], (feature_dim, d_model)) * s(feature_dim),
        # Learned position table indexed by the packer's WITHIN-SEGMENT
        # positions: each packed document starts at position 0 (indexing by
        # the raw slot index t would leak the packing layout into the model).
        "pos": jax.random.normal(keys[5], (slot_len, d_model)) * 0.02,
        "wq": jax.random.normal(keys[1], (d_model, d_model)) * s(d_model),
        "wk": jax.random.normal(keys[2], (d_model, d_model)) * s(d_model),
        "wv": jax.random.normal(keys[3], (d_model, d_model)) * s(d_model),
        "out": jax.random.normal(keys[4], (d_model, feature_dim)) * s(d_model),
    }

    def loss_fn(params, x, seg, pos):
        h = x @ params["emb"] + params["pos"][pos]
        b, t, _ = h.shape
        split = lambda w: (h @ w).reshape(b, t, heads, d_model // heads)  # noqa: E731
        q, k, v = split(params["wq"]), split(params["wk"]), split(params["wv"])
        if attn_impl == "flash":
            # block_k=None defers to the kernel's length-aware
            # default (512 at long T — measured faster on v5e).
            attn = flash_attention(q, k, v, block_q=min(128, t),
                                   block_k=None if t >= 4096
                                   else min(128, t), causal=True,
                                   segment_ids=seg)
        else:
            attn = attention_reference(q, k, v, causal=True,
                                       segment_ids=seg)
        y = attn.reshape(b, t, d_model) @ params["out"]
        # Predict the NEXT step's features; the target is valid only where
        # the next position continues the SAME document.
        cont = (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] >= 0)
        err = ((y[:, :-1] - x[:, 1:]) ** 2).mean(axis=-1)
        cont = cont.astype(jnp.float32)
        return (err * cont).sum() / jnp.maximum(cont.sum(), 1.0)

    @jax.jit
    def step(params, x, seg, pos):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, seg, pos)
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.05 * g, params, grads), loss

    reader = make_columnar_reader(dataset_url, num_epochs=None,
                                  shuffle_row_groups=True,
                                  schema_fields=["seq", "length"])
    # The packed DELIVERY path: reader -> pack_ragged -> the loader's
    # prefetch/staging machinery, one call.
    loader = make_packed_jax_dataloader(reader, slot_len=slot_len,
                                        slots=slots,
                                        sequence_fields=["seq"],
                                        length_field="length",
                                        max_batches=steps,
                                        stage_to_device=False)
    loss = float("nan")
    valid_tokens, total_slots, padded_lens = 0, 0, []
    with loader:
        for packed in loader:
            seg_np = np.asarray(packed[PACK_SEGMENT_KEY])
            seg = jnp.asarray(seg_np)
            pos = jnp.asarray(packed[PACK_POSITION_KEY])
            x = jnp.asarray(packed["seq"])
            params, loss = step(params, x, seg, pos)
            mask = packed_valid_mask(seg_np)
            valid_tokens += int(mask.sum())
            total_slots += mask.size
            padded_lens.extend(
                int((seg_np[b] == sid).sum())
                for b in range(slots)
                for sid in range(int(seg_np[b].max()) + 1))
    packed_util = valid_tokens / max(total_slots, 1)
    # The padded alternative: one row per document at the static max length.
    max_len = max(padded_lens) if padded_lens else 1
    padded_util = (sum(padded_lens) / (len(padded_lens) * max_len)
                   if padded_lens else 0.0)
    return float(loss), packed_util, padded_util


def main(dataset_url=None, frames=1024):
    import shutil
    import tempfile

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="sequence_example_")
        dataset_url = f"file://{tmpdir}/frames"
        generate_frames_dataset(dataset_url, frames=frames)
    try:
        loss = train_sequence(dataset_url)
        print(f"trained {WINDOW}-frame windows, final loss={loss:.4f}")
        # The ragged demo writes its own dataset — always under a tmpdir,
        # never beside a caller-supplied URL (which may be read-only).
        with tempfile.TemporaryDirectory(
                prefix="sequence_example_ragged_") as ragged_dir:
            ragged_url = f"file://{ragged_dir}/ragged"
            generate_ragged_dataset(ragged_url)
            ragged_loss = train_ragged_causal(ragged_url)
            print(f"trained ragged causal sequences, "
                  f"final loss={ragged_loss:.4f}")
            packed_loss, packed_util, padded_util = train_packed_causal(
                ragged_url)
            print(f"trained packed causal LM, final loss={packed_loss:.4f} "
                  f"(slot utilization {packed_util:.0%} packed vs "
                  f"{padded_util:.0%} padded)")
        return loss
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    main()
