"""Worker contract.

Reference parity: ``petastorm/workers_pool/worker_base.py::WorkerBase``.
"""

from __future__ import annotations


class WorkerBase:
    """A pool worker. Subclasses implement :meth:`process`; results are
    emitted via ``publish_func`` (possibly several per ventilated item).

    Reader workers that tag payloads for resumable iteration
    (``reader_impl/delivery_tracker.py``) must publish AT MOST ONE tagged
    payload per ventilated item: the tracker counts one delivery per tag, so
    chunked publishes would over-count and make resume skip epochs. Untagged
    payloads (plain pool users) are unconstrained."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def process(self, *args, **kwargs):
        raise NotImplementedError

    def shutdown(self):
        """Called once when the pool stops this worker (optional cleanup)."""

    def publish_func(self, data):  # pragma: no cover - replaced in __init__
        raise NotImplementedError


class EOFSentinel:
    """Internal end-of-work marker placed on worker input queues."""
