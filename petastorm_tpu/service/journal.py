"""Append-only JSONL write-ahead log with compacted snapshots.

The dispatcher's durability layer: every control-plane mutation (worker
registration, client assignment, fcfs split pop, fencing bump) is appended
as one JSON line *after* being applied in memory, and the full state is
periodically compacted into a snapshot so recovery cost stays bounded by
``compact_every`` instead of growing with uptime.

Crash-safety invariants:

- Records are flushed per append (``fsync=True`` additionally makes each
  record durable against OS/power loss; the default survives process
  crashes, which is what the service's failure model targets).
- Snapshots are written atomically (tmp file + ``os.replace``), so a crash
  mid-compaction leaves the previous snapshot intact.
- Every record carries a monotonically increasing ``seq`` and the snapshot
  records the ``seq`` watermark it folded in, so a crash *between* the
  snapshot replace and the WAL truncation replays nothing twice.
- A torn final line (crash mid-append) is detected and truncated off,
  whether it is missing its newline OR newline-terminated but unparseable
  (buffered writes flush at page boundaries, not record boundaries, so a
  crash can persist a mangled record complete with its "\n"); everything
  before it replays normally. Mid-file corruption still refuses recovery —
  that is damage, not a crash signature.

The layout inside ``path`` is two files: ``snapshot.json`` and
``wal.jsonl``. :meth:`load` returns the snapshot state (or ``None``) plus
the post-watermark records, in append order — the dispatcher installs the
state and re-applies the records through the same mutation helpers the
live handlers use (``docs/guides/service.md#failure-model-and-recovery``).
"""

from __future__ import annotations

import json
import os

from petastorm_tpu import failpoints
from petastorm_tpu.telemetry.log import service_logger

logger = service_logger(__name__)

SNAPSHOT_NAME = "snapshot.json"
WAL_NAME = "wal.jsonl"


class Journal:
    """One dispatcher's WAL + snapshot pair under ``path``.

    :param path: journal directory (created if missing).
    :param compact_every: appended records between automatic compactions
        (checked by :meth:`maybe_compact`).
    :param fsync: fsync the WAL after every append (durable against OS
        crash, not just process crash) and the snapshot before its rename.
    """

    def __init__(self, path, compact_every=256, fsync=False):
        self.path = str(path)
        self._compact_every = int(compact_every)
        self._fsync = fsync
        os.makedirs(self.path, exist_ok=True)
        self._wal_path = os.path.join(self.path, WAL_NAME)
        self._snapshot_path = os.path.join(self.path, SNAPSHOT_NAME)
        self._wal_file = None
        self._closed = False
        self._seq = 0                  # last seq assigned
        self._since_snapshot = 0       # records appended since last snapshot
        self.records_appended = 0      # this process's appends
        self.compactions = 0           # this process's compactions
        self.snapshot_failures = 0     # compactions that failed (OSError)

    # -- recovery ----------------------------------------------------------

    def load(self):
        """Read the journal → ``(snapshot_state_or_None, records)``.

        Restores the internal ``seq`` cursor so appends continue the
        sequence; records at or below the snapshot's watermark (a crash
        landed between snapshot replace and WAL truncation) are skipped,
        and a torn tail line is dropped with a warning.
        """
        state, watermark = None, 0
        try:
            with open(self._snapshot_path, "r", encoding="utf-8") as f:
                snap = json.load(f)
            state = snap["state"]
            watermark = int(snap.get("seq", 0))
        except FileNotFoundError:
            pass
        except (ValueError, KeyError, TypeError) as exc:
            # A torn snapshot cannot happen under the atomic-replace write
            # path; a hand-damaged one must not brick recovery silently.
            logger.warning("journal snapshot %s unreadable (%s) — "
                           "recovering from the WAL alone",
                           self._snapshot_path, exc)
        records = []
        self._seq = watermark
        try:
            with open(self._wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            data = b""
        # Every complete record is written as one line ending in "\n"
        # (json.dumps emits no newlines), so bytes past the last newline
        # are a torn append (crash mid-write). They must be TRUNCATED off
        # the file, not just skipped: a later append() reopens in append
        # mode, and concatenating onto the fragment would weld two records
        # into one unparseable MID-file line that bricks the next recovery.
        complete, _, torn = data.rpartition(b"\n")
        if torn:
            logger.warning(
                "journal %s: dropping %d-byte torn final WAL line "
                "(crash mid-append)", self.path, len(torn))
            with open(self._wal_path, "r+b") as f:
                f.truncate(len(data) - len(torn))
        lines = complete.split(b"\n") if complete else []
        parsed = []  # (line_index, record)
        bad_final = None
        for i, line in enumerate(lines):
            stripped = line.strip()
            if not stripped:
                continue
            try:
                record = json.loads(stripped.decode("utf-8"))
                if not isinstance(record, dict):
                    raise ValueError("WAL record is not a JSON object")
            except ValueError:
                if i == len(lines) - 1:
                    # Garbage FINAL line: the other face of a crash
                    # mid-append — the buffered write flushed a partial or
                    # mangled record WITH its trailing newline (page-sized
                    # flush boundaries don't respect record boundaries).
                    # Same remedy as the torn tail: truncate it off and
                    # restore the pre-append state.
                    bad_final = (i, line)
                    break
                raise ValueError(
                    f"journal {self.path}: corrupt WAL record at line "
                    f"{i + 1} (mid-file, not the crash-mid-append case — "
                    f"refusing to recover from ambiguous state)")
            parsed.append((i, record))
        if bad_final is not None:
            i, line = bad_final
            keep = sum(len(ln) + 1 for ln in lines[:i])
            logger.warning(
                "journal %s: dropping unparseable final WAL line %d "
                "(%d bytes — crash mid-append)", self.path, i + 1,
                len(line))
            with open(self._wal_path, "r+b") as f:
                f.truncate(keep)
        for _, record in parsed:
            seq = int(record.get("seq", 0))
            if seq <= watermark:
                continue  # already folded into the snapshot
            records.append(record)
            self._seq = max(self._seq, seq)
        self._since_snapshot = len(records)
        return state, records

    # -- writing -----------------------------------------------------------

    def append(self, record):
        """Append one record (a JSON-serializable dict); assigns ``seq``."""
        if self._closed:
            # The lazy open must NOT resurrect a closed journal: a handler
            # racing shutdown would durably write a record that post-dates
            # the stop and leak the reopened handle.
            raise RuntimeError(f"journal {self.path} is closed")
        fp = failpoints.ACTIVE
        if fp is not None:
            fp.fire("journal.append")  # enospc raises BEFORE the write:
            #   the WAL never holds a half-applied record, and the seq
            #   cursor below stays consistent with what is on disk.
        self._seq += 1
        record = dict(record, seq=self._seq)
        if self._wal_file is None:
            self._wal_file = open(self._wal_path, "a", encoding="utf-8")
        self._wal_file.write(json.dumps(record) + "\n")
        self._wal_file.flush()
        if self._fsync:
            if fp is not None:
                fp.fire("journal.fsync")
            os.fsync(self._wal_file.fileno())
        self.records_appended += 1
        self._since_snapshot += 1
        return record

    def snapshot(self, state):
        """Compact: atomically persist ``state`` with the current ``seq``
        watermark, then truncate the WAL."""
        if self._closed:
            raise RuntimeError(f"journal {self.path} is closed")
        tmp = self._snapshot_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"seq": self._seq, "state": state}, f)
                f.flush()
                if self._fsync:
                    os.fsync(f.fileno())
            fp = failpoints.ACTIVE
            if fp is not None and fp.fire("journal.compact") \
                    == "torn_rename":
                # The crash-between-tmp-write-and-rename signature: the
                # tmp file exists, snapshot.json is still the OLD one, and
                # the WAL was NOT truncated — recovery must replay the
                # pre-compaction WAL byte-identically.
                raise OSError(
                    "failpoint journal.compact: torn snapshot rename")
            os.replace(tmp, self._snapshot_path)
        except OSError:
            # A failed compaction must leave the journal exactly as it
            # was: old snapshot intact, WAL intact, seq/since-snapshot
            # cursors untouched (the truncation below never ran). The
            # orphan tmp is removed so a later compaction cannot be
            # confused by it; recovery ignores it either way.
            self.snapshot_failures += 1
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # Crash window here is safe: the WAL still holds <= watermark
        # records, which load() skips.
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "w", encoding="utf-8")
        self._since_snapshot = 0
        self.compactions += 1

    def maybe_compact(self, state_fn):
        """Compact when ``compact_every`` records accumulated since the
        last snapshot; ``state_fn()`` is called only when compacting."""
        if self._since_snapshot >= self._compact_every:
            self.snapshot(state_fn())
            return True
        return False

    def close(self):
        self._closed = True
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None

    @property
    def stats(self):
        return {
            "path": self.path,
            "records_appended": self.records_appended,
            "compactions": self.compactions,
            "snapshot_failures": self.snapshot_failures,
            "records_since_snapshot": self._since_snapshot,
        }
