"""Sequence encoder with ring attention — the long-context consumer.

The reference's long-sequence feature is NGram window assembly
(SURVEY.md §5): multi-frame sensor/video rows become ``[B, T, ...]`` windows.
This model closes the loop on TPU: windows from
``collate_ngram_rows``/``make_jax_dataloader`` feed a transformer-style
encoder whose attention runs **sequence-parallel** over a mesh axis using
**ring attention** — each device holds a ``T/sp`` slice of the sequence, and
K/V blocks rotate around the ICI ring via ``lax.ppermute`` while an online
(flash-style) softmax accumulates, so no device ever materializes the full
``[T, T]`` score matrix or the full sequence. This is the standard JAX
long-context recipe: ``shard_map`` + collective permute, letting XLA overlap
the ring hop with the local block's compute.

All shapes are static; the ring loop is a ``lax.fori_loop`` (compiler-visible
control flow); matmuls run in bfloat16 on the MXU with f32 softmax
statistics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# Single source of truth for the kernel's length-aware block_k default:
# at/above this T, pass block_k=None and let the kernel pick its tuned
# long-T tile (512 today) — retuning the kernel retunes every call site.
from petastorm_tpu.ops.flash_attention import (
    _LONG_T_THRESHOLD as _FLASH_LONG_T,
)


def attention_reference(q, k, v, causal=False, lengths=None,
                        segment_ids=None):
    """Plain (unsharded) scaled-dot-product attention — numerics oracle for
    the ring version. Shapes: [B, T, H, Dh].

    ``causal``: mask keys after each query's position (decoder style).
    ``lengths``: optional per-example valid key counts [B] — keys at or past
    ``lengths[b]`` are masked out (NGram windows shorter than T).
    ``segment_ids``: optional [B, T] ids for packed batches
    (``jax_utils.packing``) — positions attend only within their segment;
    requires T_q == T_kv.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    t_q, t_kv = q.shape[1], k.shape[1]
    neg_inf = jnp.array(-jnp.inf, scores.dtype)
    mask = None
    if causal:
        row = jnp.arange(t_q)[:, None] + (t_kv - t_q)  # last-aligned
        mask = (jnp.arange(t_kv)[None, :] <= row)[None, None]  # [1,1,Tq,Tkv]
    if lengths is not None:
        valid = (jnp.arange(t_kv)[None, :]
                 < lengths[:, None])[:, None, None, :]         # [B,1,1,Tkv]
        mask = valid if mask is None else mask & valid
    if segment_ids is not None:
        same = (segment_ids[:, :, None]
                == segment_ids[:, None, :])[:, None]           # [B,1,Tq,Tkv]
        mask = same if mask is None else mask & same
    row_valid = None
    if mask is not None:
        # Rows with no valid key (lengths[b] == 0, or causal cross-length
        # suffix alignment) must yield ZERO output nan-free in forward AND
        # vjp — same guard as the flash kernel's oracle: substitute finite
        # scores, then zero the probabilities.
        row_valid = mask.any(axis=-1, keepdims=True)
        scores = jnp.where(mask, scores, neg_inf)
        scores = jnp.where(row_valid, scores, 0.0)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if row_valid is not None:
        probs = jnp.where(row_valid, probs, 0.0)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def _stripe(x, sp):
    """Permute the T axis of [B, T, ...] so that a contiguous shard r over
    the permuted axis holds the STRIDED positions r, r+sp, r+2·sp, … of the
    original sequence (striped placement for balanced causal ring)."""
    b, t = x.shape[:2]
    return (x.reshape((b, t // sp, sp) + x.shape[2:])
            .swapaxes(1, 2).reshape(x.shape))


def _unstripe(x, sp):
    b, t = x.shape[:2]
    return (x.reshape((b, sp, t // sp) + x.shape[2:])
            .swapaxes(1, 2).reshape(x.shape))


def _ring_flash_block(q, k, v, axis_name, axis_size, varying_axes=None,
                      causal=False, placement="contiguous", lengths=None,
                      segment_ids=None):
    """Per-shard ring attention with the Pallas flash kernel as the local
    attention — NO [L, L] score block materializes anywhere, even
    sequence-parallel (the kernel is O(block²); ring steps merge the
    normalized partials via their log-sum-exp, the exact blockwise-softmax
    combination).

    Per ring step the resident K/V block attends through
    ``flash_attention_with_lse``; the (out, lse) partials fold into a
    running ``(num, m, den)`` online-softmax state at per-ROW granularity
    (O(L·H) statistics, not O(L²)). Causal masking per block: striped
    placement uses the kernel's causal diagonal (shift 0 when the key
    shard is at-or-before the query shard in the interleaved order, strict
    -1 after); contiguous skips fully-future blocks and runs the diagonal
    block causally. Per-example lengths become per-block ``kv_lengths``
    (original-position masks translated into each block's local prefix).
    Backward rides the kernel's lse-cotangent path — no hand-written ring
    backward schedule. ``segment_ids`` (packed batches): the local q ids
    stay put while the resident K/V block's ids ride the ring — the kernel
    takes the ``(q_ids, kv_ids)`` pair per step.
    """
    from petastorm_tpu.ops.flash_attention import flash_attention_with_lse

    b, l, h, dh = q.shape
    blk = min(128, l)
    # block_k=None defers to the kernel's length-aware default (512 once
    # the resident block reaches 4096 — measured faster on v5e); below
    # that, match block_q so short shards keep their exact tiles.
    blk_k = None if l >= _FLASH_LONG_T else blk
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    r = jax.lax.axis_index(axis_name)

    def block_lens(src):
        if lengths is None:
            return None
        if placement == "striped":
            # k_pos = src + sp·j < len  ⟺  j < ceil((len - src) / sp)
            cnt = (lengths - src + axis_size - 1) // axis_size
        else:
            cnt = lengths - src * l
        return jnp.clip(cnt, 0, l).astype(jnp.int32)

    def partial_attn(k_cur, v_cur, kseg_cur, src, causal_, shift):
        segs = (None if segment_ids is None
                else (segment_ids, kseg_cur))
        return flash_attention_with_lse(
            q, k_cur, v_cur, block_q=blk, block_k=blk_k, causal=causal_,
            causal_shift=shift, kv_lengths=block_lens(src),
            segment_ids=segs)

    def merge(carry, o_b, lse_b):
        num, m, den = carry
        o_b = o_b.astype(jnp.float32)
        m_new = jnp.maximum(m, lse_b)
        safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe))
        beta = jnp.where(jnp.isneginf(lse_b), 0.0, jnp.exp(lse_b - safe))
        num = num * alpha[..., None] + o_b * beta[..., None]
        den = den * alpha + beta
        return num, m_new, den

    def body(i, carry):
        k_cur, v_cur, kseg_cur, num, m, den = carry
        src = (r - i) % axis_size
        if not causal:
            o_b, lse_b = partial_attn(k_cur, v_cur, kseg_cur, src, False, 0)
            num, m, den = merge((num, m, den), o_b, lse_b)
        elif placement == "striped":
            # Key shard at-or-before the query shard in interleaved order →
            # standard causal diagonal; after → strict causal (shift -1).
            o_b, lse_b = jax.lax.cond(
                src <= r,
                lambda kc, vc, kg, s: partial_attn(kc, vc, kg, s, True, 0),
                lambda kc, vc, kg, s: partial_attn(kc, vc, kg, s, True, -1),
                k_cur, v_cur, kseg_cur, src)
            num, m, den = merge((num, m, den), o_b, lse_b)
        else:  # contiguous: skip fully-future, diagonal block causal
            def future(kc, vc, kg, s, carry):
                return carry

            def diag(kc, vc, kg, s, carry):
                o_b, lse_b = partial_attn(kc, vc, kg, s, True, 0)
                return merge(carry, o_b, lse_b)

            def past(kc, vc, kg, s, carry):
                o_b, lse_b = partial_attn(kc, vc, kg, s, False, 0)
                return merge(carry, o_b, lse_b)

            num, m, den = jax.lax.cond(
                src > r, future,
                lambda kc, vc, kg, s, c: jax.lax.cond(s == r, diag, past,
                                                      kc, vc, kg, s, c),
                k_cur, v_cur, kseg_cur, src, (num, m, den))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        if segment_ids is not None:
            kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return k_nxt, v_nxt, kseg_cur, num, m, den

    from petastorm_tpu.models._shard_compat import mark_varying

    def varying(x):
        return mark_varying(x, varying_axes or (axis_name,))

    kseg0 = (segment_ids if segment_ids is not None
             else varying(jnp.zeros((b, l), jnp.int32)))
    init = (k, v, kseg0,
            varying(jnp.zeros((b, l, h, dh), jnp.float32)),
            varying(jnp.full((b, l, h), -jnp.inf, jnp.float32)),
            varying(jnp.zeros((b, l, h), jnp.float32)))
    _, _, _, num, _, den = jax.lax.fori_loop(0, axis_size, body, init)
    return (num / jnp.maximum(den, 1e-30)[..., None]).astype(q.dtype)


def ring_attention_block(q, k, v, axis_name, axis_size, varying_axes=None,
                         causal=False, placement="contiguous",
                         lengths=None, segment_ids=None):
    """Per-shard ring attention body (runs inside shard_map).

    ``q, k, v``: the local sequence slice, [B, L, H, Dh] with L = T/sp.
    K/V blocks rotate ``axis_size`` times around ``axis_name``; an online
    softmax (running max + running sum, f32) makes the result exactly equal
    to attention over the full sequence.

    ``causal``: at ring step ``i`` the resident K/V block originated on
    device ``src = (r - i) mod sp``, so global key positions are known and
    the causal mask is applied per block. Placement decides who owns which
    positions:

    - ``"contiguous"``: device r owns positions [r·L, (r+1)·L). Blocks with
      src > r are fully future — their matmuls are skipped via ``lax.cond``
      — but the ppermute barrier makes each ring step as slow as its
      busiest device, so the skip saves energy/MXU slots, not wall-clock
      (device sp-1 computes sp blocks, device 0 computes 1).
    - ``"striped"``: device r owns positions r, r+sp, r+2·sp, … (use the
      :func:`ring_attention` wrapper, which pre/post-permutes). Every block
      on every device is then ~half-causal-valid — perfectly balanced; no
      block is skippable but no device idles.
    """
    b, l, h, dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.array(dh, jnp.float32))
    qf = q.astype(jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    r = jax.lax.axis_index(axis_name)
    row_ids = jnp.arange(l)

    group = h // k.shape[2]  # grouped-query: q-heads per shared K/V head

    def block_update(k_cur, v_cur, kseg_cur, acc, row_max, row_sum, src):
        if group > 1:
            # GQA: repeat the K/V heads AT LOCAL COMPUTE only — the ring
            # still permutes the grouped (small) blocks, so ICI traffic
            # scales with h_kv; only this shard's [B, L, H, Dh] repeat
            # materializes, and only on the dense path (the flash path
            # group-maps fetches in-kernel instead).
            k_cur = jnp.repeat(k_cur, group, axis=2)
            v_cur = jnp.repeat(v_cur, group, axis=2)
        scores = jnp.einsum("blhd,bmhd->bhlm", qf,
                            k_cur.astype(jnp.float32)) * scale
        if segment_ids is not None:
            # Packed batches: the resident K block's ids rotated here with
            # it, so the same-segment mask needs no position bookkeeping
            # (and composes with striping — the ids were striped alongside).
            same = segment_ids[:, :, None] == kseg_cur[:, None, :]  # [B,L,L]
            scores = jnp.where(same[:, None], scores, -jnp.inf)
        if causal or lengths is not None:
            # ORIGINAL global positions of the resident block's keys (the
            # striped wrapper permuted the sequence; these formulas undo it).
            if placement == "striped":
                # global position of local index j on device d is d + sp·j
                q_pos = r + axis_size * row_ids
                k_pos = src + axis_size * row_ids
            else:
                q_pos = r * l + row_ids
                k_pos = src * l + row_ids
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]            # [L, L]
            scores = jnp.where(mask, scores, -jnp.inf)
        if lengths is not None:
            valid = k_pos[None, :] < lengths[:, None]          # [B, L]
            scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
        blk_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        # A block can be fully masked for some rows (causal): keep the raw
        # -inf running max but exponentiate against a finite substitute so
        # no (-inf) - (-inf) nan appears; those rows contribute zeros.
        safe_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
        correction = jnp.where(jnp.isneginf(row_max), 0.0,
                               jnp.exp(row_max - safe_max))
        probs = jnp.exp(scores - safe_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhlm,bmhd->bhld", probs, v_cur.astype(jnp.float32))
        row_sum = row_sum * correction + probs.sum(axis=-1)
        return acc, new_max, row_sum

    def body(i, carry):
        k_cur, v_cur, kseg_cur, acc, row_max, row_sum = carry
        src = (r - i) % axis_size
        if causal and placement == "contiguous":
            # Fully-future block for this device: skip both matmuls.
            acc, row_max, row_sum = jax.lax.cond(
                src > r,
                lambda *args: args[3:],
                lambda *args: block_update(*args, src=src),
                k_cur, v_cur, kseg_cur, acc, row_max, row_sum)
        else:
            acc, row_max, row_sum = block_update(k_cur, v_cur, kseg_cur,
                                                 acc, row_max, row_sum,
                                                 src=src)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        if segment_ids is not None:
            kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return k_nxt, v_nxt, kseg_cur, acc, row_max, row_sum

    # The softmax stats start as constants but the loop body mixes them with
    # the (sequence-varying) K/V blocks; mark them varying over the ring axis
    # so the fori_loop carry types line up under shard_map's vma typing.
    from petastorm_tpu.models._shard_compat import mark_varying

    def varying(x):
        return mark_varying(x, varying_axes or (axis_name,))

    kseg0 = (segment_ids if segment_ids is not None
             else varying(jnp.zeros((b, l), jnp.int32)))
    init = (k, v, kseg0,
            varying(jnp.zeros((b, h, l, dh), jnp.float32)),
            varying(jnp.full((b, h, l), -jnp.inf, jnp.float32)),
            varying(jnp.zeros((b, h, l), jnp.float32)))
    _, _, _, acc, _, row_sum = jax.lax.fori_loop(0, axis_size, body, init)
    out = acc / jnp.maximum(row_sum, 1e-30)[..., None]
    return jnp.einsum("bhld->blhd", out).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", batch_axis=None,
                   causal=False, placement="striped", lengths=None,
                   segment_ids=None, local_attn="dense"):
    """Sequence-parallel attention over ``mesh[axis_name]``.

    Inputs are global ``[B, T, H, Dh]`` arrays (sharded or shardable on T);
    output matches :func:`attention_reference` up to float tolerance.
    ``batch_axis``: mesh axis the batch dim is sharded over (data parallel),
    so shard_map doesn't force a reshard at the boundary.

    ``causal``: decoder-style masking. ``placement`` (causal only) picks the
    position→device layout: ``"striped"`` (default) pre-permutes so every
    device does equal causal work per ring step; ``"contiguous"`` keeps the
    natural layout and skips fully-future blocks (imbalanced — see
    :func:`ring_attention_block`). Output always returns in natural order.
    ``lengths`` ([B] int, optional): keys at or past ``lengths[b]`` are
    masked for example ``b`` — masking is by ORIGINAL position, so it
    composes with the striped permutation.
    ``segment_ids`` ([B, T] int, optional): packed batches
    (``jax_utils.packing``) — positions attend only within their segment;
    the ids ride the K/V ring so masking needs no extra bookkeeping.
    Mutually exclusive with ``lengths`` (give padding its own id).
    ``local_attn``: ``"dense"`` (each ring step computes its [L, L] score
    block with XLA), ``"flash"`` (each step runs the Pallas kernel and
    merges partials by log-sum-exp — NO [L, L] buffer even per step; the
    long-T choice), or ``"auto"`` (flash once T reaches
    ``ULYSSES_FLASH_THRESHOLD``). All masks compose with flash, including
    packed ``segment_ids`` (the kernel takes the local q ids + the
    ring-carried kv ids as a pair).
    """
    from jax import shard_map

    sp = mesh.shape[axis_name]
    if v.shape[2] != k.shape[2]:
        raise ValueError(
            f"k has {k.shape[2]} heads but v has {v.shape[2]}; K and V "
            "must share their (possibly grouped) head count")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"ring_attention grouped-query heads must divide: q has "
            f"{q.shape[2]} heads, k/v have {k.shape[2]}")
    if local_attn == "auto":
        local_attn = ("flash" if q.shape[1] >= ULYSSES_FLASH_THRESHOLD
                      else "dense")
    if local_attn not in ("dense", "flash"):
        raise ValueError(f"local_attn {local_attn!r} is not 'auto', "
                         "'dense', or 'flash'")
    if local_attn == "flash" and q.shape[1] // sp < 8:
        # Below the TPU min sublane tile the kernel cannot tile; dense
        # per-block attention is cheaper at these sizes anyway.
        local_attn = "dense"
    if (causal or lengths is not None or segment_ids is not None) \
            and q.shape[1] != k.shape[1]:
        # Both placements derive key positions from q's local length, and
        # contiguous's full-skip condition assumes the same partitioning.
        raise ValueError(
            "causal/lengths/segment ring attention requires T_q == T_kv "
            f"(got {q.shape[1]} vs {k.shape[1]})")
    if lengths is not None and segment_ids is not None:
        raise ValueError(
            "segment_ids and lengths are mutually exclusive: give padded "
            "slots their own segment id instead")
    striped = causal and placement == "striped"
    if striped:
        q, k, v = _stripe(q, sp), _stripe(k, sp), _stripe(v, sp)
        if segment_ids is not None:
            segment_ids = _stripe(segment_ids, sp)

    spec = P(batch_axis, axis_name, None, None)
    varying_axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
    # The block's position formulas must describe the ACTUAL data layout:
    # striping is only applied above (causal), so a lengths-only call with
    # the default placement="striped" still holds contiguous data.
    block_fn = (_ring_flash_block if local_attn == "flash"
                else ring_attention_block)
    block = functools.partial(block_fn, axis_name=axis_name,
                              axis_size=sp, varying_axes=varying_axes,
                              causal=causal,
                              placement="striped" if striped
                              else "contiguous")
    # pallas_call outputs carry no varying-mesh-axes annotation, which the
    # vma checker rejects — opt out only when the flash kernel runs.
    check_vma = local_attn != "flash"
    if lengths is None and segment_ids is None:
        sharded = shard_map(block, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=check_vma)
        out = sharded(q, k, v)
    elif segment_ids is not None:
        sharded = shard_map(
            lambda a, b, c, sg: block(a, b, c, segment_ids=sg),
            mesh=mesh,
            in_specs=(spec, spec, spec, P(batch_axis, axis_name)),
            out_specs=spec, check_vma=check_vma)
        out = sharded(q, k, v, segment_ids)
    else:
        sharded = shard_map(
            lambda a, b, c, le: block(a, b, c, lengths=le),
            mesh=mesh, in_specs=(spec, spec, spec, P(batch_axis)),
            out_specs=spec, check_vma=check_vma)
        out = sharded(q, k, v, lengths)
    return _unstripe(out, sp) if striped else out


# Full-sequence length at/above which the Ulysses local attention switches
# from dense (one [T, T] block) to the Pallas flash kernel (O(block²)).
ULYSSES_FLASH_THRESHOLD = 1024


def ulysses_attention_block(q, k, v, axis_name, axis_size, causal=False,
                            local_attn="auto", lengths=None,
                            segment_ids=None):
    """Per-shard Ulysses (all-to-all) attention body (runs inside shard_map).

    Input: the local sequence slice ``[B, L, H, Dh]`` with ``L = T/sp``.
    The DeepSpeed-Ulysses recipe, JAX-style: an all-to-all reshards from
    sequence-sharded/head-replicated to head-sharded/sequence-complete, each
    device runs attention over the full sequence for its ``H/sp`` heads,
    and a reverse all-to-all restores sequence sharding. Two all-to-alls
    per attention vs the ring's ``sp`` permutes.

    ``local_attn`` picks the per-head-group attention: ``"dense"`` (one
    [T, T] block), ``"flash"`` (the Pallas tiled kernel — no [T, T] buffer,
    the point of Ulysses at long T), or ``"auto"`` (flash once the full
    sequence reaches ``ULYSSES_FLASH_THRESHOLD``, dense below — short
    sequences fit comfortably and dodge the kernel's fixed overhead).
    """
    b, l, h, dh = q.shape
    if h % axis_size:
        raise ValueError(
            f"ulysses attention needs heads ({h}) divisible by the mesh "
            f"axis ({axis_size}); use ring attention otherwise")

    def to_heads(x):
        # [B, L, H, Dh] -> all_to_all over the head axis: each device trades
        # its sequence slice of all heads for the full sequence of its
        # H/axis_size heads -> [B, L*axis_size = T, H/axis_size, Dh].
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_sequence(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # After to_heads each device holds the FULL sequence for its head group,
    # so per-example lengths / full [B, T] segment ids apply directly to the
    # local attention.
    local_attn = _resolve_ulysses_local(l * axis_size, local_attn)
    if local_attn == "flash":
        from petastorm_tpu.ops import flash_attention

        t_full = l * axis_size
        block = min(128, t_full)
        # block_k=None: the kernel's length-aware default (512 at the
        # full-sequence lengths Ulysses attends over) — measured faster.
        out = flash_attention(qh, kh, vh, block_q=block,
                              block_k=None if t_full >= _FLASH_LONG_T else block,
                              causal=causal, kv_lengths=lengths,
                              segment_ids=segment_ids)
    else:
        out = attention_reference(qh, kh, vh, causal=causal,
                                  lengths=lengths,
                                  segment_ids=segment_ids)
    return to_sequence(out)


def _resolve_ulysses_local(t_full, local_attn):
    """Resolve ``local_attn`` ("auto" by T threshold; "flash" falls back to
    dense below the TPU min sublane tile, where the kernel's (block, 128)
    scratch would not tile for Mosaic)."""
    if local_attn == "auto":
        local_attn = ("flash" if t_full >= ULYSSES_FLASH_THRESHOLD
                      else "dense")
    if local_attn not in ("dense", "flash"):
        raise ValueError(f"local_attn {local_attn!r} is not 'auto', "
                         "'dense', or 'flash'")
    if local_attn == "flash" and t_full < 8:
        local_attn = "dense"
    return local_attn


def ulysses_attention(q, k, v, mesh, axis_name="sp", batch_axis=None,
                      causal=False, local_attn="auto", lengths=None,
                      segment_ids=None):
    """All-to-all sequence-parallel attention over ``mesh[axis_name]``.

    Same contract as :func:`ring_attention` (global ``[B, T, H, Dh]`` in,
    matches :func:`attention_reference` numerics); requires ``H`` divisible
    by the axis size. The two collectives ride ICI like the ring's permutes
    — pick by workload: Ulysses moves ``O(T·Dh·H/sp)`` twice, the ring moves
    K/V ``sp`` times but never needs the full sequence on one device.
    ``causal`` masks decoder-style; ``local_attn`` as in
    :func:`ulysses_attention_block` (``"flash"``/long-T ``"auto"`` keeps the
    per-head-group attention free of [T, T] buffers too).
    """
    from jax import shard_map

    if k.shape[2] != q.shape[2] or v.shape[2] != q.shape[2]:
        raise NotImplementedError(
            f"ulysses_attention reshards HEADS over the sequence axis, so "
            f"grouped-query K/V (q {q.shape[2]} heads vs k/v "
            f"{k.shape[2]}/{v.shape[2]}) is not supported — use "
            "ring_attention (its K/V ring permutes the grouped heads "
            "directly, shrinking ICI traffic by the group factor) or "
            "repeat K/V to the query head count first")
    local_attn = _resolve_ulysses_local(q.shape[1], local_attn)
    spec = P(batch_axis, axis_name, None, None)
    block = functools.partial(ulysses_attention_block, axis_name=axis_name,
                              axis_size=mesh.shape[axis_name], causal=causal,
                              local_attn=local_attn)
    if lengths is not None and segment_ids is not None:
        raise ValueError(
            "segment_ids and lengths are mutually exclusive: give padded "
            "slots their own segment id instead")
    # pallas_call outputs carry no varying-mesh-axes annotation, which
    # the vma checker rejects — opt out only when the flash kernel
    # actually runs, keeping the check live for the dense path.
    check_vma = local_attn != "flash"
    if lengths is None and segment_ids is None:
        sharded = shard_map(block, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=check_vma)
        return sharded(q, k, v)
    if segment_ids is not None:
        # The FULL [B, T] ids replicate over the sequence axis: after the
        # head all-to-all each device attends over the whole sequence.
        sharded = shard_map(
            lambda a, b, c, sg: block(a, b, c, segment_ids=sg),
            mesh=mesh, in_specs=(spec, spec, spec, P(batch_axis, None)),
            out_specs=spec, check_vma=check_vma)
        return sharded(q, k, v, segment_ids)
    sharded = shard_map(
        lambda a, b, c, le: block(a, b, c, lengths=le),
        mesh=mesh, in_specs=(spec, spec, spec, P(batch_axis)),
        out_specs=spec, check_vma=check_vma)
    return sharded(q, k, v, lengths)


# --- a small encoder around it -------------------------------------------

def init_seq_params(rng, feature_dim, d_model=64, num_heads=4, num_classes=10,
                    max_len=512, dtype=jnp.float32):
    """Parameter pytree: embed → (q,k,v,o) attention → classifier.

    ``num_heads`` is NOT stored in the pytree (a static int inside jit-traced
    params would poison reshapes); pass it to :func:`apply_seq_model` /
    :func:`make_seq_train_step`."""
    del num_heads  # accepted for signature convenience; static, not stored
    keys = jax.random.split(rng, 7)
    s = lambda fan: 1.0 / jnp.sqrt(fan)  # noqa: E731
    return {
        "embed": jax.random.normal(keys[0], (feature_dim, d_model), dtype) * s(feature_dim),
        "pos": jax.random.normal(keys[1], (max_len, d_model), dtype) * 0.02,
        "wq": jax.random.normal(keys[2], (d_model, d_model), dtype) * s(d_model),
        "wk": jax.random.normal(keys[3], (d_model, d_model), dtype) * s(d_model),
        "wv": jax.random.normal(keys[4], (d_model, d_model), dtype) * s(d_model),
        "wo": jax.random.normal(keys[5], (d_model, d_model), dtype) * s(d_model),
        "cls": jax.random.normal(keys[6], (d_model, num_classes), dtype) * s(d_model),
    }


def seq_param_partition_specs():
    """PartitionSpecs over a ("data", "sp") mesh: weights replicated (the
    parallel axis is the sequence, not the model)."""
    return {"embed": P(), "pos": P(), "wq": P(), "wk": P(), "wv": P(),
            "wo": P(), "cls": P()}


def apply_seq_model(params, windows, num_heads=4, mesh=None, attn_axis="sp",
                    compute_dtype=jnp.bfloat16, attn_impl="dense",
                    causal=False, lengths=None, local_attn="auto"):
    """``windows``: [B, T, F] float (NGram windows collated to a time axis).

    With ``mesh``: sequence-parallel attention over ``mesh[attn_axis]`` (T
    must divide by the axis size) — ``attn_impl="ring"`` (default; K/V
    ppermute ring, online softmax) or ``"ulysses"`` (all-to-all head
    resharding; needs heads divisible by the axis). Without a mesh:
    single-shard attention — ``attn_impl="dense"`` (XLA einsum softmax;
    ``"ring"`` also maps here, being its exact single-shard equivalent) or
    ``"flash"`` (the Pallas tiled kernel,
    ``petastorm_tpu.ops.flash_attention`` — O(block²) memory, the TPU
    choice for long windows). Returns f32 logits [B, num_classes].

    ``causal``: decoder-style attention masking (all impls, incl. the
    sequence-parallel ones). ``lengths``: per-example valid timestep counts
    [B] int — positions at/after ``lengths[b]`` neither attend nor are
    attended to nor pooled, so a ragged window padded to T produces exactly
    the logits of its unpadded self (all impls, single-shard AND
    sequence-parallel). ``local_attn``: the sequence-parallel impls' local
    attention ("auto" = Pallas flash at long T, dense below — see
    :func:`ring_attention` / :func:`ulysses_attention`).
    """
    h = num_heads
    x = windows.astype(compute_dtype) @ params["embed"].astype(compute_dtype)
    b, t, d = x.shape
    x = x + params["pos"][:t].astype(compute_dtype)

    def split(w):
        y = x @ w.astype(compute_dtype)
        return y.reshape(b, t, h, d // h)

    q, k, v = split(params["wq"]), split(params["wk"]), split(params["wv"])
    if mesh is not None:
        if attn_impl == "dense":  # the no-mesh default: means "ring" here
            attn_impl = "ring"
        if attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_impl {attn_impl!r} is not a sequence-parallel "
                f"implementation; with a mesh use 'ring' or 'ulysses'")
        batch_axis = "data" if "data" in mesh.axis_names else None
        parallel_attn = (ulysses_attention if attn_impl == "ulysses"
                         else ring_attention)
        attn = parallel_attn(q, k, v, mesh, attn_axis,
                             batch_axis=batch_axis, causal=causal,
                             lengths=lengths, local_attn=local_attn)
    elif attn_impl == "ring":
        # Symmetric remap: "ring" is the mesh-side default (the train-step
        # factory passes it unconditionally); without a mesh it means plain
        # dense attention on the single shard.
        attn = attention_reference(q, k, v, causal=causal, lengths=lengths)
    elif attn_impl == "flash":
        from petastorm_tpu.ops import flash_attention

        if t < 8:
            # Below the TPU min sublane tile the kernel's (block, 128)
            # scratch would not tile for Mosaic; dense is cheaper anyway.
            attn = attention_reference(q, k, v, causal=causal,
                                       lengths=lengths)
        else:
            block = min(128, t)
            attn = flash_attention(q, k, v, block_q=block,
                                   block_k=None if t >= _FLASH_LONG_T else block,
                                   causal=causal, kv_lengths=lengths)
    elif attn_impl == "dense":
        attn = attention_reference(q, k, v, causal=causal, lengths=lengths)
    else:
        raise ValueError(
            f"attn_impl {attn_impl!r} is not valid without a mesh "
            f"('ulysses' needs one); use 'dense', 'ring', or 'flash'")
    attn = attn.reshape(b, t, d) @ params["wo"].astype(compute_dtype)
    if lengths is None:
        pooled = attn.mean(axis=1)
    else:
        # Masked mean over the valid prefix: padded positions contribute
        # exact zeros to the sum, so logits for a padded batch are
        # bit-identical to the unpadded batch's.
        valid = (jnp.arange(t)[None, :] < lengths[:, None])
        pooled = ((attn * valid[..., None].astype(attn.dtype)).sum(axis=1)
                  / jnp.maximum(lengths[:, None], 1).astype(attn.dtype))
    logits = pooled @ params["cls"].astype(compute_dtype)
    return logits.astype(jnp.float32)


def make_seq_train_step(learning_rate=0.05, num_heads=4, mesh=None,
                        attn_axis="sp", attn_impl="ring", causal=False,
                        local_attn="auto"):
    """``step(params, windows, labels, mask[, lengths]) -> (params, loss)``
    — masked cross-entropy + SGD, sequence-parallel attention (ring or
    ulysses) when a mesh is given, decoder-style masking with ``causal``.
    ``lengths`` (optional, [B] int): per-example valid timesteps — attention
    and pooling ignore the padded tail. The returned step is jittable as-is
    (all statics are closed over)."""
    def loss_fn(params, windows, labels, mask, lengths):
        logits = apply_seq_model(params, windows, num_heads=num_heads,
                                 mesh=mesh, attn_axis=attn_axis,
                                 attn_impl=attn_impl, causal=causal,
                                 lengths=lengths, local_attn=local_attn)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)

    def step(params, windows, labels, mask, lengths=None):
        loss, grads = jax.value_and_grad(loss_fn)(params, windows, labels,
                                                  mask, lengths)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
