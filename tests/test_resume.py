"""Resumable reader iteration: state_dict() / resume_state round trips.

No reference analogue — SURVEY.md §5 flags "deterministic resumable
iteration" as the rebuild opportunity (the reference has no iterator state
save). Contract under test: at-least-once at row-group granularity — after
interrupt + resume, every row is seen at least num_epochs times across both
runs, fully-delivered row groups are never re-read, and totals are exact
when the interrupt lands on a row-group boundary.
"""

import collections

import numpy as np
import pytest

from petastorm_tpu import (make_batch_reader, make_columnar_reader,
                           make_reader)
from petastorm_tpu.reader_impl.delivery_tracker import (DeliveryTracker,
                                                        PiecePayload,
                                                        item_key,
                                                        read_table_tag,
                                                        tag_table)
from petastorm_tpu.workers_pool.ventilator import ConcurrentVentilator


# --- unit: tracker + tagging ---------------------------------------------

def test_delivery_tracker_counts_and_preload():
    tracker = DeliveryTracker(preload={"0:0": 2})
    tracker.record("0:0")
    tracker.record("1:0")
    assert tracker.counts() == {"0:0": 3, "1:0": 1}


def test_table_tagging_roundtrip():
    import pyarrow as pa

    table = pa.table({"x": [1, 2]})
    tagged = tag_table(table, item_key(7, 0))
    assert read_table_tag(tagged) == "7:0"
    assert read_table_tag(table) is None
    # tag survives Arrow IPC (the process-pool transport)
    from petastorm_tpu.reader_impl.arrow_table_serializer import (
        ArrowTableSerializer,
    )

    serializer = ArrowTableSerializer()
    assert read_table_tag(
        serializer.deserialize(serializer.serialize(tagged))) == "7:0"


def test_ventilator_per_item_iterations():
    seen = collections.Counter()
    items = [{"value": i} for i in range(3)]
    vent = ConcurrentVentilator(
        lambda **kw: seen.update([kw["value"]]), items,
        iterations=3, per_item_iterations=[3, 1, 0])
    vent.start()
    import time
    deadline = time.monotonic() + 10
    while not vent.completed() and time.monotonic() < deadline:
        vent.processed_item()
        time.sleep(0.001)
    assert dict(seen) == {0: 3, 1: 1}


def test_ventilator_per_item_iterations_validation():
    items = [{"value": 0}]
    with pytest.raises(ValueError, match="max"):
        ConcurrentVentilator(lambda **kw: None, items, iterations=2,
                             per_item_iterations=[1])
    with pytest.raises(ValueError, match="parallel"):
        ConcurrentVentilator(lambda **kw: None, items, iterations=1,
                             per_item_iterations=[1, 1])


# --- end-to-end: interrupt + resume --------------------------------------

def _read_ids_with_interrupt(url, stop_after, **kwargs):
    """Read rows until stop_after, checkpoint, and return (ids, state)."""
    ids = []
    with make_reader(url, shuffle_row_groups=True, **kwargs) as reader:
        for row in reader:
            ids.append(int(row.id))
            if len(ids) >= stop_after:
                break
        state = reader.state_dict()
    return ids, state


def test_resume_row_reader_at_least_once(petastorm_dataset):
    total_ids = set()
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     shuffle_row_groups=False) as reader:
        for row in reader:
            total_ids.add(int(row.id))

    first, state = _read_ids_with_interrupt(petastorm_dataset.url,
                                            stop_after=len(total_ids) // 3,
                                            num_epochs=1,
                                            reader_pool_type="dummy")
    assert state["version"] == 1
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy",
                     resume_state=state) as reader:
        second = [int(row.id) for row in reader]
    # Every row of the dataset seen at least once across both runs.
    assert set(first) | set(second) == total_ids
    # Fully-delivered row groups are not re-read: the resumed run is
    # strictly smaller than a fresh full read.
    assert len(second) < len(total_ids)


def test_resume_after_full_epoch_yields_nothing(petastorm_dataset):
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy") as reader:
        consumed = sum(1 for _ in reader)
        state = reader.state_dict()
    assert consumed > 0
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy",
                     resume_state=state) as reader:
        assert list(reader) == []


def test_resume_multi_epoch_exact_totals(petastorm_dataset):
    epochs = 3
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy") as reader:
        rows_per_epoch = sum(1 for _ in reader)

    stop = rows_per_epoch + rows_per_epoch // 2
    first, state = _read_ids_with_interrupt(petastorm_dataset.url,
                                            stop_after=stop,
                                            num_epochs=epochs,
                                            reader_pool_type="dummy")
    with make_reader(petastorm_dataset.url, num_epochs=epochs,
                     reader_pool_type="dummy",
                     resume_state=state) as reader:
        second = [int(row.id) for row in reader]
    counts = collections.Counter(first + second)
    # Every row seen at least `epochs` times across both runs (at-least-once).
    assert all(c >= epochs for c in counts.values())
    # Over-delivery is bounded: only the row group being consumed at the
    # interrupt is re-read — at most one row group's worth of rows
    # (fixture: 10 rows per row group).
    over_delivered = [k for k, c in counts.items() if c > epochs]
    assert len(over_delivered) <= 10
    assert all(counts[k] == epochs + 1 for k in over_delivered)


def test_resume_state_mismatch_raises(petastorm_dataset):
    _, state = _read_ids_with_interrupt(petastorm_dataset.url, stop_after=3,
                                        num_epochs=2,
                                        reader_pool_type="dummy")
    with pytest.raises(ValueError, match="num_epochs"):
        make_reader(petastorm_dataset.url, num_epochs=5,
                    reader_pool_type="dummy", resume_state=state)


def test_resume_requires_finite_epochs(petastorm_dataset):
    _, state = _read_ids_with_interrupt(petastorm_dataset.url, stop_after=3,
                                        num_epochs=1,
                                        reader_pool_type="dummy")
    with pytest.raises(ValueError, match="finite num_epochs"):
        make_reader(petastorm_dataset.url, num_epochs=None,
                    reader_pool_type="dummy", resume_state=state)


def test_resume_columnar_reader(petastorm_dataset):
    with make_columnar_reader(petastorm_dataset.url, schema_fields=["id"],
                              num_epochs=1, reader_pool_type="dummy") as r:
        batches = list(r)
        all_ids = {int(i) for b in batches for i in b.id}
        assert len(batches) > 1

    with make_columnar_reader(petastorm_dataset.url, schema_fields=["id"],
                              num_epochs=1, reader_pool_type="dummy") as r:
        first_ids = {int(i) for i in next(iter(r)).id}
        state = r.state_dict()
    with make_columnar_reader(petastorm_dataset.url, schema_fields=["id"],
                              num_epochs=1, reader_pool_type="dummy",
                              resume_state=state) as r:
        second_ids = {int(i) for b in r for i in b.id}
    assert first_ids | second_ids == all_ids


def test_resume_batch_reader_process_pool(scalar_dataset):
    """Tags survive the zmq + Arrow-IPC transport."""
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           reader_pool_type="process", workers_count=2) as r:
        all_ids = {int(i) for b in r for i in b.id}

    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           reader_pool_type="process", workers_count=2) as r:
        first = next(iter(r))
        first_ids = {int(i) for i in first.id}
        state = r.state_dict()
    assert sum(state["delivered"].values()) == 1
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           reader_pool_type="process", workers_count=2,
                           resume_state=state) as r:
        second_ids = {int(i) for b in r for i in b.id}
    assert first_ids | second_ids == all_ids


def test_tracker_rollback_uncounts_recent_deliveries():
    tracker = DeliveryTracker(preload={"9:0": 1})
    tracker.record("0:0", num_rows=10)
    tracker.record("1:0", num_rows=10)
    tracker.record("2:0", num_rows=10)
    # Consumer surfaced only 15 of the 30 recorded rows -> the two newest
    # deliveries roll back entirely (whole deliveries only).
    assert tracker.counts_rolled_back_to(15) == {"9:0": 1, "0:0": 1}
    assert tracker.counts_rolled_back_to(30) == {
        "9:0": 1, "0:0": 1, "1:0": 1, "2:0": 1}
    assert tracker.counts_rolled_back_to(0) == {"9:0": 1}
    assert tracker.total_rows_recorded() == 30


def test_loader_state_dict_rejects_shuffle_buffer(petastorm_dataset):
    from petastorm_tpu.jax_utils import make_jax_dataloader

    reader = make_reader(petastorm_dataset.url, num_epochs=1,
                         reader_pool_type="dummy")
    with make_jax_dataloader(reader, batch_size=4, stage_to_device=False,
                             shuffle_buffer_size=16) as loader:
        next(iter(loader))
        with pytest.raises(ValueError, match="shuffle_buffer_size"):
            loader.state_dict()


def test_reset_raises_on_resumed_reader(petastorm_dataset):
    _, state = _read_ids_with_interrupt(petastorm_dataset.url, stop_after=3,
                                        num_epochs=1,
                                        reader_pool_type="dummy")
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy",
                     resume_state=state) as reader:
        for _ in reader:
            pass
        with pytest.raises(NotImplementedError, match="resumed reader"):
            reader.reset()


def test_loader_state_dict_excludes_buffered_rows(petastorm_dataset):
    """Checkpoint mid-training through the loader: rows sitting in the
    loader's prefetch buffers must be re-read on resume."""
    from petastorm_tpu.jax_utils import make_jax_dataloader

    reader = make_reader(petastorm_dataset.url, num_epochs=1,
                         reader_pool_type="dummy", shuffle_row_groups=False)
    with make_jax_dataloader(reader, batch_size=4, stage_to_device=False,
                             host_prefetch=8) as loader:
        it = iter(loader)
        first = next(it)
        import time
        time.sleep(0.3)  # let the producer run ahead into its buffers
        state = loader.state_dict()
        first_ids = {int(i) for i in first["id"]}

    reader2 = make_reader(petastorm_dataset.url, num_epochs=1,
                          reader_pool_type="dummy", shuffle_row_groups=False,
                          resume_state=state)
    with make_jax_dataloader(reader2, batch_size=4, stage_to_device=False,
                             last_batch="keep") as loader2:
        resumed_ids = {int(i) for b in loader2 for i in b["id"]}
    all_ids = {int(r["id"]) for r in petastorm_dataset.rows}
    # Nothing buffered-but-unyielded is lost: only the 4 yielded rows may be
    # missing from the resumed stream.
    assert first_ids | resumed_ids == all_ids


def test_reset_resets_delivery_accounting(petastorm_dataset):
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy") as reader:
        assert sum(1 for _ in reader) > 0
        reader.reset()
        consumed = 0
        for row in reader:
            consumed += 1
            if consumed == 5:
                state = reader.state_dict()
        assert consumed > 5
    # The post-reset checkpoint describes the second pass only: resuming it
    # yields the not-yet-delivered remainder, not an empty stream.
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     reader_pool_type="dummy",
                     resume_state=state) as reader:
        assert sum(1 for _ in reader) > 0


def test_resume_rejects_different_filters(scalar_dataset):
    from petastorm_tpu import make_batch_reader

    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           reader_pool_type="dummy",
                           filters=[("id", "<", 20)]) as reader:
        next(iter(reader))
        state = reader.state_dict()
    with pytest.raises(ValueError, match="planning"):
        make_batch_reader(scalar_dataset.url, num_epochs=1,
                          reader_pool_type="dummy",
                          filters=[("id", ">=", 10)], resume_state=state)


def test_resume_rejects_different_dataset(petastorm_dataset, tmp_path):
    from petastorm_tpu.test_util.dataset_factory import create_test_dataset

    _, state = _read_ids_with_interrupt(petastorm_dataset.url, stop_after=3,
                                        num_epochs=1,
                                        reader_pool_type="dummy")
    other_url = f"file://{tmp_path}/other_ds"
    create_test_dataset(other_url, rows_count=30, rows_per_row_group=10)
    with pytest.raises(ValueError, match="dataset_path"):
        make_reader(other_url, num_epochs=1, reader_pool_type="dummy",
                    resume_state=state)


def test_resumed_reader_declines_equal_step_derivation(petastorm_dataset):
    from petastorm_tpu.jax_utils.sharding import (
        derive_equal_step_max_batches,
    )

    _, state = _read_ids_with_interrupt(petastorm_dataset.url, stop_after=3,
                                        num_epochs=1, cur_shard=0,
                                        shard_count=1,
                                        reader_pool_type="dummy")
    with make_reader(petastorm_dataset.url, num_epochs=1, cur_shard=0,
                     shard_count=1, reader_pool_type="dummy",
                     resume_state=state) as reader:
        with pytest.warns(UserWarning, match="resumed reader"):
            assert derive_equal_step_max_batches(reader, 4) is None
