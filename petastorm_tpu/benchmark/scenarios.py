"""Named benchmark scenarios from BASELINE.md's config list.

The reference's headline workload shapes, runnable on synthetic data via
``python -m petastorm_tpu.benchmark scenario <name>``:

- ``tabular`` — config #3 (Criteo-DLRM-like): a wide Arrow schema (dense
  floats + integer categoricals) read through ``make_batch_reader``,
  measuring the row-group predicate-pushdown win: ``filters`` prune row
  groups from Parquet statistics before any byte of data is read.
- ``ngram`` — config #4 (multi-frame video/lidar): timestamped
  ``NdarrayCodec`` frames windowed by :class:`~petastorm_tpu.ngram.NGram`
  with a ``delta_threshold``, measuring windows/sec through ``make_reader``.
- ``image`` — config #2 (ImageNet-shaped ``CompressedImageCodec``): row vs
  columnar decode images/sec plus the loader's input-stall %.
- ``weighted`` — config #5 (multi-corpus shuffle): throughput and empirical
  mix ratio through ``WeightedSamplingReader``.
- ``converter_mixing`` — config #5 end-to-end: ``make_spark_converter``
  materialization -> per-corpus batch readers -> weighted mix ->
  ``make_jax_dataloader`` (the whole pipeline, not just the sampler).
- ``packed`` — ragged-sequence delivery: ``make_packed_jax_dataloader``
  tokens/sec plus packed-vs-padded slot utilization (the attention-FLOP
  waste packing removes).

Each scenario materializes its own synthetic dataset (unless given a url),
runs the measurement, and returns a flat dict of numbers (the CLI prints it
as one JSON line, same contract as the repo-root ``bench.py``).
"""

from __future__ import annotations

import shutil
import tempfile
import time

import numpy as np

DEFAULT_TABULAR_ROWS = 40_000
DEFAULT_TABULAR_DAYS = 8
DEFAULT_NGRAM_FRAMES = 2_000


def _invariant_failure(message):
    """Build the chaos-invariant RuntimeError AND dump the flight
    recorder first (telemetry/flight.py): the violation's postmortem —
    the ring of control-plane events right up to the failed check — is
    written to disk and its path appended to the error, so a red chaos
    run (or the fuzzer's shrunk reproducer) always ships its own
    evidence."""
    from petastorm_tpu.telemetry.flight import RECORDER

    RECORDER.note("scenario.invariant_violation", error=message[:200])
    path = RECORDER.dump("invariant-violation")
    if path:
        message += f"; flight recorder dump: {path}"
    return RuntimeError(message)


# ---------------------------------------------------------------------------
# Scenario: wide-schema tabular with predicate pushdown (config #3)
# ---------------------------------------------------------------------------

def make_tabular_dataset(dataset_url, rows=DEFAULT_TABULAR_ROWS,
                         dense_cols=13, sparse_cols=26,
                         days=DEFAULT_TABULAR_DAYS):
    """Materialize a Criteo-shaped plain-Parquet dataset.

    Rows are written clustered by ``day`` (one row group per day chunk), so a
    ``filters=[("day", "=", k)]`` scan can prune (days-1)/days of the file
    from statistics alone — the property the scenario measures.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.fs_utils import FilesystemResolver

    resolver = FilesystemResolver(dataset_url)
    fs = resolver.filesystem()
    path = resolver.get_dataset_path()
    fs.create_dir(path, recursive=True)

    rng = np.random.RandomState(7)
    day = np.repeat(np.arange(days, dtype=np.int32), rows // days)
    rows = len(day)  # trim to an exact multiple
    columns = {"day": day,
               # Unique per-row key: lets the service/chaos scenarios check
               # delivery invariants (no lost rows, no duplicates) instead
               # of trusting row counts.
               "sample_index": np.arange(rows, dtype=np.int64),
               "label": rng.randint(0, 2, rows).astype(np.int32)}
    for i in range(dense_cols):
        columns[f"dense_{i}"] = rng.rand(rows).astype(np.float32)
    for i in range(sparse_cols):
        columns[f"cat_{i}"] = rng.randint(0, 10_000, rows).astype(np.int64)
    table = pa.table(columns)
    with fs.open_output_stream(path.rstrip("/") + "/part-00000.parquet") as f:
        # One row group per day: clustering is what makes stats selective.
        pq.write_table(table, f, row_group_size=rows // days)
    return rows


def tabular_predicate_scenario(dataset_url=None, rows=DEFAULT_TABULAR_ROWS,
                               days=DEFAULT_TABULAR_DAYS, workers=3):
    """Full scan vs predicate-pushdown scan over the wide tabular dataset."""
    from petastorm_tpu.reader.reader import make_batch_reader

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_tabular_")
        dataset_url = f"file://{tmpdir}/ds"
        rows = make_tabular_dataset(dataset_url, rows=rows, days=days)

    def scan(**kwargs):
        seen = 0
        t0 = time.perf_counter()
        with make_batch_reader(dataset_url, reader_pool_type="thread",
                               workers_count=workers, num_epochs=1,
                               shuffle_row_groups=False, **kwargs) as reader:
            rowgroups = reader.diagnostics["rowgroups_total"]
            for batch in reader:
                # column-batch namedtuple: every field is an equal-length array
                seen += len(batch[0])
        return seen, time.perf_counter() - t0, rowgroups

    try:
        full_rows, full_s, full_rg = scan()
        sel_rows, sel_s, sel_rg = scan(filters=[("day", "=", 1)])
        return {
            "scenario": "tabular_predicate_pushdown",
            "rows": full_rows,
            "full_scan_rows_per_sec": round(full_rows / full_s, 1),
            "pushdown_rows_per_sec": round(sel_rows / sel_s, 1),
            "full_scan_rowgroups": full_rg,
            "pushdown_rowgroups": sel_rg,
            "rowgroups_pruned_pct": round(100.0 * (1 - sel_rg / full_rg), 1),
            "pushdown_wall_speedup": round(full_s / sel_s, 2),
        }
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Scenario: NGram multi-frame windows (config #4)
# ---------------------------------------------------------------------------

def make_ngram_dataset(dataset_url, frames=DEFAULT_NGRAM_FRAMES,
                       frame_shape=(32, 32, 3)):
    """Materialize a timestamped frame sequence (video/lidar stand-in)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("FrameSchema", [
        UnischemaField("ts", np.int64, (), ScalarCodec(), False),
        UnischemaField("frame", np.float32, frame_shape, NdarrayCodec(), False),
        UnischemaField("ego_speed", np.float32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(11)

    def rows():
        for t in range(frames):
            yield {"ts": np.int64(t),
                   "frame": rng.rand(*frame_shape).astype(np.float32),
                   "ego_speed": np.float32(rng.rand())}

    materialize_rows(dataset_url, schema, rows(), rows_per_row_group=256)
    return schema


def ngram_window_scenario(dataset_url=None, frames=DEFAULT_NGRAM_FRAMES,
                          window=5, workers=3):
    """Windows/sec through make_reader + NGram (sort + delta_threshold)."""
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.reader.reader import make_reader

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_ngram_")
        dataset_url = f"file://{tmpdir}/ds"
        make_ngram_dataset(dataset_url, frames=frames)

    fields = {i: ["ts", "frame", "ego_speed"] for i in range(window)}
    ngram = NGram(fields, delta_threshold=1, timestamp_field="ts")
    try:
        windows = 0
        t0 = time.perf_counter()
        with make_reader(dataset_url, schema_fields=ngram, num_epochs=1,
                         reader_pool_type="thread", workers_count=workers,
                         shuffle_row_groups=False) as reader:
            for w in reader:
                windows += 1
                assert len(w) == window
        wall = time.perf_counter() - t0
        return {
            "scenario": "ngram_windows",
            "frames": frames,
            "window_length": window,
            "windows": windows,
            "windows_per_sec": round(windows / wall, 1),
            "frames_per_sec": round(windows * window / wall, 1),
        }
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Scenario: image classification input pipeline (config #2)
# ---------------------------------------------------------------------------

def make_image_dataset(dataset_url, rows=1024, image_shape=(64, 64, 3),
                       num_classes=10):
    """Materialize an ImageNet-shaped dataset (CompressedImageCodec)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import (CompressedImageCodec,
                                             ScalarCodec)
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("ImageSchema", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("image", np.uint8, image_shape,
                       CompressedImageCodec("jpeg"), False),
        UnischemaField("label", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(3)

    def rows_gen():
        for i in range(rows):
            yield {"id": i,
                   "image": rng.randint(0, 255, image_shape, dtype=np.uint8),
                   "label": np.int32(i % num_classes)}

    materialize_rows(dataset_url, schema, rows_gen(),
                     rows_per_row_group=128)


def image_pipeline_scenario(dataset_url=None, rows=1024, workers=3,
                            batch_size=128, device_stage="off",
                            device_prefetch=2, json_out=None):
    """Row vs columnar decode throughput + loader stall on an image schema.

    The config-#2 shape (ImageNet + CompressedImageCodec): the number that
    matters is images/sec through the full delivery path and the columnar
    path's decode advantage over the reference's per-row architecture.

    ``device_stage="on"`` adds the accelerator-side decode leg
    (``docs/guides/device_decode.md``): the same columnar stream through
    ``make_jax_dataloader`` with a :class:`DeviceStage` — raw uint8 staged,
    cast + normalize fused on the device, ``device_prefetch`` batches
    double-buffered in flight — reporting its images/sec, measured
    ``h2d_bytes_per_image``, and dispatch overlap. ``json_out`` appends
    the result (knobs included) as one JSON line, BENCH-style.
    """
    from petastorm_tpu.jax_utils import DeviceStage, make_jax_dataloader
    from petastorm_tpu.jax_utils.batcher import batch_iterator
    from petastorm_tpu.reader.reader import make_columnar_reader, make_reader

    if device_stage not in ("on", "off"):
        raise ValueError(f"device_stage must be on|off, got {device_stage!r}")
    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_image_")
        dataset_url = f"file://{tmpdir}/ds"
        make_image_dataset(dataset_url, rows=rows)

    def columnar_reader():
        return make_columnar_reader(dataset_url, num_epochs=1,
                                    shuffle_row_groups=False,
                                    reader_pool_type="thread",
                                    workers_count=workers,
                                    schema_fields=["image", "label"])

    def decode_leg(factory):
        reader = factory(dataset_url, num_epochs=1, shuffle_row_groups=False,
                         reader_pool_type="thread", workers_count=workers,
                         schema_fields=["image", "label"])
        n, t0 = 0, time.perf_counter()
        with reader:
            for batch in batch_iterator(reader, batch_size,
                                        last_batch="drop"):
                n += batch_size
        return n, n / (time.perf_counter() - t0)

    try:
        measured_rows, row_ips = decode_leg(make_reader)
        if measured_rows == 0:
            raise ValueError(
                f"Dataset at {dataset_url} yields no full batch of "
                f"{batch_size} rows — pass a smaller batch size")
        _, col_ips = decode_leg(make_columnar_reader)
        with make_jax_dataloader(columnar_reader(), batch_size,
                                 stage_to_device=False) as loader:
            n = sum(1 for _ in loader)
            stall = loader.diagnostics["input_stall_pct"]
        result = {
            "scenario": "image_pipeline",
            "rows": measured_rows,  # full batches measured (drop policy)
            "row_decode_images_per_sec": round(row_ips, 1),
            "columnar_decode_images_per_sec": round(col_ips, 1),
            "columnar_vs_row": round(col_ips / row_ips, 2),
            "loader_batches": n,
            "loader_input_stall_pct": stall,
            "device_stage": device_stage,
            "device_prefetch": device_prefetch,
        }
        if device_stage == "on":
            stage = DeviceStage(normalize=(127.5, 127.5))

            def device_stage_pass():
                loader = make_jax_dataloader(columnar_reader(), batch_size,
                                             last_batch="drop",
                                             non_tensor_policy="drop",
                                             device_prefetch=device_prefetch,
                                             device_stage=stage)
                rows_seen, t0 = 0, time.perf_counter()
                with loader:
                    for batch in loader:
                        rows_seen += batch_size
                return rows_seen, time.perf_counter() - t0, loader

            # Warm pass first: the fused kernel's jit compile (and page
            # cache) would otherwise ride inside the one timed window.
            device_stage_pass()
            n_rows, wall, loader = device_stage_pass()
            diag = loader.diagnostics
            result.update({
                "device_stage_images_per_sec": round(n_rows / wall, 1),
                "device_stage_input_stall_pct": diag["input_stall_pct"],
                "dispatch_overlap_pct": diag["dispatch_overlap_pct"],
                "h2d_bytes_per_image": round(
                    diag["h2d_bytes"] / max(1, diag["rows"]), 1),
            })
        if json_out:
            import json

            with open(json_out, "a", encoding="utf-8") as f:
                f.write(json.dumps(result) + "\n")
        return result
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Scenario: weighted multi-corpus mixing (config #5)
# ---------------------------------------------------------------------------

def weighted_mixing_scenario(dataset_url=None, rows=8_192, workers=2,
                             weights=(0.8, 0.2)):
    """Throughput + empirical mix ratio through WeightedSamplingReader.

    The config-#5 shape: several corpora mixed by sampling probability, each
    corpus row-group-sharded per host (here: two synthetic corpora tagged by
    a ``corpus`` column; the reported ratio should track ``weights``).
    ``dataset_url``: optional base url; corpora are written under it.
    """
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader

    schema = Unischema("MixSchema", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("corpus", np.int32, (), ScalarCodec(), False),
        UnischemaField("value", np.float32, (32,), None, False),
    ])

    urls = [f"{dataset_url.rstrip('/')}/corpus_{c}"
            for c in range(len(weights))] if dataset_url else None
    tmpdir = None
    if dataset_url is None:
        # Synthesize only when no url is given (a provided url must already
        # hold corpus_<i> datasets — never overwritten).
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_mix_")
        rng = np.random.RandomState(13)
        urls = []
        per_corpus = rows // len(weights)
        for corpus in range(len(weights)):
            url = f"file://{tmpdir}/corpus_{corpus}"
            rows_gen = ({"id": i, "corpus": np.int32(corpus),
                         "value": rng.rand(32).astype(np.float32)}
                        for i in range(per_corpus))
            materialize_rows(url, schema, rows_gen, rows_per_row_group=256)
            urls.append(url)

    try:
        readers = [make_reader(u, num_epochs=None, reader_pool_type="thread",
                               workers_count=workers) for u in urls]
        draws = min(rows, 4_096)
        counts = np.zeros(len(weights), np.int64)
        with WeightedSamplingReader(readers, list(weights),
                                    random_seed=17) as mixed:
            t0 = time.perf_counter()
            for _ in range(draws):
                counts[int(next(mixed).corpus)] += 1
            wall = time.perf_counter() - t0
        ratio = (counts / counts.sum()).round(3).tolist()
        return {
            "scenario": "weighted_mixing",
            "rows_drawn": int(counts.sum()),
            "rows_per_sec": round(counts.sum() / wall, 1),
            "target_weights": list(weights),
            "empirical_mix": ratio,
        }
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Scenario: converter-driven multi-corpus mixing (config #5, full pipeline)
# ---------------------------------------------------------------------------

def converter_mixing_scenario(dataset_url=None, rows=8_192,
                              weights=(0.8, 0.2), batch_size=256,
                              batches=24, workers=2):
    """Config #5 measured END-TO-END through the converter: N in-memory
    frames -> ``make_spark_converter`` (content-hash materialization) ->
    ``make_batch_reader`` per corpus -> ``WeightedSamplingReader`` mix ->
    ``make_jax_dataloader`` — throughput and empirical mix ratio of what the
    training loop actually receives (``weighted_mixing_scenario`` benches
    the sampler alone; this one pays the whole pipeline).

    ``dataset_url``: optional parent cache directory for the converter's
    materialization (default: a fresh tmpdir, removed afterwards).
    """
    import pandas as pd

    import petastorm_tpu.spark.dataset_converter as dc
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.spark.dataset_converter import (
        make_spark_converter, set_parent_cache_dir_url)
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader

    prev_cache_dir = dc._parent_cache_dir_url
    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_convmix_")
        set_parent_cache_dir_url(f"file://{tmpdir}")
    else:
        set_parent_cache_dir_url(dataset_url)
    rng = np.random.RandomState(29)
    per_corpus = rows // len(weights)
    converters, readers = [], []
    try:
        for corpus in range(len(weights)):
            frame = pd.DataFrame({
                "id": np.arange(per_corpus, dtype=np.int64),
                "corpus": np.full(per_corpus, corpus, np.int32),
                "value": rng.rand(per_corpus).astype(np.float32),
            })
            converters.append(make_spark_converter(
                frame, parquet_row_group_size_bytes=4096))
        readers = [make_batch_reader(c.cache_dir_url, num_epochs=None,
                                     reader_pool_type="thread",
                                     workers_count=workers)
                   for c in converters]
        counts = np.zeros(len(weights), np.int64)
        n_batches = 0
        with WeightedSamplingReader(readers, list(weights),
                                    random_seed=31) as mixed:
            loader = make_jax_dataloader(mixed, batch_size,
                                         max_batches=batches,
                                         stage_to_device=False)
            t0 = time.perf_counter()
            with loader:
                for batch in loader:
                    tags, tag_counts = np.unique(batch["corpus"],
                                                 return_counts=True)
                    counts[tags] += tag_counts
                    n_batches += 1
            wall = time.perf_counter() - t0
        ratio = (counts / counts.sum()).round(3).tolist()
        return {
            "scenario": "converter_mixing",
            "batches": n_batches,
            "rows_drawn": int(counts.sum()),
            "rows_per_sec": round(counts.sum() / wall, 1),
            "target_weights": list(weights),
            "empirical_mix": ratio,
        }
    finally:
        for c in converters:
            c.delete()
        set_parent_cache_dir_url(prev_cache_dir)  # restore the global
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Scenario: packed sequence delivery (ragged docs -> pack_ragged -> loader)
# ---------------------------------------------------------------------------

def packed_delivery_scenario(dataset_url=None, docs=2_048, max_len=48,
                             slot_len=96, slots=8, feature_dim=8,
                             workers=3):
    """Packed vs padded delivery of a ragged-sequence corpus: tokens/sec
    through ``make_packed_jax_dataloader`` and the slot utilization of each
    layout — the FLOP-waste number packing exists to fix (every padding
    slot burns MXU cycles at train time).

    ``dataset_url``: optional location for the generated corpus (default:
    a fresh tmpdir, removed afterwards).
    """
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.jax_utils import (PACK_SEGMENT_KEY,
                                         make_packed_jax_dataloader,
                                         packed_valid_mask)
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("PackedBench", [
        UnischemaField("seq", np.float32, (max_len, feature_dim),
                       NdarrayCodec(), False),
        UnischemaField("length", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(41)

    def rows():
        for _ in range(docs):
            n = int(rng.randint(4, max_len + 1))
            seq = np.zeros((max_len, feature_dim), np.float32)
            seq[:n] = rng.rand(n, feature_dim)
            yield {"seq": seq, "length": np.int32(n)}

    tmpdir = None
    if dataset_url is None:
        # Synthesize only when no dataset was supplied — --dataset-url
        # reuses an existing ragged corpus (seq + length columns), like
        # every other scenario.
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_packed_")
        dataset_url = f"file://{tmpdir}/ds"
        materialize_rows(dataset_url, schema, rows(),
                         rows_per_row_group=256)
    try:
        reader = make_columnar_reader(dataset_url, num_epochs=1,
                                      shuffle_row_groups=False,
                                      workers_count=workers)
        from petastorm_tpu.jax_utils import PACK_POSITION_KEY

        loader = make_packed_jax_dataloader(
            reader, slot_len=slot_len, slots=slots,
            sequence_fields=["seq"], length_field="length",
            stage_to_device=False)
        valid = total = batches = doc_count = observed_max = 0
        t0 = time.perf_counter()
        with loader:
            for batch in loader:
                seg = batch[PACK_SEGMENT_KEY]
                pos = batch[PACK_POSITION_KEY]
                mask = packed_valid_mask(seg)
                valid += int(mask.sum())
                total += seg.size
                batches += 1
                # Positions encode the doc structure for free: each doc
                # contributes exactly one valid position-0 token, and the
                # longest doc is max(position) + 1 (vectorized — keeps the
                # timed region free of per-segment Python loops).
                doc_count += int(((pos == 0) & mask).sum())
                if mask.any():
                    observed_max = max(observed_max, int(pos.max()) + 1)
        wall = time.perf_counter() - t0
        # The padded alternative pads every doc to the dataset's STATIC
        # on-disk max length (the sequence field's schema shape) — the
        # run-invariant baseline; longest-observed-length is the fallback
        # only when the schema leaves the length dimension open.
        field = reader.schema.fields["seq"]
        static_max = (field.shape[0]
                      if field.shape and field.shape[0] is not None
                      else None)
        pad_len = static_max if static_max is not None else observed_max
        return {
            "scenario": "packed_delivery",
            "docs": doc_count,
            "batches": batches,
            "tokens_per_sec": round(valid / wall, 1),
            "packed_utilization": round(valid / max(total, 1), 3),
            "padded_utilization": round(
                valid / max(doc_count * pad_len, 1), 3),
            "padded_baseline": ("static_schema_max_len"
                                if static_max is not None
                                else "longest_observed_doc"),
        }
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


def _parse_predicate(spec):
    """``FIELD:OP:VALUE[:MODULUS]`` (or a ColumnPredicate / wire dict) →
    :class:`~petastorm_tpu.predicates.ColumnPredicate`. VALUE parses as
    int, then float, then string; ``in``/``not-in`` take a
    comma-separated VALUE list."""
    if spec is None:
        return None
    from petastorm_tpu.predicates import ColumnPredicate

    if isinstance(spec, ColumnPredicate):
        return spec
    if isinstance(spec, dict):
        return ColumnPredicate.from_wire(spec)

    def scalar(text):
        for cast in (int, float):
            try:
                return cast(text)
            except ValueError:
                continue
        return text

    parts = str(spec).split(":")
    if len(parts) not in (3, 4):
        raise ValueError(
            f"--predicate must be FIELD:OP:VALUE[:MODULUS], got {spec!r}")
    field, op, value = parts[0], parts[1], parts[2]
    parsed = ([scalar(v) for v in value.split(",")]
              if op in ("in", "not-in") else scalar(value))
    modulus = int(parts[3]) if len(parts) == 4 else None
    return ColumnPredicate(field, op, parsed, modulus=modulus)


# ---------------------------------------------------------------------------
# Scenario: disaggregated data service, loopback (dispatcher + workers +
# client all on 127.0.0.1 — the serving tier's overhead vs a local reader)
# ---------------------------------------------------------------------------

def service_loopback_scenario(dataset_url=None, rows=DEFAULT_TABULAR_ROWS,
                              days=DEFAULT_TABULAR_DAYS, workers=2,
                              batch_size=512, mode="static", skew_ms=0.0,
                              credits=8, json_out=None, chaos=None,
                              chaos_interval_s=1.5, chaos_max_events=4,
                              chaos_seed=None, failpoint_points=None,
                              failpoint_window=None,
                              failpoint_delay_s=None,
                              failpoint_targets=None,
                              failpoint_max_fires=None,
                              journal_dir=None,
                              metrics_port=None,
                              trace_out=None, epochs=1, cache="off",
                              cache_mem_mb=256.0, cache_dir=None,
                              fleet_cache=False,
                              fleet_cache_drain_after=None,
                              sharding=None, shuffle_seed=None,
                              ordered=False, predicate=None,
                              filter_placement="client", transport=None,
                              hedging=False, hedge_floor_s=0.25,
                              hedge_min_samples=16, hedge_quantile=0.99,
                              hedge_multiplier=4.0, brownout=None):
    """Rows/sec through the full disaggregated path: dispatcher + ``workers``
    batch workers + one client, all over loopback TCP, streamed into
    ``JaxDataLoader`` via ``ServiceBatchSource`` — against the same dataset
    read by a local ``make_batch_reader`` pipeline, so the number reported
    is the serving tier's overhead (serialize → TCP → deserialize) at
    one-machine scale. ``workers`` is the number of batch workers; each runs
    a 2-thread reader pool.

    ``skew_ms`` is fault injection for the head-of-line question: the FIRST
    worker sleeps that long before every batch send. Under the multiplexed
    drain the client's throughput stays bounded by the fast workers'
    buffered output (the slow worker's stall shows up in
    ``per_worker_stall_s``, not in delivery); a blocking round-robin drain
    would serialize every fast batch behind the slow one. ``credits`` is
    the per-worker flow-control window handed to the client.

    ``transport`` pins the delivery tier for both ends of the fleet:
    ``"tcp"`` forces the framed sockets everywhere, ``"shm"``/``"auto"``
    negotiate the shared-memory ring per stream (always granted on
    loopback; ``docs/guides/service.md#transport-tiers``). Delivery
    semantics are identical across tiers, so two same-seed ``ordered``
    runs that differ only in ``transport`` must report equal
    ``stream_digest`` values — the scenario's cheap invariance check.

    ``chaos`` arms the fault-injection harness
    (:mod:`petastorm_tpu.service.chaos`): ``"dispatcher-restart"`` (crash +
    journal-replay restart on the same port), ``"worker-kill"``,
    ``"conn-drop"``, or a comma-separated mix, injected every
    ``chaos_interval_s`` while the epoch streams, at most
    ``chaos_max_events`` times (``None`` = unbounded — note that repeated
    ``conn-drop`` restarts every in-flight piece set, so an unbounded
    drop rate faster than a piece set streams never converges).
    ``"failpoints"`` is different in kind: instead of timed external
    events it arms the process-wide **seeded failpoint schedule**
    (:mod:`petastorm_tpu.failpoints`) for the run — torn frames and
    connection resets inside the framed transport, dropped dispatcher
    replies AFTER the state mutation applied, WAL append/fsync ENOSPC,
    damaged cache-entry writes — each fired at call indices derived from
    ``chaos_seed``, so two runs of one seed inject the identical fault
    sequence (the injection log lands in the result as
    ``failpoint_injections``). ``chaos_seed`` also drives the TIMED
    kinds' event sequence (action choice + interval jitter via the seed
    tree), making every chaos run reproducible from its ``--json-out``
    line. The scenario then checks
    delivery invariants on the dataset's unique ``sample_index`` — zero
    lost rows always; zero duplicates too when only the control plane was
    perturbed (dispatcher restarts) — and RAISES if they are violated, so
    a chaos run doubles as an acceptance check. All workers are paced
    ~30 ms/batch under chaos so the epoch outlasts the injections.
    Recovery counters land in the result (``dispatcher_recovery``,
    ``client_recovery``, ``chaos_events``).

    The result is BENCH-style (``metric``/``value``/``unit``/
    ``vs_baseline`` + detail keys, one JSON object); ``json_out`` appends
    it as one JSON line to that path so skew/loopback numbers land in the
    perf trajectory instead of stdout only. The ``telemetry`` key carries
    the final metrics-registry snapshot plus per-stage p50/p99 latency
    quantiles from the loader histograms — distributions, not just means.

    ``metrics_port`` serves the process's metrics registry in Prometheus
    text format for the run's duration (0 picks a free port; the bound
    address lands in the result as ``metrics_address``). ``trace_out``
    arms batch-lifecycle tracing and writes Perfetto-loadable Chrome
    ``trace_event`` JSON there: every batch id carries contiguous spans
    from worker decode through client queue to device dispatch
    (``docs/guides/diagnostics.md#metrics-and-tracing``).

    ``epochs`` streams the dataset that many times through ONE loader
    iteration (dispatcher-owned epoch tracking), and the result carries a
    per-epoch breakdown (``epochs_detail``: rows, wall, rows/s, and the
    fleet's cache hit rate within each epoch) — the cold-vs-warm epoch
    trajectory. ``cache`` arms the workers' decoded-batch cache
    (``off`` | ``mem`` | ``mem+disk``; ``docs/guides/caching.md``) with
    ``cache_mem_mb`` of host RAM per worker; under ``mem+disk`` every
    worker shares ``cache_dir`` (default: a scenario-owned tempdir), so a
    takeover after ``--chaos worker-kill`` re-serves the victim's pieces
    from the disk tier instead of re-decoding them. The ``cache-corrupt``
    chaos kind (requires ``mem+disk`` and ``epochs >= 2``; clamps the
    memory tier to ~0 so warm loads actually read the damaged disk files)
    truncates / bit-flips disk-tier entry files mid-run and asserts the
    fleet counted at least one ``cache_corrupt_entries`` while delivery
    stayed intact — corrupt entries degrade to fresh decode, never to bad
    bytes.

    ``hedging`` arms the client's hedged watermark re-serves
    (``docs/guides/service.md#failure-model-and-recovery``): a stream
    silent past the fitted inter-batch-gap threshold gets its in-flight
    piece re-granted at its watermark from a peer worker, first
    ``piece_done`` wins, duplicates drop through the exactly-once dedup
    — so a hedged run's ``stream_digest`` must equal the unhedged
    same-seed run's. ``hedge_floor_s``/``hedge_min_samples``/``hedge_quantile``/
    ``hedge_multiplier`` tune the trigger for short benchmark epochs
    (with a few dozen gap samples the p99 IS the injected stall —
    fitting ``quantile=0.5`` keeps the threshold anchored to the
    healthy gap scale); the race tallies land in the result as
    ``hedge_counts``. ``brownout`` arms the dispatcher's
    journaled overload-shedding state machine (``True`` for defaults or
    a config dict — see :class:`petastorm_tpu.service.resilience.\
BrownoutConfig`).

    ``shuffle_seed`` arms the dispatcher's seed-tree deterministic
    shuffle; ``ordered`` re-sequences client delivery into the canonical
    piece order. The result always carries ``stream_digest`` — an
    order-sensitive blake2b of every delivered batch's bytes — so two
    ``--json-out`` lines assert run-to-run determinism by string
    equality (byte-identity needs ``ordered``; without it the digest
    still certifies WHAT arrived, not the interleaving). Chaos delivery
    invariants are exactly-once on every path: zero lost rows AND zero
    duplicates under dispatcher restarts, worker kills, and connection
    drops alike (per-piece watermarks re-grant at the delivery cursor;
    ``docs/guides/service.md#delivery-semantics``).
    """
    from petastorm_tpu.jax_utils.batcher import batch_iterator
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_batch_reader
    from petastorm_tpu.service import (BatchWorker, Dispatcher,
                                       ServiceBatchSource)
    from petastorm_tpu.service.chaos import (CHAOS_KINDS, ChaosInjector,
                                             StreamDigest,
                                             cache_corrupt_action,
                                             connection_drop_action,
                                             delivery_invariants,
                                             dispatcher_restart_action,
                                             job_cancel_action,
                                             worker_drain_action,
                                             worker_kill_action)

    # --sharding is the canonical knob name (static|fcfs|dynamic); `mode`
    # stays as the original spelling.
    mode = sharding or mode
    if mode not in ("static", "fcfs", "dynamic"):
        raise ValueError(
            f"sharding must be static|fcfs|dynamic, got {mode!r}")
    # --predicate FIELD:OP:VALUE[:MODULUS] — a declared row filter
    # (docs/guides/pipeline.md#graph-rewrites). --filter-placement picks
    # the topology: "client" masks received batches trainer-side (the
    # baseline), "worker" hoists the filter below the workers' decode.
    predicate_obj = _parse_predicate(predicate)
    if filter_placement not in ("client", "worker"):
        raise ValueError(
            f"filter-placement must be client|worker, got "
            f"{filter_placement!r}")
    # --transport auto|tcp|shm pins the delivery tier for BOTH ends of
    # the loopback fleet (docs/guides/service.md#transport-tiers);
    # delivery semantics are byte-identical across tiers, so same-seed
    # ordered digests must compare equal between tcp and shm runs.
    from petastorm_tpu.service.transport import resolve_mode

    transport = resolve_mode(transport)
    chaos_kinds = ([k.strip() for k in chaos.split(",") if k.strip()]
                   if isinstance(chaos, str) else list(chaos or []))
    if predicate_obj is not None and chaos:
        raise ValueError(
            "--predicate cannot combine with --chaos: the chaos delivery "
            "invariants assert the FULL row multiset, which a row filter "
            "deliberately thins")
    for kind in chaos_kinds:
        if kind not in CHAOS_KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r}; choose from {CHAOS_KINDS}")
    if chaos_kinds and mode == "fcfs":
        raise ValueError("chaos invariants need static or dynamic sharding "
                         "(fcfs has no per-client delivery contract to "
                         "check)")
    if chaos_kinds and dataset_url is not None:
        raise ValueError(
            "chaos delivery invariants are checked against the scenario's "
            "own synthesized dataset (unique sample_index per row, known "
            "row count) — omit --dataset-url when --chaos is armed")
    if "cache-corrupt" in chaos_kinds:
        if cache != "mem+disk":
            raise ValueError(
                "--chaos cache-corrupt damages disk-tier entry files: it "
                "needs --cache mem+disk")
        if epochs < 2:
            raise ValueError(
                "--chaos cache-corrupt needs --epochs >= 2: entries fill "
                "during epoch 1 and only a warm epoch LOADS them, which "
                "is where corruption detection (and the degrade-to-fresh-"
                "decode path) runs")
        if cache_mem_mb > 1.0:
            # A roomy memory tier answers every warm lookup from RAM, so
            # the damaged disk files are never loaded and the run fails
            # its own >=1-corrupt-entry-detected assertion despite
            # nothing being wrong. This leg exists to exercise the disk
            # load path — force it.
            import logging

            logging.getLogger(__name__).warning(
                "cache-corrupt: clamping cache_mem_mb %s -> 0.001 so "
                "warm loads hit the disk tier (memory hits would never "
                "read the damaged files)", cache_mem_mb)
            cache_mem_mb = 0.001

    from petastorm_tpu.cache_impl import CacheConfig

    # Fleet cache tier (docs/guides/caching.md#fleet-cache-tier): every
    # worker joins the consistent-hash ring; --fleet-cache-drain-after N
    # drains the first worker after the client has consumed N batches —
    # a call-count trigger (not a timer), so the drain (and the warm
    # handoff it kicks off) lands at the same stream position on every
    # run of a seeded schedule.
    if fleet_cache and cache == "off":
        raise ValueError(
            "--fleet-cache places decoded-batch cache entries on the "
            "peer ring: it needs --cache mem or mem+disk")
    if fleet_cache_drain_after is not None and not fleet_cache:
        raise ValueError(
            "--fleet-cache-drain-after drives the warm-handoff path: "
            "arm --fleet-cache with it")
    if fleet_cache_drain_after is not None and workers < 2:
        raise ValueError(
            "--fleet-cache-drain-after needs >= 2 workers: a drained "
            "worker's entries must have a surviving peer to land on")

    if epochs < 1:
        raise ValueError("epochs must be >= 1")
    if epochs > 1 and mode == "fcfs":
        raise ValueError(
            "--epochs > 1 requires static or dynamic sharding: fcfs "
            "clients report no per-client epoch boundaries, so the "
            "per-epoch breakdown would silently lump every epoch into one "
            "row. fcfs is also single-tenant by construction — its one "
            "shared queue has no per-job assignment, so the dispatcher "
            "rejects register_job under it. Use --sharding dynamic for "
            "multi-epoch streams, work-stealing rebalancing, and "
            "multi-job fleets (--sharding static also supports both)")
    cache_tmp = None
    if cache == "mem+disk" and cache_dir is None:
        # One SHARED disk tier for the whole fleet (atomic-rename writes
        # make that safe): a worker-kill takeover re-serves the victim's
        # warm pieces from disk instead of re-decoding.
        cache_tmp = tempfile.mkdtemp(prefix="petastorm_tpu_batchcache_")
        cache_dir = cache_tmp
    # Constructed with the FINAL directory so CacheConfig's own
    # validation runs (e.g. --cache-dir without mem+disk is rejected).
    cache_config = CacheConfig(mode=cache, mem_mb=cache_mem_mb,
                               cache_dir=cache_dir)

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_service_")
        dataset_url = f"file://{tmpdir}/ds"
        rows = make_tabular_dataset(dataset_url, rows=rows, days=days)
    journal_tmp = None
    if chaos_kinds and journal_dir is None:
        journal_tmp = tempfile.mkdtemp(prefix="petastorm_tpu_journal_")
        journal_dir = journal_tmp

    # "failpoints" is the seeded in-process schedule, not a timed external
    # event — only the TIMED kinds need an injector thread and the pacing
    # that makes the epoch span its intervals (failpoints fire on call
    # counts, so the epoch needs no minimum wall time).
    timed_kinds = [k for k in chaos_kinds if k != "failpoints"]
    chaos_pace_s = 0.03 if timed_kinds else 0.0
    lease_timeout_s = 2.0 if chaos_kinds else 30.0

    # Flight-recorder breadcrumb (telemetry/flight.py): a chaos run that
    # dies mid-flight dumps a ring whose FIRST useful entry says what
    # configuration was running.
    from petastorm_tpu.telemetry.flight import RECORDER as _FLIGHT

    _FLIGHT.note("scenario.start", scenario="service", sharding=mode,
                 chaos=",".join(chaos_kinds) or None,
                 chaos_seed=chaos_seed, epochs=epochs)

    def make_dispatcher(host="127.0.0.1", port=0):
        # The restart factory passes the SAME shuffle_seed: the journal
        # guard refuses a seed change mid-run (it would silently shift
        # the piece order and break the determinism contract).
        return Dispatcher(host=host, port=port, mode=mode,
                          num_epochs=epochs, journal_dir=journal_dir,
                          lease_timeout_s=lease_timeout_s,
                          shuffle_seed=shuffle_seed,
                          brownout=brownout)

    # Telemetry arming and every node start happen INSIDE the try: a
    # failing dispatcher/worker start must still stop the HTTP server +
    # snapshot-ring threads and disarm the trace collector (the tier-1
    # leak guard would otherwise cascade one failure into many).
    metrics_server = None
    trace_armed = False
    dispatcher_holder = []
    fleet = []
    injector = None
    failpoint_schedule = None
    try:
        if metrics_port is not None:
            from petastorm_tpu.telemetry.http import MetricsServer

            metrics_server = MetricsServer(port=metrics_port,
                                           snapshot_interval_s=1.0).start()
        if trace_out:
            from petastorm_tpu.telemetry import tracing

            tracing.COLLECTOR.acquire()
            trace_armed = True
        dispatcher_holder.append(make_dispatcher().start())
        for i in range(workers):
            # Appended one by one so a failing start() mid-fleet still
            # leaves the already-started workers in `fleet` for teardown.
            fleet.append(BatchWorker(
                dataset_url,
                dispatcher_address=dispatcher_holder[0].address,
                batch_size=batch_size, reader_factory="batch",
                worker_id=f"bench-worker-{i}",
                batch_delay_s=max(skew_ms / 1000.0 if i == 0 else 0.0,
                                  chaos_pace_s),
                # Fleet-cache runs need snappy heartbeats too: the peer
                # ring and the drain-edge handoff both ride them.
                heartbeat_interval_s=(0.5 if (chaos_kinds or fleet_cache)
                                      else 5.0),
                batch_cache=cache_config.build(),
                fleet_cache=fleet_cache,
                transport=transport,
                reader_kwargs={"workers_count": 2}).start())
        if fleet_cache:
            # Stream only after every worker's placement ring converged
            # on the full fleet: registration seeds each joiner's ring,
            # but earlier joiners learn of later ones via heartbeat — a
            # short run racing that first tick would fill every entry
            # against a partial ring and never exercise the warm paths.
            expected = {w.worker_id for w in fleet}
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if all(set(w._fleet_tier.ring_peers()) == expected
                       for w in fleet):
                    break
                time.sleep(0.02)
            else:
                raise RuntimeError(
                    "fleet cache ring did not converge on "
                    f"{sorted(expected)} within 10s")
        source = ServiceBatchSource(
            dispatcher_holder[0].address, credits=credits, ordered=ordered,
            heartbeat_interval_s=0.3 if chaos_kinds else 2.0,
            predicate=predicate_obj,
            filter_placement=(filter_placement if predicate_obj is not None
                              else "client"),
            # Snappy rebalance loop: steal latency is what the dynamic
            # skew leg measures, and the sync RPC is a tiny control
            # message (drained workers poke the loop anyway). Every 50 ms
            # the straggler commits to ~1 more batch it could have shed.
            dynamic_sync_interval_s=0.05,
            transport=transport,
            hedging=hedging, hedge_floor_s=hedge_floor_s,
            hedge_min_samples=hedge_min_samples,
            hedge_quantile=hedge_quantile,
            hedge_multiplier=hedge_multiplier)
        loader = JaxDataLoader(None, batch_size, batch_source=source,
                               stage_to_device=False,
                               trace_path=trace_out or None)
        if "failpoints" in chaos_kinds:
            from petastorm_tpu import failpoints as failpoints_mod

            # Armed AFTER bring-up so the schedule's budget lands on the
            # streaming epoch, not on registration; derived entirely from
            # the seed, so the same --chaos-seed replays byte-identically.
            # ``failpoint_points`` restricts the armed vocabulary (the
            # fuzzer's shrinker; a comma string from the CLI); a replay
            # PIN uses ``failpoint_window`` well below every armed
            # point's call count, so both runs reach every scheduled
            # fire and the logs compare equal.
            if isinstance(failpoint_points, str):
                failpoint_points = tuple(
                    p.strip() for p in failpoint_points.split(",")
                    if p.strip())
            schedule_kwargs = {"points": failpoint_points}
            if failpoint_window is not None:
                schedule_kwargs["window"] = int(failpoint_window)
            # Straggler shaping (the overload_tail leg + hedge tests):
            # a bigger delay makes "delay" actions real stalls, targets
            # pin a point to ONE site's key (e.g. one worker id) so the
            # straggler is deterministic, max_fires sets how often.
            if failpoint_delay_s is not None:
                schedule_kwargs["delay_s"] = float(failpoint_delay_s)
            if failpoint_targets is not None:
                schedule_kwargs["targets"] = dict(failpoint_targets)
            if failpoint_max_fires is not None:
                schedule_kwargs["max_fires_per_point"] = int(
                    failpoint_max_fires)
            failpoint_schedule = failpoints_mod.arm(
                failpoints_mod.FaultSchedule(
                    chaos_seed if chaos_seed is not None else 0,
                    **schedule_kwargs))
        if timed_kinds:
            actions = []
            for kind in timed_kinds:
                if kind == "dispatcher-restart":
                    actions.append((kind, dispatcher_restart_action(
                        dispatcher_holder, make_dispatcher)))
                elif kind == "worker-kill":
                    actions.append((kind, worker_kill_action(fleet)))
                elif kind == "cache-corrupt":
                    actions.append((kind, cache_corrupt_action(cache_dir)))
                elif kind == "job-cancel":
                    actions.append((kind, job_cancel_action(
                        lambda: dispatcher_holder[0].address)))
                elif kind == "worker-drain":
                    actions.append((kind, worker_drain_action(
                        lambda: dispatcher_holder[0])))
                else:
                    actions.append((kind, connection_drop_action(
                        lambda: [dispatcher_holder[0]] + fleet)))
            injector = ChaosInjector(actions,
                                     interval_s=chaos_interval_s,
                                     max_events=(chaos_max_events
                                                 or None),
                                     seed=chaos_seed).start()
        def fleet_cache_totals():
            """Summed (hits, misses) across the fleet's batch caches, or
            ``None`` when caching is off."""
            hits = misses = 0
            armed = False
            for worker in fleet:
                stats = worker.cache_stats()
                if stats is not None:
                    armed = True
                    hits += stats["hits"]
                    misses += stats["misses"]
            return (hits, misses) if armed else None

        served_rows = batches = 0
        got_ids = []
        arrivals = []  # (elapsed_s, cumulative rows) per batch
        digest = StreamDigest()
        t0 = time.perf_counter()
        with loader:
            for batch in loader:
                batches += 1
                served_rows += len(next(iter(batch.values())))
                digest.update(batch)
                if chaos_kinds and "sample_index" in batch:
                    got_ids.extend(int(i) for i in batch["sample_index"])
                arrivals.append((time.perf_counter() - t0, served_rows))
                if fleet_cache_drain_after is not None \
                        and batches == int(fleet_cache_drain_after):
                    # Deterministic mid-stream drain: triggered by the
                    # consumed-batch count, so seeded replays drain at
                    # the identical stream position. The worker's next
                    # heartbeat sees "draining" and ships its warm
                    # entries to the peers inheriting its ring segments.
                    dispatcher_holder[0].drain_worker(
                        "bench-worker-0",
                        reason="fleet-cache scenario drain")
                    # Post-drain barrier: the handoff launches on the
                    # drained worker's next heartbeat and journals its
                    # cache_handoff record AFTER the entries shipped —
                    # waiting for the record means everything consumed
                    # from here on measures the handed-off (warm)
                    # fleet, not a race against the shipping thread.
                    # Bounded and best-effort: a handoff that never
                    # reports just leaves the rest of the stream to
                    # cold-fill, which the per-run counters expose.
                    barrier = time.monotonic() + 10.0
                    while time.monotonic() < barrier \
                            and not dispatcher_holder[0].cache_handoffs():
                        time.sleep(0.02)
        service_wall = time.perf_counter() - t0
        epoch_starts = [(int(count), int(epoch_num)) for count, epoch_num
                        in source.diagnostics["epoch_starts"]]
        # Exact per-epoch cache attribution: workers bucket every lookup
        # by the requesting stream's epoch (the stream header carries it),
        # so prefetch-ahead lookups never smear into the previous epoch.
        cache_by_epoch = {}
        for worker in fleet:
            for worker_epoch, bucket in worker.cache_stats_by_epoch().items():
                totals = cache_by_epoch.setdefault(worker_epoch,
                                                   {"hits": 0, "misses": 0})
                totals["hits"] += bucket["hits"]
                totals["misses"] += bucket["misses"]
        if injector is not None:
            injector.stop()
        # Delivery timeline: when half the rows had reached the trainer.
        # Under skew this is the head-of-line number — a blocking drain
        # paces EVERY delivery at the slow worker's rate (half at ~half the
        # wall), the multiplexed drain front-loads the fast workers'
        # batches (half at roughly the fast workers' production time).
        time_to_half = next((t for t, n in arrivals
                             if n >= served_rows / 2), service_wall)
        stall_pct = loader.diagnostics["input_stall_pct"]
        source_diag = source.diagnostics

        # Per-epoch breakdown: the client's epoch_starts give exact batch
        # boundaries in production order (= consumption order, FIFO), so
        # each epoch's rows and wall fall straight out of the arrivals
        # timeline — cold-vs-warm throughput becomes visible in BENCH
        # trajectories instead of being averaged away.
        epochs_detail = []
        # Keep the client-reported epoch NUMBER with each boundary (a
        # resumed client starts past 0; an empty epoch shares its start
        # count with the next) — the worker cache buckets are keyed by
        # that same number via the stream header, so the join is exact.
        for index, (first, epoch_num) in enumerate(epoch_starts):
            last = (epoch_starts[index + 1][0]
                    if index + 1 < len(epoch_starts) else len(arrivals))
            if first >= last:
                continue
            prev_t, prev_rows = ((0.0, 0) if first == 0
                                 else arrivals[first - 1])
            end_t, end_rows = arrivals[last - 1]
            epoch_wall = max(1e-9, end_t - prev_t)
            epoch_rows = end_rows - prev_rows
            detail = {
                "epoch": epoch_num,
                "rows": epoch_rows,
                "wall_s": round(epoch_wall, 3),
                "rows_per_s": round(epoch_rows / epoch_wall, 1),
            }
            bucket = cache_by_epoch.get(epoch_num)
            if bucket is not None:
                lookups = bucket["hits"] + bucket["misses"]
                detail["cache_hits"] = bucket["hits"]
                detail["cache_misses"] = bucket["misses"]
                detail["cache_hit_rate"] = round(
                    bucket["hits"] / lookups, 4) if lookups else None
            epochs_detail.append(detail)

        # Local baseline: the same dataset through the same collation,
        # no network tier.
        local_rows = 0
        t0 = time.perf_counter()
        with make_batch_reader(dataset_url, reader_pool_type="thread",
                               workers_count=2, num_epochs=1,
                               shuffle_row_groups=False) as reader:
            for b in batch_iterator(reader, batch_size, last_batch="keep"):
                local_rows += len(next(iter(b.values())))
        local_wall = time.perf_counter() - t0
        service_rps = round(served_rows / service_wall, 1)
        result = {
            "scenario": "service_loopback",
            # BENCH-style envelope: the headline number, named.
            "metric": "service_rows_per_sec",
            "value": service_rps,
            "unit": "rows/sec",
            "vs_baseline": round(
                (served_rows / service_wall) / (local_rows / local_wall), 2),
            "mode": mode,
            "workers": workers,
            "transport": transport,
            "skew_ms": skew_ms,
            "credits": credits,
            "epochs": epochs,
            # Determinism surface: equal digests (same seed, ordered) =
            # byte-identical delivered streams — the cheap A/B assert.
            "shuffle_seed": shuffle_seed,
            "ordered": ordered,
            "stream_digest": digest.hexdigest(),
            # Declared row filter in force (None when unfiltered):
            # placement + the delivered row count make selectivity and
            # hoist economics readable from the json line.
            "filter": ({"predicate": predicate_obj.to_wire(),
                        "placement": filter_placement,
                        "rows_delivered": served_rows}
                       if predicate_obj is not None else None),
            "duplicates_dropped":
                source_diag["recovery"]["duplicates_dropped"],
            # Hedged re-serve race tallies (all zero when hedging is off
            # or no stream ever went silent past the fitted threshold).
            "hedging": hedging,
            "hedge_counts": dict(
                source_diag["resilience"]["hedge_counts"]),
            "epochs_detail": epochs_detail,
            "rows": served_rows,
            "batches": batches,
            "service_rows_per_sec": service_rps,
            "service_wall_s": round(service_wall, 3),
            "time_to_half_rows_s": round(time_to_half, 3),
            "local_rows_per_sec": round(local_rows / local_wall, 1),
            "service_vs_local": round(
                (served_rows / service_wall) / (local_rows / local_wall), 2),
            "loader_input_stall_pct": stall_pct,
            "per_worker_batches": {
                wid: counters["batches"]
                for wid, counters in source_diag["per_worker"].items()},
            "per_worker_stall_s": {
                wid: counters["stall_s"]
                for wid, counters in source_diag["per_worker"].items()},
            "per_worker_pieces": {
                wid: counters.get("pieces", 0)
                for wid, counters in source_diag["per_worker"].items()},
        }
        if mode == "dynamic":
            recovery = source_diag.get("recovery", {})
            result["steals_applied"] = recovery.get("steals_applied", 0)
            result["steals_failed"] = recovery.get("steals_failed", 0)
            result["dedup_dropped"] = recovery.get("dedup_dropped", 0)
        if cache != "off":
            totals = fleet_cache_totals() or (0, 0)
            per_worker_stats = [w.cache_stats() for w in fleet]
            result["cache"] = {
                "mode": cache,
                "mem_mb": cache_mem_mb,
                "dir": cache_dir,
                "hits": totals[0],
                "misses": totals[1],
                "hit_rate": round(totals[0] / max(1, sum(totals)), 4),
                "bytes_mem": sum(s["bytes_mem"]
                                 for s in per_worker_stats if s),
                "evictions_mem": sum(s["evictions_mem"]
                                     for s in per_worker_stats if s),
                "evictions_disk": sum(s["evictions_disk"]
                                      for s in per_worker_stats if s),
                "corrupt_entries": sum(s.get("corrupt_entries", 0)
                                       for s in per_worker_stats if s),
                # Shuffle-compatible serving: entries that went out
                # through a seed-tree serve-time permutation (nonzero iff
                # --shuffle-seed and a warm tier met), and old-format
                # entries evicted by the version check.
                "permuted_serves": sum(s.get("permuted_serves", 0)
                                       for s in per_worker_stats if s),
                "version_evicted": sum(s.get("version_evicted", 0)
                                       for s in per_worker_stats if s),
            }
            if fleet_cache:
                # Fleet-tier attribution: remote warmth movement (peer
                # fetches, placement pushes, drain handoffs) summed
                # across the fleet — cold re-decodes avoided by the
                # ring show up here, not in the local hit counters.
                result["cache"]["fleet"] = {
                    "remote_hits": sum(s.get("remote_hits", 0)
                                       for s in per_worker_stats if s),
                    "remote_misses": sum(s.get("remote_misses", 0)
                                         for s in per_worker_stats if s),
                    "remote_errors": sum(s.get("remote_errors", 0)
                                         for s in per_worker_stats if s),
                    "breaker_skips": sum(s.get("breaker_skips", 0)
                                         for s in per_worker_stats if s),
                    "pushes_sent": sum(s.get("pushes_sent", 0)
                                       for s in per_worker_stats if s),
                    "handoff_entries_sent": sum(
                        s.get("handoff_entries_sent", 0)
                        for s in per_worker_stats if s),
                    "handoff_entries_received": sum(
                        s.get("handoff_entries_received", 0)
                        for s in per_worker_stats if s),
                    "drained_after_batches": fleet_cache_drain_after,
                }
        # Final registry snapshot + per-stage latency quantiles: BENCH
        # artifacts capture distributions (p50/p99), not just means.
        from petastorm_tpu.telemetry import REGISTRY as _registry

        result["telemetry"] = {
            "stage_quantiles_s": loader.stage_quantiles(),
            "registry": _registry.snapshot(),
        }
        if metrics_server is not None:
            result["metrics_address"] = list(metrics_server.address)
        if trace_out:
            result["trace_out"] = trace_out
        if chaos_kinds:
            # Exactly-once on EVERY path: per-piece watermarks re-grant a
            # re-served piece at the delivery cursor (worker-kill
            # takeover, conn-drop retry) and journal replay restores the
            # control plane (dispatcher restart), so zero lost rows AND
            # zero duplicates is the contract under all chaos kinds — the
            # pre-watermark harness only promised at-least-once off the
            # steal path.
            allow_duplicates = False
            # Every epoch delivers the full id set once: the expected
            # multiset scales with the epoch count.
            invariants = delivery_invariants(
                list(range(rows)) * epochs, got_ids, allow_duplicates)
            status = source.dispatcher_status()
            recovery = status.get("recovery", {})
            chaos_events = injector.events if injector is not None else []
            injection_log = (failpoint_schedule.log_snapshot()
                             if failpoint_schedule is not None else [])
            result.update({
                "chaos": ",".join(chaos_kinds),
                "chaos_seed": chaos_seed,
                "chaos_events": chaos_events,
                "chaos_errors": (injector.errors
                                 if injector is not None else []),
                "chaos_pace_s": chaos_pace_s,
                "failpoint_injections": injection_log,
                "lost_rows": invariants["lost_rows"],
                "duplicate_rows": invariants["duplicate_rows"],
                "fencing_epoch": status.get("fencing_epoch"),
                "dispatcher_recovery": recovery,
                "client_recovery": source.diagnostics.get("recovery", {}),
            })
            if not invariants["ok"]:
                raise _invariant_failure(
                    f"chaos run violated delivery invariants: "
                    f"{invariants['lost_rows']} lost rows, "
                    f"{invariants['duplicate_rows']} duplicates "
                    f"(allow_duplicates={allow_duplicates}); seed: "
                    f"{chaos_seed}; events: {chaos_events}; "
                    f"failpoints: {injection_log}")
            if "failpoints" in chaos_kinds and failpoint_points is None \
                    and not injection_log:
                raise _invariant_failure(
                    "failpoints chaos ran but the schedule fired nothing "
                    "— the run proved no robustness (too-short epoch "
                    "never reached the seeded fire indices, or the "
                    "failpoints were compiled out)")
            if "dispatcher-restart" in chaos_kinds and (
                    recovery.get("journal_replays", 0) < 1
                    or recovery.get("fencing_bumps", 0) < 1):
                raise _invariant_failure(
                    f"dispatcher-restart chaos recorded no recovery: "
                    f"{recovery} (events: {chaos_events})")
            if "cache-corrupt" in chaos_kinds and (
                    result["cache"]["corrupt_entries"] < 1):
                raise _invariant_failure(
                    "cache-corrupt chaos ran but no worker counted a "
                    "corrupt entry: either no injection landed on an "
                    "entry a warm epoch later loaded, or — the bug this "
                    "guard exists for — a damaged entry was served "
                    f"without detection (events: {chaos_events})")
        if json_out:
            import json

            with open(json_out, "a", encoding="utf-8") as f:
                f.write(json.dumps(result) + "\n")
        return result
    finally:
        if injector is not None:
            injector.stop()
        if failpoint_schedule is not None:
            from petastorm_tpu import failpoints as failpoints_mod

            failpoints_mod.disarm()
        for worker in fleet:
            worker.stop()
        if dispatcher_holder:
            dispatcher_holder[0].stop()
        if metrics_server is not None:
            metrics_server.stop()
        if trace_armed:
            from petastorm_tpu.telemetry import tracing

            tracing.COLLECTOR.release()
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)
        if journal_tmp:
            shutil.rmtree(journal_tmp, ignore_errors=True)
        if cache_tmp:
            shutil.rmtree(cache_tmp, ignore_errors=True)


SCENARIOS = {
    "tabular": tabular_predicate_scenario,
    "ngram": ngram_window_scenario,
    "image": image_pipeline_scenario,
    "weighted": weighted_mixing_scenario,
    "converter_mixing": converter_mixing_scenario,
    "packed": packed_delivery_scenario,
    "service": service_loopback_scenario,
}
