"""Memory-budgeted, disk-spilling cache of collated batches.

The tentpole of the decode-bypass tier (``docs/guides/caching.md``): an
entry is one *batch sequence* — every collated batch decoded for one cache
key (a service worker keys per row-group piece; the JAX loader keys per
reader plan) — stored not as live numpy dicts but as the **serializer
frames** the framed-socket transport already speaks, packed back-to-back
into one contiguous buffer per entry:

- the service worker's hit path hands ``memoryview`` slices of that buffer
  straight to ``framed_socket.send_framed_frames`` — one ``sendmsg``
  scatter-gather per batch with **zero re-serialization** (the decode AND
  the pickle are both skipped on a warm epoch);
- the JAX loader's hit path rebuilds numpy dicts from the same frames via
  the serializer's zero-copy out-of-band reconstruction;
- the disk tier writes/reads the entry as one meta header plus that same
  contiguous payload, so spilled entries round-trip without re-framing and
  **survive worker restarts** (composing with the control plane's
  re-registration: a restarted worker re-serves warm pieces from disk).

Tiers: a memory LRU under ``mem_budget_bytes`` (evictions drop the entry,
or merely drop the *memory copy* when the disk tier holds it — entries are
written through to disk at fill time, so an abrupt worker death never loses
the disk tier's warmth), and an optional disk tier under
``disk_budget_bytes`` enforced by the shared LRU policy
(:mod:`~petastorm_tpu.cache_impl.eviction`).

Thread-safe: concurrent streams look up, fill, and evict under one lock
with file I/O outside it; duplicate fills of one key are benign (last
commit wins, byte-identical by construction). Multi-process safe on a
shared directory: entry files are temp-written and atomically renamed.
"""

from __future__ import annotations

import hashlib
import os
import struct
import tempfile
import threading
import time
from collections import OrderedDict

from petastorm_tpu import failpoints
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    CACHE_BYTES,
    CACHE_CORRUPT,
    CACHE_DISK_WRITE_ERRORS,
    CACHE_ENTRIES,
    CACHE_EVICTIONS,
    CACHE_FILL_SECONDS,
    CACHE_HITS,
    CACHE_MISSES,
    CACHE_PERMUTED_SERVES,
    CACHE_SERVE_SECONDS,
    CACHE_VERSION_EVICTED,
)

#: On-disk entry format version, stamped in the magic line AND the meta
#: header. Version 3 adds the per-batch frame index (absolute payload
#: offsets) that serve-time permutation seeks on. Version 2 added a
#: payload crc32 (a truncated file was already caught by the frame-length
#: sum, but a bit-flipped payload byte passed it — chaos mode
#: ``cache-corrupt`` exercises exactly this).
ENTRY_FORMAT_VERSION = 3
_MAGIC = b"PTBCACHE3\n"
#: Magics of formats this build used to write: recognized so an old entry
#: is counted/evicted as a VERSION mismatch (expected after an upgrade —
#: deleted, refilled by the next decode) rather than as corruption.
_OLD_MAGICS = (b"PTBCACHE1\n", b"PTBCACHE2\n")
_LEN = struct.Struct("!Q")

logger = service_logger(__name__)

#: Disk-tier entry suffix (the shared eviction policy scopes to it).
ENTRY_SUFFIX = ".ptbc"

CACHE_MODES = ("off", "mem", "mem+disk")


class CacheConfig:
    """The three CLI knobs (``--cache``, ``--cache-mem-mb``,
    ``--cache-dir``) as a value object; :meth:`build` turns it into a
    :class:`BatchCache` (or ``None`` for ``off``)."""

    def __init__(self, mode="off", mem_mb=256, cache_dir=None, disk_mb=None):
        if mode not in CACHE_MODES:
            raise ValueError(
                f"cache mode must be one of {CACHE_MODES}, got {mode!r}")
        if mode != "mem+disk" and (cache_dir is not None
                                   or disk_mb is not None):
            # Silently dropping these would run an operator who asked for
            # restart persistence with a cold, memory-only cache.
            raise ValueError(
                f"cache_dir/disk_mb only apply to mode='mem+disk' "
                f"(got mode={mode!r} with cache_dir={cache_dir!r}, "
                f"disk_mb={disk_mb!r})")
        self.mode = mode
        self.mem_mb = mem_mb
        self.cache_dir = cache_dir
        self.disk_mb = disk_mb

    def build(self):
        if self.mode == "off":
            return None
        return BatchCache(
            mem_budget_bytes=int(self.mem_mb * (1 << 20)),
            cache_dir=self.cache_dir if self.mode == "mem+disk" else None,
            spill_to_disk=self.mode == "mem+disk",
            disk_budget_bytes=(int(self.disk_mb * (1 << 20))
                               if self.disk_mb else None))


class CachedBatch:
    """One batch of an entry: its row count and the serializer frames as
    zero-copy views into the entry's contiguous buffer."""

    __slots__ = ("rows", "fmt", "frames")

    def __init__(self, rows, fmt, frames):
        self.rows = rows
        self.fmt = fmt
        self.frames = frames

    def to_dict(self):
        """Rebuild the ``{field: ndarray}`` batch (the loader's hit path).

        PICKLE entries copy their out-of-band frames out of the shared
        entry buffer first: protocol-5 reconstruction aliases frame memory
        into WRITABLE rebuilt arrays, and a cached entry's buffer must
        never be writable through a served batch. COLUMNAR entries skip
        the copy — ``np.frombuffer`` over the entry's immutable ``bytes``
        yields read-only column views, so a warm hit is zero-copy and a
        trainer mutating the delivered batch gets a loud ``ValueError``
        instead of silently corrupting the cache (the view does pin the
        entry buffer until the batch is dropped, which is safe: evicting
        an immutable buffer merely drops the cache's reference)."""
        from petastorm_tpu.reader_impl.framed_socket import (
            PAYLOAD_COLUMNAR,
            decode_payload,
        )

        if self.fmt == PAYLOAD_COLUMNAR:
            # toreadonly(): entry buffers routed through the shm FramePool
            # are writable memoryviews — the served views must not be.
            return decode_payload(
                self.fmt, [memoryview(f).toreadonly() for f in self.frames])
        frames = [self.frames[0]] + [bytearray(f) for f in self.frames[1:]]
        return decode_payload(self.fmt, frames)


class CachedEntry:
    """One key's batch sequence: per-batch meta + one contiguous buffer.

    The **frame index** (``_offsets``) records each batch's absolute
    payload offset, so :meth:`batch_at` is an O(frames-per-batch) seek —
    the primitive serve-time permutation scatter-gathers on: any batch's
    frames slice out of the shared buffer without touching (or copying)
    the skipped prefix."""

    __slots__ = ("meta", "buf", "nbytes", "_offsets")

    def __init__(self, meta, buf):
        self.meta = meta          # [(rows, fmt, [frame_len, ...]), ...]
        self.buf = buf            # bytes: every batch's frames back to back
        self.nbytes = len(buf)
        offsets, offset = [], 0
        for _, _, frame_lens in meta:
            offsets.append(offset)
            offset += sum(frame_lens)
        self._offsets = offsets   # frame index: batch -> payload offset

    @property
    def rows(self):
        return sum(rows for rows, _, _ in self.meta)

    @property
    def num_batches(self):
        return len(self.meta)

    def batch_at(self, index):
        """The ``index``-th batch as zero-copy views into the buffer —
        random access via the frame index (serve-time permutation's seek
        path; ``batches()`` below is the sequential walk)."""
        rows, fmt, frame_lens = self.meta[index]
        view = memoryview(self.buf)
        offset = self._offsets[index]
        frames = []
        for length in frame_lens:
            frames.append(view[offset:offset + length])
            offset += length
        return CachedBatch(rows, fmt, frames)

    def batches(self):
        for index in range(len(self.meta)):
            yield self.batch_at(index)

    def to_dicts(self):
        return [batch.to_dict() for batch in self.batches()]


class EntryBuilder:
    """Accumulates one entry's batches during a fill (a cache miss being
    decoded). ``commit()`` publishes atomically — an abandoned builder
    (stream aborted mid-decode) publishes nothing, so a partial epoch can
    never be served as a complete one."""

    def __init__(self, cache, key):
        self._cache = cache
        self._key = key
        self._meta = []
        self._chunks = []
        self._spent_s = 0.0
        self._committed = False

    def add_batch(self, batch, rows=None):
        """Serialize ``batch`` and append it; returns ``(rows, fmt,
        frames)`` — the freshly-encoded frames, so a worker sends the very
        frames it just cached (one serialize per batch, not two)."""
        from petastorm_tpu.reader_impl.framed_socket import encode_payload

        t0 = time.perf_counter()
        fmt, frames = encode_payload(batch)
        if rows is None:
            rows = batch_rows(batch)
        self._append(rows, fmt, frames)
        self._spent_s += time.perf_counter() - t0
        return rows, fmt, frames

    def add_frames(self, rows, fmt, frames):
        """Append an already-encoded batch (caller did the serialization)."""
        t0 = time.perf_counter()
        self._append(rows, fmt, frames)
        self._spent_s += time.perf_counter() - t0

    def _append(self, rows, fmt, frames):
        views = [memoryview(f) for f in frames]
        self._meta.append((int(rows), int(fmt),
                           [v.nbytes for v in views]))
        # Copy NOW: out-of-band frames alias the decoded arrays' memory,
        # which the producer reuses/free's after the batch is sent.
        self._chunks.extend(bytes(v) for v in views)

    def commit(self):
        """Freeze into a :class:`CachedEntry` and publish it to the tiers.
        Returns the entry (callers may serve from it immediately)."""
        if self._committed:
            raise RuntimeError("EntryBuilder.commit() called twice")
        self._committed = True
        t0 = time.perf_counter()
        entry = CachedEntry(self._meta,
                            self._cache._materialize(
                                b"".join(self._chunks)))
        self._chunks = None
        self._cache._publish(self._key, entry)
        CACHE_FILL_SECONDS.observe(self._spent_s
                                   + (time.perf_counter() - t0))
        return entry


def batch_rows(batch):
    """Row count of a collated ``{field: array}`` batch (every column has
    equal length; an empty dict is zero rows). Shared by the cache's
    builders and the service worker's send accounting — one definition,
    so stored and streamed row counts can never diverge."""
    for value in batch.values():
        return int(len(value))
    return 0


class BatchCache:
    """See the module docstring. ``cache_dir=None`` with
    ``spill_to_disk=True`` creates a private temp directory that
    ``cleanup()`` removes; a caller-provided directory persists (the
    restart-warmth contract) and ``cleanup()`` only releases tracking."""

    def __init__(self, mem_budget_bytes=256 << 20, cache_dir=None,
                 spill_to_disk=False, disk_budget_bytes=None):
        if mem_budget_bytes <= 0:
            raise ValueError("mem_budget_bytes must be positive")
        self._mem_budget = int(mem_budget_bytes)
        self._disk_budget = disk_budget_bytes
        self._disk = bool(spill_to_disk)
        self._lock = threading.Lock()
        self._entries = OrderedDict()   # key -> CachedEntry (LRU order)
        self._mem_bytes = 0
        self._owns_dir = False
        self._dir = None
        if self._disk:
            from petastorm_tpu import cache_impl as tracking

            if cache_dir is None:
                self._dir = tempfile.mkdtemp(prefix="petastorm_batch_cache_")
                self._owns_dir = True
                tracking.register_cache_dir(self._dir)
            else:
                self._dir = str(cache_dir)
                if not os.path.isdir(self._dir):
                    os.makedirs(self._dir, exist_ok=True)
                    tracking.register_cache_dir(self._dir)
        # Instance counters (the registry families aggregate across every
        # cache in the process; a worker's diagnostics report its own).
        self.hits_mem = 0
        self.hits_disk = 0
        self.misses = 0
        self.evictions_mem = 0
        self.evictions_disk = 0
        self.corrupt_entries = 0
        self.version_evicted = 0
        self.permuted_serves = 0
        self.disk_write_errors = 0
        self._m_hits_mem = CACHE_HITS.labels("mem")
        self._m_hits_disk = CACHE_HITS.labels("disk")
        self._m_bytes_mem = CACHE_BYTES.labels("mem")
        self._m_entries_mem = CACHE_ENTRIES.labels("mem")
        self._m_bytes_disk = CACHE_BYTES.labels("disk")
        self._m_entries_disk = CACHE_ENTRIES.labels("disk")
        self._m_evict_mem = CACHE_EVICTIONS.labels("mem")
        self._m_evict_disk = CACHE_EVICTIONS.labels("disk")
        # This instance's contribution to the disk-tier gauges (what
        # cleanup() retracts): per-instance write/evict deltas — on a
        # directory shared across processes each process reports its own
        # writes, matching the gauges' "summed over cache instances in
        # the process" contract.
        self._disk_bytes_acct = 0
        self._disk_entries_acct = 0
        # Optional frame allocator (the shm transport's shared frame
        # pool): entry buffers materialize through it so warm serves can
        # travel as (offset, len) references instead of copies.
        self._frame_allocator = None

    def set_frame_allocator(self, allocate):
        """Arm (or with ``None`` disarm) an entry-buffer allocator —
        ``allocate(nbytes) -> writable buffer or None``. The shm
        transport points this at its shared frame pool so cached frames
        live in client-attachable memory (mapped serves); ``None`` from
        the allocator (pool full) falls back to a heap buffer — the
        cache works identically either way, entries just serve copied
        instead of mapped."""
        self._frame_allocator = allocate

    def _materialize(self, blob):
        """Route one entry's contiguous payload through the armed
        allocator (identity when disarmed, empty, or the pool is full)."""
        allocate = self._frame_allocator
        if allocate is None or not len(blob):
            return blob
        view = allocate(len(blob))
        if view is None:
            return blob
        view[:] = blob
        return view

    @property
    def cache_dir(self):
        return self._dir

    # -- lookup ------------------------------------------------------------

    def get(self, key):
        """The :class:`CachedEntry` for ``key`` or ``None`` (a miss).
        Checks memory, then disk; a disk hit is promoted into the memory
        tier (it is about to be hot)."""
        return self.get_tiered(key)[0]

    def get_tiered(self, key, count_miss=True):
        """``(entry, tier)`` — the entry plus which tier answered
        (``"mem"``/``"disk"``), or ``(None, None)`` on a miss. Serve-time
        permutation callers use the tier to attribute their
        ``cache_permuted_serves_total`` bumps.

        ``count_miss=False`` suppresses the miss accounting on the empty
        result — the fleet tier probes the local tiers first and only
        counts a miss once the remote tier also comes up empty (a remote
        warm hit must not read as a local miss in ``CACHEHIT%``)."""
        t0 = time.perf_counter()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits_mem += 1
        if entry is not None:
            self._m_hits_mem.inc()
            CACHE_SERVE_SECONDS.observe(time.perf_counter() - t0)
            return entry, "mem"
        if self._disk:
            entry = self._load_disk(key)
            if entry is not None:
                with self._lock:
                    self.hits_disk += 1
                    self._insert_locked(key, entry)
                self._m_hits_disk.inc()
                CACHE_SERVE_SECONDS.observe(time.perf_counter() - t0)
                return entry, "disk"
        if count_miss:
            self.note_miss()
        return None, None

    def note_miss(self):
        """Count one lookup that no tier answered (split out of
        :meth:`get_tiered` so the fleet tier can defer the bump until its
        remote probe also misses)."""
        with self._lock:
            self.misses += 1
        CACHE_MISSES.inc()

    def note_permuted_serve(self, tier):
        """One entry was served through a serve-time permutation (shuffle-
        compatible serving). Called by the serve sites (the worker's piece
        engine, the loader's replay) — the cache itself never knows the
        order its bytes go out in."""
        with self._lock:
            self.permuted_serves += 1
        CACHE_PERMUTED_SERVES.labels(tier or "mem").inc()

    def peek(self, key):
        """Memory-tier probe without LRU touch or hit/miss accounting —
        the fleet tier's peer-serve path: a peer asking for an entry must
        not perturb this worker's own hit statistics or eviction order."""
        with self._lock:
            return self._entries.get(key)

    def get_batches(self, key):
        """The decoded ``[{field: ndarray}, ...]`` sequence, or ``None``."""
        entry = self.get(key)
        return None if entry is None else entry.to_dicts()

    def contains(self, key):
        with self._lock:
            if key in self._entries:
                return True
        return self._disk and os.path.exists(self._entry_path(key))

    #: ``contains`` without counter side effects — fillers use it to check
    #: whether a just-committed entry was actually retained by any tier
    #: (an entry larger than every budget is committed but kept nowhere).
    retained = contains

    # -- fill --------------------------------------------------------------

    def begin_fill(self, key):
        return EntryBuilder(self, key)

    def put_batches(self, key, batches):
        """Convenience: cache a complete batch sequence in one call."""
        builder = self.begin_fill(key)
        for batch in batches:
            builder.add_batch(batch)
        return builder.commit()

    def put_entry(self, key, meta, blob):
        """Adopt an already-framed entry — the fleet tier's ingest path
        for peer-shipped entries (remote fetch promotion, drain handoff).

        ``meta`` is the entry's ``[(rows, fmt, [frame_len, ...]), ...]``
        and ``blob`` the matching contiguous payload.  The frames are
        adopted as-is (zero re-serialization, routed through the armed
        frame allocator exactly like a local fill); a meta/payload length
        disagreement raises ``ValueError`` — a torn transfer must never
        be published as a complete entry."""
        meta = [(int(rows), int(fmt), [int(l) for l in lens])
                for rows, fmt, lens in meta]
        expected = sum(length for _, _, lens in meta for length in lens)
        if expected != len(blob):
            raise ValueError(
                "entry payload is %d bytes but meta frames sum to %d"
                % (len(blob), expected))
        entry = CachedEntry(meta, self._materialize(bytes(blob)))
        self._publish(key, entry)
        return entry

    def hot_entries(self):
        """Snapshot of the memory tier as ``[(key, entry), ...]``,
        hottest (most recently used) first — what a draining worker ships
        to the peers inheriting its pieces.  Entries are immutable, so
        the snapshot stays valid after the lock drops even if eviction
        races the handoff."""
        with self._lock:
            return [(key, entry)
                    for key, entry in reversed(self._entries.items())]

    def _publish(self, key, entry):
        if self._disk:
            self._store_disk(key, entry)
        with self._lock:
            self._insert_locked(key, entry)

    def _insert_locked(self, key, entry):
        old = self._entries.pop(key, None)
        if old is not None:
            self._account_mem_locked(-old.nbytes, -1)
        if entry.nbytes <= self._mem_budget:
            self._entries[key] = entry
            self._account_mem_locked(entry.nbytes, 1)
        # else: a single entry larger than the whole budget lives on disk
        # only (or, memory-only mode, is simply not retained).
        while self._mem_bytes > self._mem_budget and self._entries:
            _, evicted = self._entries.popitem(last=False)
            self._account_mem_locked(-evicted.nbytes, -1)
            self.evictions_mem += 1
            self._m_evict_mem.inc()
            # Disk tier already holds it (write-through at fill): dropping
            # the memory copy loses nothing but the memcpy saved.

    def _account_mem_locked(self, bytes_delta, entries_delta):
        self._mem_bytes += bytes_delta
        self._m_bytes_mem.inc(bytes_delta)
        self._m_entries_mem.inc(entries_delta)

    # -- disk tier ---------------------------------------------------------

    def _entry_path(self, key):
        digest = hashlib.sha256(str(key).encode("utf-8")).hexdigest()
        return os.path.join(self._dir, digest + ENTRY_SUFFIX)

    def _store_disk(self, key, entry):
        import json
        import zlib

        meta = json.dumps({
            "format": ENTRY_FORMAT_VERSION,
            "crc32": zlib.crc32(entry.buf) & 0xFFFFFFFF,
            # The frame index rides along explicitly (offset per batch):
            # redundant with the cumulative frame_lens, which doubles as a
            # consistency check on load — an offset that disagrees with
            # the running sum marks the file bad.
            "batches": [{"rows": rows, "fmt": fmt, "frame_lens": lens,
                         "offset": offset}
                        for (rows, fmt, lens), offset
                        in zip(entry.meta, entry._offsets)],
        }).encode("utf-8")
        path = self._entry_path(key)
        tmp_path = None
        try:
            old_size = os.path.getsize(path)
        except OSError:
            old_size = None
        fp = failpoints.ACTIVE
        partial = False
        try:
            if fp is not None:
                # "oserror" raises into the degrade-to-pass-through path
                # below; "partial" PUBLISHES a truncated entry — the torn
                # write a crash mid-replace-free filesystem still allows —
                # which the warm load must detect (frame-length sum / crc)
                # and degrade from, never serve.
                partial = fp.fire("cache.write") == "partial"
            # mkstemp INSIDE the guard: a vanished/unwritable cache dir is
            # a degraded cache, not a stream error — the tier is
            # best-effort end to end.
            fd, tmp_path = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC)
                f.write(_LEN.pack(len(meta)))
                f.write(meta)
                if partial:
                    f.write(entry.buf[:entry.nbytes // 2])
                else:
                    f.write(entry.buf)
            os.replace(tmp_path, path)
        except OSError:  # disk full, dir removed, fd exhaustion — skip
            with self._lock:
                self.disk_write_errors += 1
            CACHE_DISK_WRITE_ERRORS.inc()
            logger.warning(
                "disk-tier cache entry write failed — skipping the entry "
                "(cache degrades to pass-through for it)", exc_info=True)
            if tmp_path is not None:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
            return
        new_size = len(_MAGIC) + _LEN.size + len(meta) + entry.nbytes
        self._account_disk(new_size - (old_size or 0),
                           0 if old_size is not None else 1)
        if self._disk_budget is not None:
            from petastorm_tpu.cache_impl.eviction import evict_dir_to_limit

            deleted, freed = evict_dir_to_limit(self._dir, self._disk_budget,
                                                ENTRY_SUFFIX)
            if deleted:
                with self._lock:
                    self.evictions_disk += deleted
                self._m_evict_disk.inc(deleted)
                self._account_disk(-freed, -deleted)

    def _account_disk(self, bytes_delta, entries_delta):
        """Track this instance's disk-tier residency contribution (clamped
        at zero: an eviction may free files another instance wrote)."""
        with self._lock:
            bytes_delta = max(bytes_delta, -self._disk_bytes_acct)
            entries_delta = max(entries_delta, -self._disk_entries_acct)
            self._disk_bytes_acct += bytes_delta
            self._disk_entries_acct += entries_delta
        self._m_bytes_disk.inc(bytes_delta)
        self._m_entries_disk.inc(entries_delta)

    def _load_disk(self, key):
        import json
        import zlib

        path = self._entry_path(key)
        try:
            fp = failpoints.ACTIVE
            if fp is not None:
                fp.fire("cache.read")  # oserror → a transient read
                #   failure is a MISS (fresh decode), never a stream error
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if any(blob.startswith(magic) for magic in _OLD_MAGICS):
            # A previous format version's entry (expected after an
            # upgrade, not damage): counted separately from corruption,
            # deleted, reported as a miss — the next decode refills it in
            # the current format. Never a stream error.
            with self._lock:
                self.version_evicted += 1
            CACHE_VERSION_EVICTED.inc()
            logger.warning(
                "disk-tier cache entry %s was written by an older format "
                "version — deleting; the next decode refills it", path)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            meta_off = len(_MAGIC)
            meta_len = _LEN.unpack_from(blob, meta_off)[0]
            payload_off = meta_off + _LEN.size + meta_len
            meta = json.loads(blob[meta_off + _LEN.size:payload_off]
                              .decode("utf-8"))
            if int(meta.get("format", 0)) != ENTRY_FORMAT_VERSION:
                raise ValueError("meta format/magic version disagree")
            payload = blob[payload_off:]
            entry = CachedEntry(
                [(m["rows"], m["fmt"], list(m["frame_lens"]))
                 for m in meta["batches"]],
                payload)
            expected = sum(length for _, _, lens in entry.meta
                           for length in lens)
            if expected != entry.nbytes:
                raise ValueError("truncated payload")
            if [m["offset"] for m in meta["batches"]] != entry._offsets:
                raise ValueError("frame index disagrees with frame lengths")
            if (zlib.crc32(payload) & 0xFFFFFFFF) != int(meta["crc32"]):
                raise ValueError("payload checksum mismatch")
        except (ValueError, KeyError, TypeError, struct.error):
            # Corrupt/torn/old-format entry: counted, removed so it cannot
            # keep failing every epoch, and reported as a MISS — the
            # caller degrades to a fresh decode (which re-fills the entry)
            # instead of serving bad bytes or erroring the stream.
            with self._lock:
                self.corrupt_entries += 1
            CACHE_CORRUPT.inc()
            logger.warning(
                "disk-tier cache entry %s failed validation — deleting "
                "and degrading to fresh decode", path)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # LRU touch for the shared eviction policy
        except OSError:
            pass
        # Pool-materialize only AFTER validation: a corrupt entry must
        # not leak bump-allocated pool bytes it will never serve from.
        pooled = self._materialize(payload)
        if pooled is not payload:
            entry = CachedEntry(entry.meta, pooled)
        return entry

    # -- observability / lifecycle -----------------------------------------

    def stats(self):
        with self._lock:
            return {
                "mode": "mem+disk" if self._disk else "mem",
                "hits": self.hits_mem + self.hits_disk,
                "hits_mem": self.hits_mem,
                "hits_disk": self.hits_disk,
                "misses": self.misses,
                "hit_rate": round(
                    (self.hits_mem + self.hits_disk)
                    / max(1, self.hits_mem + self.hits_disk + self.misses),
                    4),
                "entries_mem": len(self._entries),
                "bytes_mem": self._mem_bytes,
                "entries_disk": self._disk_entries_acct,
                "bytes_disk": self._disk_bytes_acct,
                "mem_budget_bytes": self._mem_budget,
                "evictions_mem": self.evictions_mem,
                "evictions_disk": self.evictions_disk,
                "corrupt_entries": self.corrupt_entries,
                "version_evicted": self.version_evicted,
                "permuted_serves": self.permuted_serves,
                "disk_write_errors": self.disk_write_errors,
                "cache_dir": self._dir,
            }

    def cleanup(self):
        """Release everything this cache owns: the memory tier always; the
        disk directory only when this instance created it as a private
        tempdir (a caller-provided directory is the persistence contract —
        its files outlive the process so a restarted worker re-serves warm
        pieces). Always deregisters from the leak-tracking registry."""
        with self._lock:
            while self._entries:
                _, entry = self._entries.popitem(last=False)
                self._account_mem_locked(-entry.nbytes, -1)
        # Retract this instance's disk-tier gauge contribution: gauges
        # track LIVE cache instances (shared-directory files may persist,
        # but nobody in this process owns them anymore).
        self._account_disk(-self._disk_bytes_acct, -self._disk_entries_acct)
        if self._dir is not None:
            from petastorm_tpu import cache_impl as tracking

            if self._owns_dir:
                import shutil

                shutil.rmtree(self._dir, ignore_errors=True)
            tracking.deregister_cache_dir(self._dir)
