"""Local-disk row-group cache with size-based LRU eviction.

Reference parity: ``petastorm/local_disk_cache.py::LocalDiskCache``. The
reference delegates storage to the third-party ``diskcache`` package; that is
absent in this environment (SURVEY.md §7 preamble), so the store is
self-written: one file per key (sha256-named), with ``cache_size_limit``
enforced as a real eviction budget by the shared LRU policy
(:mod:`petastorm_tpu.cache_impl.eviction` — the same policy behind the
decoded-batch cache's disk tier). Concurrent readers on one host are safe:
writes go through a temp file + atomic rename, and eviction tolerates
concurrently-deleted files.

Repeated-epoch accelerator: on a TPU pod reading from GCS, epoch 2+ hits
local NVMe instead of the network. (For bypassing the *decode* as well, see
``docs/guides/caching.md`` — this cache stores pre-decode row-group
payloads.)

Directories this cache creates are registered with the cache-dir tracker
(``cache_impl``); ``cleanup()`` deregisters (and removes the directory when
constructed with ``cleanup=True``) — the tier-1 leak guard fails tests that
orphan one.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile


class LocalDiskCache:
    _SUFFIX = ".cache"

    def __init__(self, path, size_limit, expected_row_size_estimate=None,
                 shards=None, cleanup=False, **settings):
        """``size_limit`` in bytes; ``expected_row_size_estimate`` kept for
        reference API parity (unused — eviction is measured, not estimated)."""
        self._path = path
        self._size_limit = size_limit
        self._cleanup_on_exit = cleanup
        self._registered = not os.path.isdir(path)
        os.makedirs(path, exist_ok=True)
        if self._registered:
            from petastorm_tpu import cache_impl as tracking

            tracking.register_cache_dir(path)

    def _key_path(self, key):
        digest = hashlib.sha256(repr(key).encode("utf-8")).hexdigest()
        return os.path.join(self._path, digest + self._SUFFIX)

    def get(self, key, fill_cache_func):
        file_path = self._key_path(key)
        try:
            with open(file_path, "rb") as f:
                value = self._deserialize(f.read())
        except Exception:  # corrupt/missing/format-mismatched entry → refill
            pass
        else:
            try:
                os.utime(file_path)  # LRU touch
            except OSError:  # read-only/shared cache dir: value still valid
                pass
            return value
        value = fill_cache_func()
        self._store(file_path, self._serialize(value))
        return value

    def _serialize(self, value):
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def _deserialize(self, payload):
        return pickle.loads(payload)  # noqa: S301

    def _store(self, file_path, payload):
        tmp_path = None
        try:
            # mkstemp inside the guard: the directory can vanish under a
            # concurrent cleanup() (reader teardown signals pool workers
            # before joining them) — a failed store is a skipped cache
            # write, never an error in the decode path.
            fd, tmp_path = tempfile.mkstemp(dir=self._path, suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp_path, file_path)
        except OSError:  # disk full, dir removed; cache is best-effort
            if tmp_path is not None:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
            return
        from petastorm_tpu.cache_impl.eviction import evict_dir_to_limit

        evict_dir_to_limit(self._path, self._size_limit, self._SUFFIX)

    def size_on_disk(self):
        from petastorm_tpu.cache_impl.eviction import dir_size

        return dir_size(self._path, self._SUFFIX)

    def cleanup(self):
        from petastorm_tpu import cache_impl as tracking

        if self._cleanup_on_exit:
            import shutil

            shutil.rmtree(self._path, ignore_errors=True)
        tracking.deregister_cache_dir(self._path)
