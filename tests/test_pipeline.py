"""Pipeline-parallel schedule tests over the virtual CPU mesh: the shard_map
+ ppermute + scan GPipe schedule must match the sequential stack exactly,
forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.pipeline import (
    apply_pipeline_model,
    init_pipeline_params,
    make_pipeline_train_step,
    pipeline_param_partition_specs,
    reference_forward,
)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pp",))


def _params(n_stages, seed=0):
    return init_pipeline_params(jax.random.PRNGKey(seed), feature_dim=6,
                                d_model=16, d_hidden=32,
                                num_stages=n_stages, num_classes=3)


def test_pipeline_forward_matches_sequential_stack():
    mesh = _mesh(4)
    params = _params(4)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=4)
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_forward_more_microbatches_than_stages():
    mesh = _mesh(2)
    params = _params(2, seed=1)
    x = jnp.asarray(np.random.RandomState(1).randn(12, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=6)
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential_stack():
    """The transposed schedule (scan+ppermute autodiff) must equal the
    sequential stack's gradients — including zero contribution from
    warmup/drain bubble compute."""
    mesh = _mesh(4)
    params = _params(4, seed=2)
    x = jnp.asarray(np.random.RandomState(2).randn(8, 6).astype(np.float32))
    labels = jnp.asarray(np.arange(8) % 3, jnp.int32)

    def loss_pp(p):
        logits = apply_pipeline_model(p, x, mesh, num_microbatches=4)
        return -jnp.take_along_axis(jax.nn.log_softmax(logits),
                                    labels[:, None], 1).mean()

    def loss_ref(p):
        logits = reference_forward(p, x)
        return -jnp.take_along_axis(jax.nn.log_softmax(logits),
                                    labels[:, None], 1).mean()

    g_pp = jax.grad(loss_pp)(params)
    g_ref = jax.grad(loss_ref)(params)
    for key in params:
        np.testing.assert_allclose(np.asarray(g_pp[key]),
                                   np.asarray(g_ref[key]),
                                   rtol=1e-4, atol=1e-5, err_msg=key)


def test_pipeline_train_step_descends_sharded():
    mesh = _mesh(4)
    params = _params(4, seed=3)
    specs = pipeline_param_partition_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    step = jax.jit(make_pipeline_train_step(0.1, mesh=mesh,
                                            num_microbatches=4))
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 3, 8), jnp.int32)
    mask = jnp.ones(8, bool)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_pipeline_rejects_mismatched_stage_count():
    mesh = _mesh(4)
    params = _params(2)
    x = jnp.zeros((8, 6), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        apply_pipeline_model(params, x, mesh, num_microbatches=4)


def test_pipeline_rejects_indivisible_batch():
    mesh = _mesh(2)
    params = _params(2)
    with pytest.raises(ValueError, match="microbatches"):
        apply_pipeline_model(params, jnp.zeros((7, 6), jnp.float32), mesh,
                             num_microbatches=4)


def test_pipeline_dp_x_pp_mesh():
    """Combined data x pipeline mesh: batch sharded over "data", stages
    over "pp" — must still match the sequential stack, and train."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pp"))
    params = _params(4, seed=5)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=4,
                               batch_axis="data")
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    specs = pipeline_param_partition_specs()
    sharded_params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                      for k, v in params.items()}
    step = jax.jit(make_pipeline_train_step(0.1, mesh=mesh,
                                            num_microbatches=4,
                                            batch_axis="data"))
    labels = jnp.asarray(rng.randint(0, 3, 8), jnp.int32)
    losses = []
    p = sharded_params
    for _ in range(4):
        p, loss = step(p, x, labels, jnp.ones(8, bool))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
