"""JAX delivery-layer tests: collation, pad/drop policy, device staging,
global sharding over the 8-device virtual CPU mesh (conftest.py).

Reference analogue: the reference has no JAX path; these tests play the role
its adapter tests (``test_pytorch_dataloader.py`` etc.) play for torch —
run mostly off ReaderMock, plus end-to-end reads of the conftest datasets.
"""

import numpy as np
import pytest

from petastorm_tpu.jax_utils import (
    batch_iterator,
    batch_sharding,
    collate_ngram_rows,
    collate_rows,
    make_jax_dataloader,
)
from petastorm_tpu.jax_utils.batcher import PAD_MASK_KEY
from petastorm_tpu.schema.codecs import ScalarCodec
from petastorm_tpu.schema.unischema import Unischema, UnischemaField
from petastorm_tpu.test_util.reader_mock import ReaderMock

MockSchema = Unischema("MockSchema", [
    UnischemaField("id", np.int64, (), ScalarCodec(), False),
    UnischemaField("vec", np.float32, (3,), None, False),
    UnischemaField("name", str, (), ScalarCodec(), False),
])


def _row_gen(i):
    return {"id": np.int64(i),
            "vec": np.full(3, i, dtype=np.float32),
            "name": f"row_{i}"}


def _mock_reader(rows=10):
    return ReaderMock(MockSchema, _row_gen, num_rows=rows)


# --- collation -----------------------------------------------------------

def test_collate_rows_stacks_dense_and_object_columns():
    rows = [MockSchema.make_namedtuple(**_row_gen(i)) for i in range(4)]
    batch = collate_rows(rows)
    assert batch["id"].shape == (4,) and batch["id"].dtype == np.int64
    assert batch["vec"].shape == (4, 3)
    assert batch["name"].dtype == object and batch["name"][2] == "row_2"


def test_collate_ngram_rows_builds_time_axis():
    from collections import namedtuple
    Step = namedtuple("Step", ["a", "b"])
    rows = [{0: Step(a=np.zeros(2), b=i), 1: Step(a=np.ones(2), b=i + 1)}
            for i in range(3)]
    batch = collate_ngram_rows(rows)
    assert batch["a"].shape == (3, 2, 2)  # [B, T, ...]
    assert batch["b"].shape == (3, 2)
    np.testing.assert_array_equal(batch["b"][:, 1], [1, 2, 3])


def test_collate_ngram_rows_uneven_fields_keep_offset_identity():
    from collections import namedtuple
    S0, S1 = namedtuple("S0", ["a", "x"]), namedtuple("S1", ["a"])
    rows = [{0: S0(a=1, x=7), 1: S1(a=2)} for _ in range(2)]
    batch = collate_ngram_rows(rows)
    assert batch["a"].shape == (2, 2)
    assert batch["x@0"].shape == (2,)


# --- batching policies ---------------------------------------------------

@pytest.mark.parametrize("policy,expect_batches,expect_last_rows", [
    ("drop", 3, 3), ("keep", 4, 1), ("pad", 4, 3)])
def test_last_batch_policies(policy, expect_batches, expect_last_rows):
    batches = list(batch_iterator(_mock_reader(10), 3, last_batch=policy))
    assert len(batches) == expect_batches
    assert batches[-1]["id"].shape[0] == expect_last_rows
    if policy == "pad":
        mask = batches[-1][PAD_MASK_KEY]
        assert mask.tolist() == [True, False, False]
        # wrap-padded rows repeat the partial batch's rows
        assert batches[-1]["id"][1] == batches[-1]["id"][0]


def test_max_batches_truncates():
    batches = list(batch_iterator(_mock_reader(100), 10, max_batches=3))
    assert len(batches) == 3


def test_batch_iterator_rejects_bad_policy():
    with pytest.raises(ValueError):
        list(batch_iterator(_mock_reader(), 3, last_batch="wat"))


# --- loader: host-only path ----------------------------------------------

def test_loader_host_only_yields_numpy():
    loader = make_jax_dataloader(_mock_reader(9), 3, stage_to_device=False)
    with loader:
        batches = list(loader)
    assert len(batches) == 3
    assert all(isinstance(b["vec"], np.ndarray) for b in batches)
    assert loader.diagnostics["batches"] == 3
    assert loader.diagnostics["rows"] == 9
    assert loader.diagnostics["wall_s"] > 0


def test_loader_propagates_producer_error():
    class Boom:
        batched_output = False
        ngram = None

        def __iter__(self):
            return self

        def __next__(self):
            raise RuntimeError("decode failed")

        def stop(self):
            pass

        def join(self):
            pass

    loader = make_jax_dataloader(Boom(), 2, stage_to_device=False)
    with pytest.raises(RuntimeError, match="decode failed"):
        with loader:
            list(loader)


# --- loader: device staging ----------------------------------------------

def test_loader_stages_numeric_to_device_keeps_strings_on_host():
    import jax

    loader = make_jax_dataloader(_mock_reader(6), 3)
    with loader:
        batches = list(loader)
    assert len(batches) == 2
    assert isinstance(batches[0]["vec"], jax.Array)
    assert batches[0]["vec"].shape == (3, 3)
    assert isinstance(batches[0]["name"], np.ndarray)  # host passthrough


def test_loader_non_tensor_policy_drop_and_error():
    loader = make_jax_dataloader(_mock_reader(3), 3, non_tensor_policy="drop")
    with loader:
        (batch,) = list(loader)
    assert "name" not in batch and "vec" in batch

    loader = make_jax_dataloader(_mock_reader(3), 3, non_tensor_policy="error")
    with pytest.raises(TypeError, match="non-tensor"):
        with loader:
            list(loader)


def test_loader_emits_globally_sharded_arrays():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devices, ("data",))
    sharding = batch_sharding(mesh, "data")
    loader = make_jax_dataloader(_mock_reader(16), 8, sharding=sharding,
                                 non_tensor_policy="drop")
    with loader:
        batches = list(loader)
    assert len(batches) == 2
    arr = batches[0]["vec"]
    assert isinstance(arr, jax.Array)
    assert arr.sharding.is_equivalent_to(sharding, arr.ndim)
    assert len(arr.addressable_shards) == 8
    # a jitted step consumes it without resharding
    total = jax.jit(lambda x: x.sum())(arr)
    np.testing.assert_allclose(float(total), float(np.asarray(arr).sum()))


# --- end-to-end over real datasets ---------------------------------------

def test_loader_end_to_end_petastorm_dataset(petastorm_dataset):
    from petastorm_tpu import make_reader

    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         schema_fields=["id", "matrix"], num_epochs=1,
                         shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 10)
    with loader:
        batches = list(loader)
    assert len(batches) == 3
    ids = np.concatenate([np.asarray(b["id"]) for b in batches])
    assert sorted(ids.tolist()) == list(range(30))
    assert batches[0]["matrix"].shape == (10, 4, 8)


def test_loader_end_to_end_batch_reader(scalar_dataset):
    from petastorm_tpu import make_batch_reader

    reader = make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                               num_epochs=1, shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 7, last_batch="pad",
                                 non_tensor_policy="drop")
    with loader:
        batches = list(loader)
    # 30 rows, batch 7 → 4 full + 1 padded
    assert len(batches) == 5
    assert all(np.asarray(b["id"]).shape[0] == 7 for b in batches)
    real = np.concatenate([
        np.asarray(b["id"])[np.asarray(b[PAD_MASK_KEY])] if PAD_MASK_KEY in b
        else np.asarray(b["id"]) for b in batches])
    assert sorted(real.tolist()) == list(range(30))


def test_loader_sharded_readers_partition_dataset(petastorm_dataset):
    from petastorm_tpu import make_reader

    seen = []
    for shard in range(3):
        reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                             schema_fields=["id"], num_epochs=1,
                             shuffle_row_groups=False,
                             cur_shard=shard, shard_count=3)
        loader = make_jax_dataloader(reader, 5, stage_to_device=False)
        with loader:
            for b in loader:
                seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(30))


def test_loader_reiteration_stops_previous_producer():
    """iter() mid-stream must not leave two producers on one reader."""
    loader = make_jax_dataloader(_mock_reader(100), 5, stage_to_device=False)
    it1 = iter(loader)
    next(it1)
    it2 = iter(loader)  # stops producer 1
    batches = list(it2)
    assert len(batches) >= 1
    loader.stop()
    loader.join()


def test_shuffle_buffer_decorrelates_rows():
    loader = make_jax_dataloader(_mock_reader(60), 10, stage_to_device=False,
                                 shuffle_buffer_size=30, shuffle_seed=7)
    with loader:
        ids = np.concatenate([b["id"] for b in loader]).tolist()
    assert sorted(ids) == list(range(60))     # exactly-once preserved
    assert ids != list(range(60))             # order actually changed
    # deterministic under the same seed
    loader2 = make_jax_dataloader(_mock_reader(60), 10, stage_to_device=False,
                                  shuffle_buffer_size=30, shuffle_seed=7)
    with loader2:
        ids2 = np.concatenate([b["id"] for b in loader2]).tolist()
    assert ids == ids2


def test_shuffle_buffer_rejected_for_batch_readers():
    reader = ReaderMock(MockSchema, _row_gen, num_rows=10, batched_output=True)
    with pytest.raises(ValueError, match="row reader"):
        list(batch_iterator(reader, 3, shuffle_buffer_size=8))


def test_stack_column_handles_nullable_ndarrays():
    from petastorm_tpu.jax_utils.batcher import _stack_column

    col = _stack_column([np.zeros((2, 3)), None, np.ones((2, 3))])
    assert col.dtype == object and col[1] is None
    col = _stack_column([None, np.zeros((2, 3))])
    assert col.dtype == object
    col = _stack_column([np.int64(1), None, np.int64(3)])
    assert col.dtype == object and col[1] is None


def test_lambda_fingerprint_distinguishes_closures():
    from petastorm_tpu.predicates import in_lambda

    def make_pred(t):
        return in_lambda(["id"], lambda v: v["id"] > t)

    assert repr(make_pred(5)) != repr(make_pred(10))
    assert repr(make_pred(5)) == repr(make_pred(5))


def test_loader_break_stops_producer():
    """Abandoning iteration must stop the producer thread (no leak)."""
    import time

    loader = make_jax_dataloader(_mock_reader(None), 5, stage_to_device=False)
    for _ in loader:
        break
    deadline = time.monotonic() + 5
    while loader._producer.is_alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not loader._producer.is_alive()


def test_transform_spec_repr_is_deterministic():
    from petastorm_tpu.schema.transform import TransformSpec

    t1 = TransformSpec(lambda r: r, removed_fields=["a"])
    t2 = TransformSpec(lambda r: r, removed_fields=["a"])
    t3 = TransformSpec(lambda r: dict(r, x=1), removed_fields=["a"])
    assert "0x" not in repr(t1)
    assert repr(t1) == repr(t2)
    assert repr(t1) != repr(t3)


class _PredState:
    def __init__(self, vals):
        self.vals = vals


def test_stable_repr_digests_default_object_reprs():
    from petastorm_tpu.predicates import _stable_repr, in_lambda

    r1 = _stable_repr(_PredState([1, 2]))
    r2 = _stable_repr(_PredState([1, 2]))
    r3 = _stable_repr(_PredState([9]))
    assert "0x" not in r1 and r1 == r2 and r1 != r3
    p = in_lambda(["id"], lambda v, s: v["id"] in s.vals,
                  state_arg=_PredState([1]))
    assert "0x" not in repr(p)


def test_fingerprint_distinguishes_global_names():
    from petastorm_tpu.predicates import _func_fingerprint

    assert _func_fingerprint(lambda v: sorted(v)) != \
        _func_fingerprint(lambda v: reversed(v))
    assert _func_fingerprint(lambda v: v.id) != \
        _func_fingerprint(lambda v: v.label)


def test_fingerprint_tracks_global_values():
    import sys

    from petastorm_tpu.predicates import _func_fingerprint

    mod = sys.modules[__name__]
    mod._FP_THRESHOLD = 5
    fn = eval("lambda v: v > _FP_THRESHOLD", vars(mod))
    fp1 = _func_fingerprint(fn)
    mod._FP_THRESHOLD = 10
    fp2 = _func_fingerprint(fn)
    assert fp1 != fp2


def test_sentinel_survives_slow_consumer():
    """A consumer pausing longer than any internal timeout must still see
    end-of-stream (regression: sentinel was dropped after 30s queue.Full)."""
    import time

    loader = make_jax_dataloader(_mock_reader(12), 2, stage_to_device=False,
                                 host_prefetch=1, device_prefetch=1)
    it = iter(loader)
    next(it)
    time.sleep(1.0)  # scaled-down stand-in for a long XLA compile
    rest = list(it)  # must terminate, not hang
    assert len(rest) == 5


def test_stage_in_producer_yields_device_arrays_same_values():
    import jax

    ref = make_jax_dataloader(_mock_reader(6), 3)
    with ref:
        expected = [{k: np.asarray(v) for k, v in b.items()}
                    for b in ref]
    loader = make_jax_dataloader(_mock_reader(6), 3, stage_in_producer=True)
    with loader:
        batches = list(loader)
    assert len(batches) == len(expected) == 2
    for got, want in zip(batches, expected):
        assert isinstance(got["vec"], jax.Array)
        np.testing.assert_array_equal(np.asarray(got["vec"]), want["vec"])
    # dispatch time is accounted (now on the producer thread)
    assert loader.diagnostics["device_dispatch_s"] >= 0.0


def test_stage_in_producer_rejects_sharding():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    with pytest.raises(ValueError, match="stage_in_producer"):
        make_jax_dataloader(_mock_reader(4), 2, sharding=sharding,
                            stage_in_producer=True)


def test_stage_in_producer_end_to_end(petastorm_dataset):
    import jax

    from petastorm_tpu import make_reader

    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 10, last_batch="drop",
                                 non_tensor_policy="drop",
                                 stage_in_producer=True)
    rows = 0
    with loader:
        for batch in loader:
            assert isinstance(batch["id"], jax.Array)
            rows += batch["id"].shape[0]
    assert rows > 0


def test_reiteration_joins_both_pipeline_threads(petastorm_dataset):
    """Re-iterating a stage_in_producer loader must stop and join BOTH the
    decode thread and the staging thread before reassigning queues — a
    surviving old stager would inject stale batches / a premature sentinel
    into the new iteration (even when the producer already exited)."""
    import time

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader

    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=None, shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 5, stage_in_producer=True,
                                 non_tensor_policy="drop")
    real_stage = loader._stage
    loader._stage = lambda b: (time.sleep(0.3), real_stage(b))[1]
    it = iter(loader)
    next(it)
    old_producer, old_stager = loader._producer, loader._stager
    assert old_stager is not None
    it2 = iter(loader)  # must join the old threads, then start fresh ones
    assert loader._stager is not old_stager
    assert not old_stager.is_alive()
    assert not old_producer.is_alive()
    batch = next(it2)
    assert batch["id"].shape == (5,)
    loader.stop(); loader.join()
    reader.stop(); reader.join()
