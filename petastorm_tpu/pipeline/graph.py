"""Explicit pipeline stage graph (docs/guides/pipeline.md).

The reader/worker/loader stack is described as a chain of
:class:`StageNode` s — worker side ``read → decode → transform → collate
→ serialize → send``, client/loader side ``recv → queue →
raw_stage/device_decode → device_put → consume`` — instead of the
hard-wired layout the code used to imply. Each node carries:

- its **measured cost**: a callable returning the cumulative
  ``(count, seconds)`` of the stage, fed from the per-stage histograms
  the telemetry registry already collects (``telemetry/metrics.py``) —
  nodes whose stage has no process-local series (a remote worker's
  stages seen from the trainer) carry ``None`` and are profiled through
  the graph's *signals* instead (recv-stall, credit-wait);
- a **placement** attribute — ``trainer`` (runs on the trainer host),
  ``worker`` (runs on a service worker), or ``device`` (runs on the
  accelerator). The batch-transform stage is the placement-FLIPPABLE
  one: :class:`~petastorm_tpu.service.client.ServiceBatchSource` can
  move it between trainer and worker per iteration, and the autotuner
  does so from measured profiles.

On top of the nodes, the graph binds :class:`Knob` s — the runtime
handles the online autotuner (``pipeline/autotune.py``) adjusts within
declared bounds: reader-pool ``workers_count``
(:meth:`ThreadPool.resize`), loader ``host_prefetch`` /
``device_prefetch`` (live queue/window resizes), client ``credits`` /
``ready_queue_depth``, and ``transform_placement``.

``build_loader_graph`` is the one constructor call sites use: it
inspects a :class:`JaxDataLoader` (and its reader or
``ServiceBatchSource``) and wires nodes, signals, and knobs to the live
objects. ``PipelineGraph.snapshot()`` reads everything once —
cumulative, monotonic; the autotune controller windows consecutive
snapshots into the profiles the pure planner consumes.
"""

from __future__ import annotations

import os

#: Placement vocabulary: where a stage's work executes.
PLACEMENTS = ("trainer", "worker", "device")


class StageNode:
    """One pipeline stage: a name, where it runs, and how it is measured.

    :param name: stage name (unique within a graph side).
    :param side: ``"worker"`` (produces batches) or ``"client"``
        (consumes them) — the two chains of the stage graph.
    :param placement: one of :data:`PLACEMENTS`.
    :param metric: zero-arg callable returning cumulative
        ``(count, seconds)`` for the stage, or ``None`` when the stage
        has no process-local series (its cost is then inferred from
        graph signals).
    :param flippable: True for the stage whose placement the autotuner
        may move (the batch transform).
    :param description: one line for rendering/docs.
    """

    def __init__(self, name, side, placement, metric=None, flippable=False,
                 description="", placement_fn=None, fuse_group=None):
        if side not in ("worker", "client"):
            raise ValueError(f"side must be worker|client, got {side!r}")
        if placement not in PLACEMENTS:
            raise ValueError(
                f"placement must be one of {PLACEMENTS}, got {placement!r}")
        self.name = name
        self.side = side
        self._placement = placement
        self.metric = metric
        self.flippable = flippable
        self.description = description
        #: Fuse metadata (the stage-fusion rewrite): the ordered stage
        #: names this node collapses into ONE pool task with when
        #: ``stage_fusion`` flips to ``fused`` — the graph keeps the
        #: individual nodes (cost stays attributed per constituent stage
        #: via the fused-stage telemetry), the metadata records the fusion
        #: group they execute as.
        self.fuse_group = tuple(fuse_group) if fuse_group else None
        #: Flippable stages read their placement live (a
        #: transform_placement flip must show in the next snapshot, not
        #: the build-time value forever).
        self._placement_fn = placement_fn

    @property
    def placement(self):
        if self._placement_fn is not None:
            value = self._placement_fn()
            if value in PLACEMENTS:
                return value
        return self._placement

    def measure(self):
        """Cumulative ``(count, seconds)`` — ``(0, 0.0)`` when unmeasured."""
        if self.metric is None:
            return (0, 0.0)
        return self.metric()

    def __repr__(self):
        return (f"StageNode({self.name!r}, side={self.side!r}, "
                f"placement={self.placement!r})")


class Knob:
    """A runtime-adjustable pipeline parameter with declared bounds.

    :param name: knob name (the telemetry label value).
    :param get/set: live accessors against the owning object. ``set``
        receives an already-clamped value.
    :param lo/hi: inclusive bounds — the autotuner NEVER sets a value
        outside them (clamped at apply time as well as plan time).
    :param kind: ``"int"`` (geometric hill-climb steps) or ``"choice"``
        (discrete flip between ``choices``).
    :param choices: for ``kind="choice"``: the allowed values.
    :param applies: ``"live"`` (takes effect immediately),
        ``"next-stream"`` (new worker streams only), or
        ``"next-iteration"`` (sampled at the next epoch/iteration
        boundary) — surfaced in the decision trail so an audit knows
        when a change could have mattered.
    """

    def __init__(self, name, get, set, lo=None, hi=None, kind="int",
                 choices=None, applies="live", rewrite=None):
        if kind not in ("int", "choice"):
            raise ValueError(f"kind must be int|choice, got {kind!r}")
        if kind == "choice" and not choices:
            raise ValueError("choice knobs need choices")
        if kind == "int" and (lo is None or hi is None or lo > hi):
            raise ValueError(f"int knob {name!r} needs lo <= hi bounds")
        self.name = name
        self.get = get
        self.set = set
        self.lo = lo
        self.hi = hi
        self.kind = kind
        self.choices = tuple(choices) if choices else None
        self.applies = applies
        #: Rewrite-kind tag (``pipeline/rewrites.py``): names the graph
        #: rewrite this knob applies. The planner gates tagged knobs on
        #: their trigger economics and the longer ``rewrite_hysteresis``.
        self.rewrite = rewrite

    def clamp(self, value):
        if self.kind == "choice":
            return value if value in self.choices else self.get()
        return max(self.lo, min(self.hi, int(value)))

    def descriptor(self):
        """The planner-facing bound/kind description (pure data)."""
        out = {"kind": self.kind, "applies": self.applies}
        if self.kind == "choice":
            out["choices"] = list(self.choices)
        else:
            out["lo"] = self.lo
            out["hi"] = self.hi
        if self.rewrite:
            out["rewrite"] = self.rewrite
        return out


class PipelineGraph:
    """A pipeline described as stage nodes + edges + knobs + signals.

    ``signals`` are graph-level cumulative measurements that are not a
    single stage's histogram — wall-adjacent quantities the planner
    classifies bottlenecks from: ``rows`` delivered, ``stall_s`` (the
    consumer blocked on input), ``queue_wait_s`` (the producer blocked
    on a full queue), ``recv_stall_s`` (client reader threads blocked on
    workers), ``credit_wait_s`` (workers blocked on the client's credit
    window — only visible when worker and trainer share a process, e.g.
    the loopback scenario; ``None`` otherwise).
    """

    def __init__(self, nodes, edges, knobs=(), signals=None):
        self.nodes = {}
        for node in nodes:
            key = (node.side, node.name)
            if key in self.nodes:
                raise ValueError(f"duplicate stage {key}")
            self.nodes[key] = node
        names = {key[1] for key in self.nodes}
        for src, dst in edges:
            if src not in names or dst not in names:
                raise ValueError(f"edge ({src!r}, {dst!r}) names an "
                                 f"unknown stage")
        self.edges = list(edges)
        self.knobs = {}
        for knob in knobs:
            if knob.name in self.knobs:
                raise ValueError(f"duplicate knob {knob.name!r}")
            self.knobs[knob.name] = knob
        self._signals = dict(signals or {})

    def node(self, name, side=None):
        for (node_side, node_name), node in self.nodes.items():
            if node_name == name and (side is None or node_side == side):
                return node
        raise KeyError(name)

    def snapshot(self):
        """One cumulative reading of every stage, signal, and knob.

        Monotonic where the underlying series are; the autotune
        controller subtracts consecutive snapshots to window a profile.
        Pure data — safe to can into planner tests.
        """
        stages = {}
        for (side, name), node in self.nodes.items():
            count, seconds = node.measure()
            stages[name] = {"side": side, "placement": node.placement,
                            "count": int(count),
                            "seconds": float(seconds)}
            if node.fuse_group:
                stages[name]["fuse_group"] = list(node.fuse_group)
        signals = {}
        for name, fn in self._signals.items():
            try:
                signals[name] = fn()
            except Exception:
                signals[name] = None
        return {
            "stages": stages,
            "signals": signals,
            "knobs": {name: knob.get() for name, knob in self.knobs.items()},
        }

    def describe(self):
        """Static structure (no measurements) — what ``pipeline.md``
        documents and the decision trail embeds once."""
        return {
            "stages": [{"name": node.name, "side": node.side,
                        "placement": node.placement,
                        "flippable": node.flippable,
                        "fuse_group": (list(node.fuse_group)
                                       if node.fuse_group else None),
                        "description": node.description}
                       for node in self.nodes.values()],
            "edges": list(self.edges),
            "knobs": {name: knob.descriptor()
                      for name, knob in self.knobs.items()},
        }


def _histogram_metric(child):
    """Adapt a registry histogram child to the node metric contract."""
    return lambda: (child.count, child.sum)


def _default_workers_hi():
    return max(4, 2 * (os.cpu_count() or 1))


def build_loader_graph(loader, bounds=None):
    """Describe a live :class:`JaxDataLoader`'s pipeline as a graph.

    Wires the client-side chain to the loader's own stage histograms,
    adds the worker-side chain (measured when a local reader runs
    in-process; declared-but-unmeasured for remote service workers,
    whose cost the planner reads through recv-stall/credit-wait
    signals), and binds every runtime-resizable knob the attached
    objects support:

    - ``workers_count`` — when ``loader.reader`` has a resizable pool
      (thread pools; process pools are not runtime-resizable);
    - ``host_prefetch`` / ``device_prefetch`` — always;
    - ``credits`` / ``ready_queue_depth`` / ``transform_placement`` —
      when the batch source is a ``ServiceBatchSource`` (placement only
      when a transform callable is armed).

    ``bounds`` overrides per-knob ``(lo, hi)`` tuples.
    """
    bounds = dict(bounds or {})

    def bound(name, lo, hi):
        return bounds.get(name, (lo, hi))

    stage = loader._m_stage
    source = loader._batch_source
    reader = loader.reader
    nodes = []
    edges = []
    remote = source is not None

    # -- worker side: read → decode → transform → collate → serialize → send
    worker_placement = "worker" if remote else "trainer"
    # On the local path, read+decode+transform+collate are all inside the
    # producer's reader pull — one measured stage ("decode" histogram); the
    # finer-grained split exists on the graph (the model is the contract)
    # with the measured series attached to the stage that times the whole
    # pull. Worker-side series for the service path are per-worker and
    # remote; they stay unmeasured here and profile through signals.
    nodes.append(StageNode(
        "read", "worker", worker_placement,
        description="Parquet row-group read"))
    nodes.append(StageNode(
        "decode", "worker", worker_placement,
        metric=(_histogram_metric(stage["decode"]) if not remote else None),
        description=("reader pull: codec decode (+read/transform/collate "
                     "on the local path — one measured stage)")))
    nodes.append(StageNode(
        "transform", "worker",
        worker_placement if _transform_remote(source) else "trainer",
        flippable=_has_transform(source),
        metric=(_transform_metric if _has_transform(source) else None),
        placement_fn=(
            (lambda: "trainer"
             if not _transform_remote(source) else worker_placement)
            if _has_transform(source) else None),
        description="placement-flippable collated-batch transform"))
    nodes.append(StageNode(
        "collate", "worker", worker_placement,
        description="rows → fixed-size numpy batch"))
    packing_spec = _packing_spec(source)
    if packing_spec is not None:
        # The sequence-packing stage (docs/guides/llm.md): ratio-changing
        # (N row batches → M packed batches), placement-flippable when
        # the source is wrapped in a PackedBatchSource — worker-side it
        # runs pre-serialization (cache entries hold packed frames),
        # trainer-side it packs the received row stream.
        nodes.append(StageNode(
            "pack", "worker",
            worker_placement if _packing_remote(source) else "trainer",
            flippable=_packing_flippable(source),
            metric=_packing_metric,
            placement_fn=(
                (lambda: "trainer" if not _packing_remote(source)
                 else worker_placement)
                if _packing_flippable(source) else None),
            description=(f"sequence packing into "
                         f"[{packing_spec['slots']}, "
                         f"{packing_spec['slot_len']}] + segment ids")))
    nodes.append(StageNode(
        "serialize", "worker", worker_placement,
        metric=(_fused_stage_metric("serialize") if remote else None),
        description="batch → wire frames (service path only)"))
    nodes.append(StageNode(
        "send", "worker", worker_placement,
        description="framed socket send (service path only)"))
    if packing_spec is not None:
        edges += [("read", "decode"), ("decode", "transform"),
                  ("transform", "collate"), ("collate", "pack"),
                  ("pack", "serialize"), ("serialize", "send")]
    else:
        edges += [("read", "decode"), ("decode", "transform"),
                  ("transform", "collate"), ("collate", "serialize"),
                  ("serialize", "send")]
    if remote:
        # Fuse metadata (stage-fusion rewrite): these worker-side stages
        # collapse into ONE pool task per piece when stage_fusion flips to
        # "fused". The nodes stay — collate/serialize read their fused
        # cost from the fused-stage telemetry (per-constituent
        # attribution), and the metadata names the group they execute as.
        group = ("decode", "transform", "collate", "serialize") \
            if packing_spec is None \
            else ("decode", "transform", "collate", "pack", "serialize")
        for node in nodes:
            if node.side == "worker" and node.name in group:
                node.fuse_group = group
                # Collate reads the fused task's "collate" segment (which
                # includes the packing wrapper's work when worker-placed
                # packing is fused — the pack node's own _packing_metric
                # stays the precise packing measurement); serialize was
                # wired above.
                if node.metric is None and node.name == "collate":
                    node.metric = _fused_stage_metric("collate")

    # -- client side: recv → queue → raw_stage/device_decode → device_put
    #    → consume
    nodes.append(StageNode(
        "recv", "client", "trainer",
        metric=_histogram_metric(stage["wait"]),
        description="consumer blocked on the next host batch (the stall)"))
    nodes.append(StageNode(
        "queue", "client", "trainer",
        metric=_histogram_metric(stage["queue_wait"]),
        description="producer blocked on a full host queue"))
    nodes.append(StageNode(
        "raw_stage", "client", "trainer",
        metric=_histogram_metric(stage["raw_stage"]),
        description="raw uint8 bytes batch staged to device"))
    device_stage = getattr(loader, "_device_stage", None)
    nodes.append(StageNode(
        "device_decode", "client", "device",
        metric=_histogram_metric(stage["device_decode"]),
        description=("fused on-device decode/augment kernel dispatch"
                     + (f" {device_stage.describe()}"
                        if device_stage is not None else ""))))
    nodes.append(StageNode(
        "device_put", "client", "trainer",
        metric=_histogram_metric(stage["device_put"]),
        description="H2D dispatch of ordinary tensors"))
    nodes.append(StageNode(
        "consume", "client", "device",
        metric=_histogram_metric(stage["consumer"]),
        description="training step between yields"))
    edges += [("send", "recv"), ("recv", "queue"), ("queue", "raw_stage"),
              ("queue", "device_put"), ("raw_stage", "device_decode"),
              ("device_decode", "consume"), ("device_put", "consume")]

    knobs = []
    pool = getattr(reader, "_workers_pool", None) if reader is not None \
        else None
    if pool is not None and hasattr(pool, "resize") \
            and hasattr(reader, "resize_workers"):
        lo, hi = bound("workers_count", 1, _default_workers_hi())
        knobs.append(Knob(
            "workers_count",
            get=lambda: pool.workers_count,
            set=reader.resize_workers, lo=lo, hi=hi))
    if not remote or loader._stage_in_producer:
        # A prefetched batch_source is consumed DIRECTLY (no producer
        # thread, no host queue — the source's ready-queue/credits are
        # the buffering): binding host_prefetch there would hand the
        # planner a dead knob that burns probe rounds and journals
        # fictitious decisions.
        lo, hi = bound("host_prefetch", 1, 64)
        knobs.append(Knob(
            "host_prefetch",
            get=lambda: loader.host_prefetch,
            set=lambda v: setattr(loader, "host_prefetch", v),
            lo=lo, hi=hi))
    lo, hi = bound("device_prefetch", 1, 16)
    knobs.append(Knob(
        "device_prefetch",
        get=lambda: loader.device_prefetch,
        set=lambda v: setattr(loader, "device_prefetch", v), lo=lo, hi=hi))
    if remote and hasattr(source, "set_credits") \
            and getattr(source, "credits", None) is not None:
        lo, hi = bound("credits", 1, 64)
        knobs.append(Knob(
            "credits", get=lambda: source.credits,
            set=source.set_credits, lo=lo, hi=hi, applies="next-stream"))
    if remote and hasattr(source, "set_ready_queue_depth") \
            and source._ready_queue_depth is not None:
        # Bound only when the user PINNED an explicit depth. A derived
        # depth (the default) already tracks the credits knob —
        # set_credits re-derives the live queue bound — and an autotuner
        # probe here would silently pin it, disabling derived sizing
        # forever (a revert restores the pre-probe NUMBER, not
        # derived-ness).
        lo, hi = bound("ready_queue_depth", 2, 256)
        knobs.append(Knob(
            "ready_queue_depth",
            get=lambda: source.ready_queue_depth,
            set=source.set_ready_queue_depth, lo=lo, hi=hi))
    if _has_transform(source):
        knobs.append(Knob(
            "transform_placement",
            get=lambda: source.transform_placement,
            set=source.set_transform_placement,
            kind="choice", choices=("remote", "local"),
            applies="next-iteration"))
    if _packing_flippable(source):
        # The set_transform_placement-style binding for the packing
        # stage: the autotuner may move packing between the workers
        # (cache holds packed frames, trainer receives dense batches)
        # and the trainer (workers serve row batches, this host packs).
        knobs.append(Knob(
            "packing_placement",
            get=lambda: source.packing_placement,
            set=source.set_packing_placement,
            kind="choice", choices=("worker", "trainer"),
            applies="next-iteration"))
    # -- graph-rewrite knobs (pipeline/rewrites.py): choice knobs tagged
    #    with their rewrite kind, so the planner gates them on trigger
    #    economics and the longer rewrite_hysteresis. Never bound on an
    #    fcfs-mode source: rewrites run inside the streaming engine
    #    (tagged/dynamic protocols), so an automated flip there would
    #    crash the next iteration instead of probing — the graph is built
    #    after the source's first __call__, so the mode is known.
    rewritable = remote and getattr(source, "_mode", None) != "fcfs"
    if rewritable and hasattr(source, "set_stage_fusion"):
        knobs.append(Knob(
            "stage_fusion",
            get=lambda: source.stage_fusion,
            set=source.set_stage_fusion,
            kind="choice", choices=("off", "fused"),
            applies="next-iteration", rewrite="fuse_worker_stages"))
    if rewritable and getattr(source, "_predicate", None) is not None \
            and hasattr(source, "set_filter_placement") \
            and getattr(source, "transform", None) is None:
        # With a transform armed the filter is PINNED hoisted (a
        # client-placed filter would see post-transform batches) — no
        # flippable placement, so no knob to bind.
        knobs.append(Knob(
            "filter_placement",
            get=lambda: source.filter_placement,
            set=source.set_filter_placement,
            kind="choice", choices=("client", "worker"),
            applies="next-iteration", rewrite="hoist_filter"))
    if rewritable and getattr(source, "transform", None) is not None \
            and hasattr(source, "set_cache_placement"):
        knobs.append(Knob(
            "cache_placement",
            get=lambda: source.cache_placement,
            set=source.set_cache_placement,
            kind="choice", choices=("post-transform", "post-decode"),
            applies="next-iteration", rewrite="cache_placement"))
    if rewritable and hasattr(source, "set_reader_family"):
        # row_vs_columnar: which decode family the workers serve the
        # stream through. get() reports "row" for the unset default (the
        # planner needs a concrete baseline to revert to); a worker whose
        # constructed family cannot honor the request degrades per stream
        # (bytes identical), so a probe is always safe.
        knobs.append(Knob(
            "reader_family",
            get=lambda: source.reader_family or "row",
            set=source.set_reader_family,
            kind="choice", choices=("row", "columnar"),
            applies="next-iteration", rewrite="row_vs_columnar"))

    signals = {
        "rows": lambda: loader._m_rows.value,
        "stall_s": lambda: stage["wait"].sum,
        "queue_wait_s": lambda: stage["queue_wait"].sum,
        "decode_s": lambda: stage["decode"].sum,
        "dispatch_s": lambda: (stage["raw_stage"].sum
                               + stage["device_decode"].sum
                               + stage["device_put"].sum),
        "consumer_s": lambda: stage["consumer"].sum,
    }
    if remote:
        signals["recv_stall_s"] = lambda: _source_recv_stall(source)
        signals["credit_wait_s"] = _process_credit_wait
        # Rewrite-trigger signals (pipeline/rewrites.py). The worker-side
        # ones are process-local series — populated in loopback/
        # in-process deployments (the bench scenario, tests); a remote
        # fleet's series are not visible here and the untriggerable
        # rewrites simply never probe.
        signals["worker_decode_s"] = _process_worker_decode
        signals["handoff_s"] = _process_handoff
        signals["transform_s"] = lambda: _transform_metric()[1]
        signals["cache_hits"] = lambda: _process_cache_counter("hits")
        signals["cache_misses"] = lambda: _process_cache_counter("misses")
        signals["cache_evictions"] = \
            lambda: _process_cache_counter("evictions")
        signals["filter_rows_in"] = lambda: _client_filter_rows("in")
        signals["filter_rows_kept"] = lambda: _client_filter_rows("kept")
    return PipelineGraph(nodes, edges, knobs=knobs, signals=signals)


def _fused_stage_metric(stage):
    """Node metric fed from the fused-task per-constituent counters
    (``petastorm_service_worker_fused_stage_seconds_total{stage}``) —
    visible in-process (loopback deployments); zero while unfused or
    remote."""

    def measure():
        from petastorm_tpu.telemetry.metrics import (
            WORKER_FUSED_STAGE_SECONDS,
        )

        child = WORKER_FUSED_STAGE_SECONDS.children().get((stage,))
        return (0, float(child.value) if child is not None else 0.0)

    return measure


def _process_worker_decode():
    """Cumulative worker decode seconds visible in THIS process's
    registry (loopback/in-process deployments) — the stage-work
    denominator of the fusion trigger."""
    from petastorm_tpu.telemetry.metrics import WORKER_DECODE_SECONDS

    return float(sum(child.sum
                     for child in WORKER_DECODE_SECONDS.children().values()))


def _process_handoff():
    """Cumulative stream-thread hand-off seconds (collation +
    serialization of pool outputs) across in-process workers — the cost
    the stage-fusion rewrite eliminates."""
    from petastorm_tpu.telemetry.metrics import WORKER_HANDOFF_SECONDS

    return float(sum(child.value
                     for child in WORKER_HANDOFF_SECONDS.children().values()))


def _process_cache_counter(which):
    """Tier-summed batch-cache counters visible in this process — the
    cache-placement rewrite's hit-economics signals."""
    from petastorm_tpu.telemetry.metrics import (
        CACHE_EVICTIONS,
        CACHE_HITS,
        CACHE_MISSES,
    )

    family = {"hits": CACHE_HITS, "misses": CACHE_MISSES,
              "evictions": CACHE_EVICTIONS}[which]
    return float(sum(child.value for child in family.children().values()))


def _client_filter_rows(outcome):
    from petastorm_tpu.telemetry.metrics import CLIENT_FILTER_ROWS

    return float(CLIENT_FILTER_ROWS.labels(outcome).value)


def _has_transform(source):
    return (source is not None
            and getattr(source, "transform", None) is not None)


def _packing_spec(source):
    """The packing spec dict when the source packs (PackedBatchSource
    wrapper, or a ServiceBatchSource with packing= armed), else None."""
    if source is None:
        return None
    spec = getattr(source, "spec", None)
    if spec is not None and hasattr(spec, "key_dict") \
            and hasattr(source, "packing_placement"):
        return spec.key_dict()
    packing = getattr(source, "packing", None)
    return packing.key_dict() if packing is not None \
        and hasattr(packing, "key_dict") else None


def _packing_flippable(source):
    return (source is not None
            and hasattr(source, "set_packing_placement")
            and _packing_spec(source) is not None)


def _packing_remote(source):
    return (getattr(source, "packing_placement", "worker") == "worker"
            if source is not None else True)


def _packing_metric():
    """Cumulative (count, seconds) of the packing stage across both
    placements (trainer-side always in-process; worker-side series join
    loopback deployments), mirroring ``_transform_metric``."""
    from petastorm_tpu.telemetry.metrics import PACKING_SECONDS

    count = total = 0
    for child in PACKING_SECONDS.children().values():
        count += child.count
        total += child.sum
    return count, total


def _transform_remote(source):
    return (getattr(source, "transform_placement", "remote") == "remote"
            if source is not None else True)


def _source_recv_stall(source):
    """Total seconds the client's stream-reader threads spent blocked
    waiting on their workers (per-worker stall summed)."""
    diag = getattr(source, "diagnostics", None)
    if not isinstance(diag, dict):
        return 0.0
    return float(sum(w.get("stall_s", 0.0)
                     for w in diag.get("per_worker", {}).values()))


def _transform_metric():
    """Cumulative (count, seconds) of the batch-transform stage across
    BOTH placements: the client-side histogram always lives in this
    process; worker-side series join in-process deployments (loopback),
    so the node's cost follows the stage wherever it runs."""
    from petastorm_tpu.telemetry.metrics import (
        CLIENT_TRANSFORM_SECONDS,
        WORKER_TRANSFORM_SECONDS,
    )

    client = CLIENT_TRANSFORM_SECONDS.labels()
    count, total = client.count, client.sum
    for child in WORKER_TRANSFORM_SECONDS.children().values():
        count += child.count
        total += child.sum
    return count, total


def _process_credit_wait():
    """Cumulative worker credit-wait seconds visible in THIS process's
    registry — populated in loopback/in-process deployments (the bench
    scenario, tests); a remote fleet's credit waits are not visible here
    and the planner falls back to client-side signals alone."""
    from petastorm_tpu.telemetry.metrics import WORKER_CREDIT_WAIT

    return float(sum(child.value
                     for child in WORKER_CREDIT_WAIT.children().values()))
