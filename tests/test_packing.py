"""Sequence packing: ragged rows → dense [B, T] + segment ids, and the
end-to-end property that matters — attention over a PACKED batch equals
per-sequence attention over the original ragged rows."""

import numpy as np
import pytest

from petastorm_tpu.jax_utils.packing import (
    PACK_POSITION_KEY,
    PACK_SEGMENT_KEY,
    pack_ragged,
    packed_valid_mask,
    unpack,
)


def _ragged_rows(lengths, d=4, seed=0):
    rng = np.random.RandomState(seed)
    return [{"tokens": rng.randn(n, d).astype(np.float32),
             "ids": np.arange(n).astype(np.int64) + 100 * i}
            for i, n in enumerate(lengths)]


def test_pack_roundtrip_exactly_once():
    rows = _ragged_rows([5, 9, 3, 8, 2, 7, 6])
    packed = list(pack_ragged(iter(rows), slot_len=12, slots=2))
    recovered = [seq for batch in packed for seq in unpack(batch, "ids")]
    want = sorted(tuple(r["ids"]) for r in rows)
    got = sorted(tuple(s) for s in recovered)
    assert got == want  # every sequence placed exactly once, intact


def test_pack_layout_invariants():
    rows = _ragged_rows([4, 6, 5])
    (batch,) = pack_ragged(iter(rows), slot_len=10, slots=2)
    seg, pos = batch[PACK_SEGMENT_KEY], batch[PACK_POSITION_KEY]
    assert seg.shape == pos.shape == (2, 10)
    # row 0: seqs of 4 then 6 (first-fit); row 1: seq of 5
    np.testing.assert_array_equal(seg[0], [0] * 4 + [1] * 6)
    np.testing.assert_array_equal(pos[0], list(range(4)) + list(range(6)))
    np.testing.assert_array_equal(seg[1], [0] * 5 + [-1] * 5)
    np.testing.assert_array_equal(pos[1], list(range(5)) + [0] * 5)
    # padding tokens are zeros; valid mask matches seg >= 0
    np.testing.assert_array_equal(batch["tokens"][1, 5:], 0.0)
    np.testing.assert_array_equal(packed_valid_mask(seg), seg >= 0)


def test_pack_emits_when_full_and_flushes_tail():
    rows = _ragged_rows([8, 8, 8])
    batches = list(pack_ragged(iter(rows), slot_len=8, slots=2))
    assert len(batches) == 2  # two full slots, then the flushed tail
    assert (batches[0][PACK_SEGMENT_KEY] >= 0).all()
    tail_seg = batches[1][PACK_SEGMENT_KEY]
    assert (tail_seg[0] == 0).all() and (tail_seg[1] == -1).all()


def test_pack_rejects_overlong_and_mismatched():
    with pytest.raises(ValueError, match="does not fit"):
        list(pack_ragged(iter(_ragged_rows([9])), slot_len=8, slots=1))
    bad = [{"tokens": np.zeros((4, 2), np.float32),
            "ids": np.arange(3)}]
    with pytest.raises(ValueError, match="must share the sequence axis"):
        list(pack_ragged(iter(bad), slot_len=8, slots=1))


@pytest.mark.parametrize("causal", [False, True])
def test_packed_flash_attention_equals_per_sequence(causal):
    """The gold property: flash attention over the packed batch, masked by
    segment ids, is bit-for-tolerance identical to running dense attention
    on each ragged sequence separately."""
    import jax.numpy as jnp

    from petastorm_tpu.models.sequence_model import attention_reference
    from petastorm_tpu.ops import flash_attention

    h, d = 2, 8
    lengths = [11, 5, 16, 9, 7]
    rng = np.random.RandomState(1)
    seqs = [rng.randn(n, h * 3 * d).astype(np.float32) for n in lengths]

    (batch,) = pack_ragged(
        ({"qkv": s} for s in seqs), slot_len=16, slots=3)
    seg = jnp.asarray(batch[PACK_SEGMENT_KEY])
    qkv = batch["qkv"].reshape(3, 16, 3, h, d)  # [B, T, (q|k|v), H, D]
    q, k, v = (jnp.asarray(qkv[:, :, i]) for i in range(3))

    out = flash_attention(q, k, v, block_q=8, block_k=16, causal=causal,
                          segment_ids=seg)

    for i, s in enumerate(seqs):
        per = s.reshape(1, lengths[i], 3, h, d)
        pq, pk, pv = (jnp.asarray(per[:, :, j]) for j in range(3))
        want = attention_reference(pq, pk, pv, causal=causal)
        # locate sequence i in the packed batch
        flat = [(b, sid) for b in range(seg.shape[0])
                for sid in range(int(seg[b].max()) + 1)
                if (np.asarray(seg[b]) == sid).any()]
        b, sid = flat[i]
        mask = np.asarray(seg[b]) == sid
        np.testing.assert_allclose(np.asarray(out)[b][mask],
                                   np.asarray(want)[0],
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"sequence {i} (causal={causal})")


def test_packed_flash_gradients_isolated_across_segments():
    """Gradient of a loss on ONE segment must not leak into other
    sequences' token gradients (the segment mask holds in the backward)."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.ops import flash_attention

    rng = np.random.RandomState(2)
    seg = jnp.asarray(np.array([[0] * 6 + [1] * 10]), jnp.int32)
    x = jnp.asarray(rng.randn(1, 16, 1, 8).astype(np.float32))

    def loss(x):
        out = flash_attention(x, x, x, block_q=8, block_k=16,
                              segment_ids=seg)
        return (out[0, :6] ** 2).sum()  # loss touches segment 0 only

    g = jax.grad(loss)(x)
    assert float(jnp.abs(g[0, :6]).max()) > 0
    np.testing.assert_array_equal(np.asarray(g[0, 6:]), 0.0)


def test_pack_skips_empty_sequences():
    """Zero-length rows carry no tokens: they must not burn a segment id
    (which would break the exactly-once round-trip)."""
    rows = [{"ids": np.arange(3)}, {"ids": np.arange(0)},
            {"ids": np.arange(2) + 10}]
    (batch,) = pack_ragged(iter(rows), slot_len=8, slots=1)
    np.testing.assert_array_equal(batch[PACK_SEGMENT_KEY][0],
                                  [0, 0, 0, 1, 1, -1, -1, -1])
    got = [tuple(s) for s in unpack(batch, "ids")]
    assert got == [(0, 1, 2), (10, 11)]


def test_packed_loader_end_to_end(tmp_path):
    """make_packed_jax_dataloader: reader -> pack -> the loader's staging
    machinery, covering both reader flavors and the resume guard."""
    import pytest as _pytest

    from petastorm_tpu import make_columnar_reader, make_reader
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.jax_utils import make_packed_jax_dataloader
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("Ragged", [
        UnischemaField("seq", np.float32, (12, 3), NdarrayCodec(), False),
        UnischemaField("length", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(0)
    lengths = [int(rng.randint(2, 13)) for _ in range(24)]
    rows = []
    for n in lengths:
        seq = np.zeros((12, 3), np.float32)
        seq[:n] = rng.randn(n, 3)
        rows.append({"seq": seq, "length": np.int32(n)})
    url = f"file://{tmp_path}/ragged"
    materialize_rows(url, schema, rows, rows_per_row_group=8)

    for factory in (make_reader, make_columnar_reader):
        reader = factory(url, num_epochs=1, shuffle_row_groups=False)
        loader = make_packed_jax_dataloader(
            reader, slot_len=16, slots=2, sequence_fields=["seq"],
            length_field="length", stage_to_device=False)
        total_valid = 0
        with loader:
            for batch in loader:
                assert batch["seq"].shape == (2, 16, 3)
                assert batch[PACK_SEGMENT_KEY].shape == (2, 16)
                total_valid += int(packed_valid_mask(
                    batch[PACK_SEGMENT_KEY]).sum())
        assert total_valid == sum(lengths), factory.__name__

    reader = make_reader(url, num_epochs=1)
    loader = make_packed_jax_dataloader(
        reader, slot_len=16, slots=2, sequence_fields=["seq"],
        length_field="length", stage_to_device=False)
    with loader:
        next(iter(loader))
        with _pytest.raises(ValueError, match="batch_source"):
            loader.state_dict()


def test_packed_loader_stages_to_device(tmp_path):
    """stage_to_device=True emits committed jax arrays for packed fields
    AND the segment/position int arrays."""
    import jax

    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.jax_utils import make_packed_jax_dataloader
    from petastorm_tpu.schema.codecs import NdarrayCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("R2", [
        UnischemaField("tok", np.float32, (8, 2), NdarrayCodec(), False),
    ])
    rows = [{"tok": np.random.RandomState(i).randn(8, 2).astype(np.float32)}
            for i in range(6)]
    url = f"file://{tmp_path}/r2"
    materialize_rows(url, schema, rows, rows_per_row_group=4)

    reader = make_reader(url, num_epochs=1)
    loader = make_packed_jax_dataloader(reader, slot_len=16, slots=2,
                                        sequence_fields=["tok"])
    with loader:
        batch = next(iter(loader))
    assert isinstance(batch["tok"], jax.Array)
    assert isinstance(batch[PACK_SEGMENT_KEY], jax.Array)
    assert batch[PACK_SEGMENT_KEY].shape == (2, 16)


def test_packed_loader_rejects_row_batching_knobs_and_unagreed_sharding(
        tmp_path):
    """batch_source composes with staging, not with row-batching knobs, and
    a global sharding needs an explicitly agreed step count."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.jax_utils import make_packed_jax_dataloader
    from petastorm_tpu.schema.codecs import NdarrayCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("R3", [
        UnischemaField("tok", np.float32, (4, 2), NdarrayCodec(), False),
    ])
    url = f"file://{tmp_path}/r3"
    materialize_rows(url, schema,
                     [{"tok": np.zeros((4, 2), np.float32)}] * 4,
                     rows_per_row_group=4)
    reader = make_reader(url, num_epochs=1)
    with pytest.raises(ValueError, match="row-.?batching knobs"):
        make_packed_jax_dataloader(reader, slot_len=8, slots=2,
                                   sequence_fields=["tok"],
                                   shuffle_buffer_size=100)
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    with pytest.raises(ValueError, match="explicit max_batches"):
        make_packed_jax_dataloader(
            reader, slot_len=8, slots=2, sequence_fields=["tok"],
            sharding=NamedSharding(mesh, P("data")))
    reader.stop(); reader.join()


# ---------------------------------------------------------------------------
# pack_ragged input hygiene (ISSUE 14 satellites)
# ---------------------------------------------------------------------------

def test_pack_warns_once_on_dropped_fields(caplog):
    """Non-array/scalar fields are dropped with ONE structured warning
    naming them — silently losing labels from a training stream is how
    data bugs ship."""
    import logging

    rows = [{"tokens": np.arange(3), "label": 7, "weight": 0.5}
            for _ in range(5)]
    with caplog.at_level(logging.WARNING,
                         logger="petastorm_tpu.jax_utils.packing"):
        list(pack_ragged(iter(rows), slot_len=8, slots=1))
    drops = [r for r in caplog.records if "dropping non-packed" in r.message
             or "dropping" in r.getMessage()]
    assert len(drops) == 1
    assert "label" in drops[0].getMessage()
    assert "weight" in drops[0].getMessage()


def test_pack_rejects_unknown_explicit_key():
    """An explicit keys= entry absent from the rows is a configuration
    error named in the exception, never a silent drop."""
    rows = [{"tokens": np.arange(3)}]
    with pytest.raises(ValueError, match="typo_field"):
        list(pack_ragged(iter(rows), slot_len=8, slots=1,
                         keys=["typo_field"]))


# ---------------------------------------------------------------------------
# StreamPacker — the service stage's incremental core
# ---------------------------------------------------------------------------

def _token_rows(lengths, seed=3):
    rng = np.random.RandomState(seed)
    return [{"tokens": rng.randint(1, 1000, size=n).astype(np.int32)}
            for n in lengths]


def _spec(slot_len=16, slots=2):
    from petastorm_tpu.service.packing_stage import PackingSpec

    return PackingSpec(slot_len=slot_len, slots=slots,
                       sequence_fields=["tokens"])


def test_stream_packer_matches_pack_ragged_golden():
    """The incremental packer's emission is bit-identical to the
    whole-stream generator fed the same rows — one first-fit semantics
    at every layer."""
    from petastorm_tpu.service.packing_stage import StreamPacker

    rng = np.random.RandomState(11)
    rows = _token_rows(list(rng.randint(1, 16, size=60)))
    packer = StreamPacker(_spec())
    got = []
    for row in rows:
        got.extend(packer.add_row(row))
    tail = packer.flush()
    if tail is not None:
        got.append(tail)
    want = list(pack_ragged(iter(rows), slot_len=16, slots=2,
                            keys=["tokens"]))
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert sorted(a) == sorted(b)
        for key in a:
            np.testing.assert_array_equal(a[key], b[key])


def test_stream_packer_state_dict_round_trip_bit_exact():
    """Kill-then-restore mid-pack: a fresh packer restored from
    state_dict() continues the packed stream bit-exactly — the open
    (carry-over) batch is real state, not replay."""
    from petastorm_tpu.service.packing_stage import StreamPacker

    rng = np.random.RandomState(7)
    rows = _token_rows(list(rng.randint(1, 16, size=40)), seed=7)
    a = StreamPacker(_spec())
    for row in rows[:25]:
        a.add_row(row)
    snapshot = a.state_dict()
    assert snapshot["open"] is not None  # mid-pack, carry-over live
    b = StreamPacker(_spec())
    b.load_state_dict(snapshot)
    rest_a, rest_b = [], []
    for row in rows[25:]:
        rest_a.extend(a.add_row(row))
        rest_b.extend(b.add_row(row))
    rest_a.append(a.flush())
    rest_b.append(b.flush())
    assert len(rest_a) == len(rest_b)
    for x, y in zip(rest_a, rest_b):
        for key in x:
            np.testing.assert_array_equal(x[key], y[key])


def test_stream_packer_refuses_spec_mismatch_and_torn_state():
    """A snapshot from a different geometry — or one torn mid-write (the
    packing.state failpoint) — must be REFUSED at restore, never
    silently resumed into a corrupted carry-over."""
    from petastorm_tpu import failpoints
    from petastorm_tpu.service.packing_stage import (
        PackingStateError,
        StreamPacker,
    )

    packer = StreamPacker(_spec())
    packer.add_row({"tokens": np.arange(5, dtype=np.int32)})
    other = StreamPacker(_spec(slot_len=32))
    with pytest.raises(PackingStateError, match="geometry|runs"):
        other.load_state_dict(packer.state_dict())

    schedule = failpoints.FaultSchedule(
        seed=0, points=("packing.state",),
        fires={"packing.state": {0: "torn"}})
    with failpoints.armed(schedule):
        torn = packer.state_dict()
    assert schedule.log == [("packing.state", 0, "torn")]
    fresh = StreamPacker(_spec())
    with pytest.raises(PackingStateError, match="crc|torn"):
        fresh.load_state_dict(torn)
    # The untorn snapshot still restores fine after the failpoint scope.
    fresh.load_state_dict(packer.state_dict())
    assert fresh.open_sequences == packer.open_sequences


def test_stream_packer_packed_batch_through_flash_equals_reference():
    """The service stage's layout contract: a StreamPacker-packed batch
    through ops.flash_attention(segment_ids=...) equals per-sequence
    attention_reference on the unpacked rows — same pin as the
    pack_ragged parity test, through the NEW stage."""
    import jax.numpy as jnp

    from petastorm_tpu.models.sequence_model import attention_reference
    from petastorm_tpu.ops import flash_attention
    from petastorm_tpu.service.packing_stage import PackingSpec, StreamPacker

    h, d = 2, 8
    lengths = [11, 5, 16, 9, 7]
    rng = np.random.RandomState(4)
    seqs = [rng.randn(n, h * 3 * d).astype(np.float32) for n in lengths]
    packer = StreamPacker(PackingSpec(slot_len=16, slots=3,
                                      sequence_fields=["qkv"]))
    batches = []
    for s in seqs:
        batches.extend(packer.add_row({"qkv": s}))
    tail = packer.flush()
    if tail is not None:
        batches.append(tail)
    (batch,) = batches
    seg = jnp.asarray(batch[PACK_SEGMENT_KEY])
    qkv = batch["qkv"].reshape(3, 16, 3, h, d)
    q, k, v = (jnp.asarray(qkv[:, :, i]) for i in range(3))
    out = flash_attention(q, k, v, block_q=8, block_k=16, segment_ids=seg)
    flat = [(b, sid) for b in range(seg.shape[0])
            for sid in range(int(seg[b].max()) + 1)
            if (np.asarray(seg[b]) == sid).any()]
    for i, s in enumerate(seqs):
        per = s.reshape(1, lengths[i], 3, h, d)
        pq, pk, pv = (jnp.asarray(per[:, :, j]) for j in range(3))
        want = attention_reference(pq, pk, pv)
        b, sid = flat[i]
        mask = np.asarray(seg[b]) == sid
        np.testing.assert_allclose(np.asarray(out)[b][mask],
                                   np.asarray(want)[0],
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"sequence {i}")
