"""Batch workers: the data plane of the disaggregated data service.

A worker wraps the ordinary single-process input pipeline — a
``make_reader``-family Reader plus ``batch_iterator`` collation — and serves
the resulting ready-to-stage numpy batch dicts over framed TCP. Each
``stream`` request names an explicit set of row-group piece indices (the
dispatcher's split plan), which the worker turns into a Reader via the
reader layer's ``piece_indices=`` planning hook; the stream then carries one
``batch`` message per collated batch and a final ``end`` message with the
row total, all payload-encoded by the pool serializers
(:mod:`petastorm_tpu.reader_impl.framed_socket`).

Remote observability: a ``diagnostics`` request snapshots every active
stream's ``Reader.diagnostics`` (and the final snapshot of recently finished
streams), so a trainer-side client can root-cause a remote input stall the
same way it would a local one (``docs/guides/diagnostics.md``).
"""

from __future__ import annotations

import threading
import time
import uuid

from petastorm_tpu import failpoints
from petastorm_tpu.reader_impl.framed_socket import (
    ConnectionClosedError,
    FramedReader,
    FramedServer,
    ProtocolError,
    encode_payload,
    send_framed,
)
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.clockalign import OffsetEstimator
from petastorm_tpu.telemetry.flight import RECORDER as FLIGHT
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    COLUMNAR_BATCHES,
    FLEET_JOB_CACHE_LOOKUPS,
    FLEET_JOB_ROWS,
    WORKER_ACTIVE_STREAMS,
    WORKER_BATCHES_SENT,
    WORKER_CREDIT_WAIT,
    WORKER_DECODE_SECONDS,
    WORKER_HANDOFF_SECONDS,
    WORKER_READERS_CONSTRUCTED,
    WORKER_ROWS_SENT,
    WORKER_STREAMS,
    WORKER_TRANSFORM_SECONDS,
)

logger = service_logger(__name__)

_FACTORIES = ("row", "batch", "columnar")

#: Final diagnostics snapshots kept for the ``diagnostics`` request.
_COMPLETED_SNAPSHOTS_KEPT = 16


def _resolve_factory(reader_factory):
    if callable(reader_factory):
        return reader_factory
    from petastorm_tpu.reader.reader import (
        make_batch_reader,
        make_columnar_reader,
        make_reader,
    )

    factories = {"row": make_reader, "batch": make_batch_reader,
                 "columnar": make_columnar_reader}
    if reader_factory not in factories:
        raise ValueError(
            f"reader_factory must be a callable or one of {_FACTORIES}, "
            f"got {reader_factory!r}")
    return factories[reader_factory]


def _digest_code(digest, code):
    """Feed a code object's behavior-shaping parts into ``digest``,
    recursing into nested code objects (lambdas, inner defs,
    comprehensions). Deliberately NOT ``repr(co_consts)``: a nested code
    object's repr embeds its memory address and absolute file path, which
    change every process — the key must be stable across restarts (warm
    disk tier) yet change when the code is edited."""
    digest.update(code.co_code)
    digest.update(" ".join(code.co_names).encode())
    for const in code.co_consts:
        if hasattr(const, "co_code"):
            _digest_code(digest, const)
        else:
            digest.update(repr(const).encode())


def _transform_identity(fn):
    """Cache-key ingredient naming a batch transform: module:qualname
    PLUS a digest of the function's compiled body and constants — a
    restarted worker whose transform code was edited must MISS the
    persistent disk tier, not serve bytes transformed by the old code
    (and two same-named lambdas with different bodies must not share
    entries). Closure-captured *values* are not hashable here and stay
    invisible — parameterize through constants or name the version in
    the qualname if a closure variable shapes the output."""
    identity = (f"{getattr(fn, '__module__', '')}:"
                f"{getattr(fn, '__qualname__', repr(fn))}")
    code = getattr(fn, "__code__", None)
    if code is not None:
        import hashlib

        digest = hashlib.blake2b(digest_size=8)
        _digest_code(digest, code)
        identity += f"#{digest.hexdigest()}"
    return identity


class BatchWorker:
    """Serve collated batches of ``dataset_url`` over TCP.

    :param dataset_url: the dataset every stream reads (workers in one
        service must all point at the same dataset).
    :param dispatcher_address: ``(host, port)`` to register with (optional —
        a worker can be addressed directly in tests).
    :param batch_size: rows per collated batch. The last batch of a stream
        is ragged (``last_batch="keep"``): the service must not drop rows —
        equal-step SPMD shaping stays the trainer-side loader's concern.
    :param reader_factory: ``"row"`` (make_reader), ``"batch"``
        (make_batch_reader), ``"columnar"`` (make_columnar_reader), or any
        callable with the same signature.
    :param reader_kwargs: extra kwargs for the factory (``workers_count``,
        ``reader_pool_type``, ``filters``, ...). ``piece_indices``,
        ``num_epochs`` and ``shuffle_row_groups`` are owned by the stream
        protocol.
    :param batch_delay_s: fault injection for benchmarks/tests — sleep this
        long before each ``batch`` send, simulating a slow worker (the
        ``--skew-ms`` knob of the ``service`` benchmark scenario).
    :param heartbeat_interval_s: renew the dispatcher lease this often; a
        worker that misses its lease (``Dispatcher(lease_timeout_s=...)``)
        is evicted. The loop also heals restarts: an ``unknown_worker``
        reply (dispatcher came back without this worker's state) triggers
        automatic re-registration under the same ``worker_id``. ``None``
        disables the loop (direct-addressed test workers).
    :param rpc_deadline_s: total time budget for each control RPC against
        the dispatcher (registration, heartbeats) across all its retries —
        the shared ``retry_with_backoff`` deadline policy.
    :param max_frame_bytes: per-connection receive frame cap (requests to
        a worker are small control messages; batches only flow OUT).
    :param batch_cache: a :class:`~petastorm_tpu.cache_impl.BatchCache` (or
        ``None``). When armed, every ``stream`` request consults the cache
        **per piece** before constructing a reader: warm pieces are served
        as pre-serialized frames scatter-gathered straight from cache
        memory (epoch ≥ 2 of a multi-epoch run skips Parquet + decode +
        pickle entirely), cold pieces are decoded through a per-piece
        reader and written through to the cache (and its disk tier, which
        survives worker restarts). Keys fingerprint the dataset url, piece
        index, batch size, selected fields, and transform config
        (``docs/guides/caching.md``). NOTE batch boundaries then align to
        piece boundaries (a ragged batch per piece tail, not just per
        stream). The worker owns the instance: ``stop()`` calls its
        ``cleanup()``.
    :param batch_transform: the placement-flippable collated-batch
        transform — ``{field: ndarray} -> {field: ndarray}``, applied to
        each batch after collation and before serialization (timed into
        ``petastorm_service_worker_transform_seconds``). A stream request
        carrying ``transform_placement="local"`` skips it (the client
        runs the identical callable trainer-side — arm
        ``ServiceBatchSource(transform=...)`` with the same function);
        the pipeline autotuner flips that placement from measured
        profiles (``docs/guides/pipeline.md#transform-placement``).
        Distinct from the reader-level ``transform_spec`` (row/DataFrame
        granularity, fixed at reader construction), which stays where it
        is.
    :param standby: register as pooled STANDBY capacity instead of
        serving: the dispatcher keeps the worker registered and leased
        but grants it nothing until the fleet autoscaler (or an operator
        via ``Dispatcher.admit_worker``) admits it into serving — the
        zero-idle-hosts elasticity pool
        (``docs/guides/service.md#multi-tenancy-and-autoscaling``).
    :param transport: data-plane tier for this worker's streams —
        ``"auto"`` (default: negotiate shared memory with colocated
        clients, TCP otherwise), ``"tcp"`` (never negotiate), or
        ``"shm"`` (same negotiation as auto; still serves TCP to
        cross-host or non-advertising clients — shm is never required
        for correctness). ``None`` defers to the
        ``PETASTORM_TRANSPORT`` env var
        (``docs/guides/service.md#transport-tiers``).
    :param on_piece_error: poison-piece policy for streams served through
        the streaming engine (tagged static + dynamic — the exactly-once
        protocols). ``"fail"`` (default): an undecodable piece errors the
        stream, the pre-quarantine behavior. ``"quarantine"``: the piece
        is skipped, announced to the client with a ``piece_failed``
        frame, and every other piece keeps serving exactly-once; the
        client records it, reports it to the dispatcher (journaled,
        excluded from re-grant), and the epoch completes without it
        (``docs/guides/service.md#failure-model-and-recovery``). Legacy
        untagged/fcfs streams cannot express ``piece_failed`` and keep
        the fail behavior regardless.
    :param fleet_cache: wrap ``batch_cache`` in the fleet cache tier
        (:class:`~petastorm_tpu.cache_impl.fleet_tier.FleetCacheTier`):
        consistent-hash entry placement across the dispatcher's cache
        peers, remote warm serves over the framed transport, and warm
        handoff of the memory tier when this worker is drained
        (``docs/guides/caching.md#fleet-cache-tier``). Requires a
        ``batch_cache`` and a ``dispatcher_address`` (ring membership
        rides the heartbeat channel); ignored without a cache.
    """

    def __init__(self, dataset_url, dispatcher_address=None,
                 host="127.0.0.1", port=0, batch_size=64,
                 reader_factory="row", reader_kwargs=None, worker_id=None,
                 register_retries=5, register_backoff=0.2,
                 batch_delay_s=0.0, heartbeat_interval_s=5.0,
                 rpc_deadline_s=30.0, max_frame_bytes=None,
                 batch_cache=None, batch_transform=None, standby=False,
                 on_piece_error="fail", corpus="", transport=None,
                 metrics_port=None, fleet_cache=False):
        from petastorm_tpu.service.transport import resolve_mode

        if on_piece_error not in ("fail", "quarantine"):
            raise ValueError(
                "on_piece_error must be 'fail' or 'quarantine', got "
                f"{on_piece_error!r}")
        self.dataset_url = dataset_url
        # Multi-corpus fleets: workers serving different datasets under
        # ONE dispatcher register with distinct corpus names; clients
        # request per-corpus assignments (docs/guides/llm.md#mixtures).
        # "" = the default (single-dataset) corpus, bit-for-bit the
        # legacy protocol.
        self.corpus = str(corpus or "")
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self._dispatcher_address = (tuple(dispatcher_address)
                                    if dispatcher_address else None)
        self._batch_size = batch_size
        # Fleet cache tier (docs/guides/caching.md#fleet-cache-tier):
        # wraps the local cache in consistent-hash placement + remote
        # warm serves + drain handoff. The tier is a drop-in for the
        # BatchCache everywhere below (engines, diagnostics, cleanup);
        # ring membership follows the dispatcher's heartbeat-published
        # peer list.
        self._fleet_tier = None
        if fleet_cache and batch_cache is not None:
            from petastorm_tpu.cache_impl.fleet_tier import FleetCacheTier

            self._fleet_tier = batch_cache = FleetCacheTier(
                batch_cache, self.worker_id)
        self._batch_cache = batch_cache
        # Lifecycle state as the dispatcher last published it over the
        # heartbeat channel — the serving→draining edge triggers the warm
        # handoff exactly once per drain.
        self._fleet_state = None
        self._handoff_thread = None
        # The placement-flippable collated-batch transform
        # (docs/guides/pipeline.md#transform-placement): applied to every
        # batch before serialization UNLESS the stream request carries
        # transform_placement="local" (the client then runs the identical
        # callable trainer-side). Cache entries are keyed by whether the
        # transform was applied, so a placement flip re-fills instead of
        # serving bytes from the other placement.
        self._batch_transform = batch_transform
        # The cache fingerprint's factory tag: the three reader families
        # collate codec columns differently, so entries must not cross them.
        self._factory_name = (reader_factory if isinstance(reader_factory,
                                                           str)
                              else getattr(reader_factory, "__qualname__",
                                           repr(reader_factory)))
        self._factory = _resolve_factory(reader_factory)
        self._reader_kwargs = dict(reader_kwargs or {})
        # piece_indices/num_epochs/shuffle_row_groups belong to the stream
        # protocol; rowgroup_selector and cur_shard/shard_count/shard_seed
        # would change (selector) or silently re-shard (sharding) the piece
        # universe the dispatcher's plan is denominated in — sample loss or
        # out-of-range splits. Split planning is the dispatcher's job.
        for owned in ("piece_indices", "num_epochs", "shuffle_row_groups",
                      "rowgroup_selector", "cur_shard", "shard_count",
                      "shard_seed"):
            if owned in self._reader_kwargs:
                raise ValueError(
                    f"reader_kwargs[{owned!r}] is owned by the service's "
                    f"split protocol (the dispatcher plans row-group "
                    f"assignment), not worker construction")
        self._register_retries = register_retries
        self._register_backoff = register_backoff
        self._batch_delay_s = float(batch_delay_s)
        self._heartbeat_interval_s = heartbeat_interval_s
        self._rpc_deadline_s = rpc_deadline_s
        self._max_frame_bytes = max_frame_bytes
        self.num_pieces = None
        self._piece_signatures = None  # set by start()/_count_pieces
        self._lock = threading.Lock()
        self._active = {}            # stream key -> {"reader", "flow"}
        self._completed = {}         # stream key -> final diagnostics dict
        # Exact per-epoch cache attribution: the stream request carries the
        # client's epoch, so hits/misses are bucketed by the epoch that
        # caused them (consumer-side boundary sampling would smear
        # prefetched lookups into the previous epoch). Bounded dict.
        self._cache_epochs = {}      # epoch -> {"hits": n, "misses": n}
        # Per-JOB attribution (multi-tenant fleets): rows/batches served
        # and cache lookups bucketed by the stream request's job_id — how
        # shared-cache economics ("3 jobs decoded this once") and per-job
        # delivery fairness are measured. Bounded: a long-lived worker in
        # a fleet serving many short-lived jobs evicts the
        # oldest-tracked job (and its labeled metric series) beyond
        # _JOBS_TRACKED_KEPT, like the per-epoch cache buckets.
        self._jobs_served = {}       # job -> {"rows": n, "batches": n}
        self._cache_jobs = {}        # job -> {"hits": n, "misses": n}
        self._standby = bool(standby)
        self._on_piece_error = on_piece_error
        # Transport tier (docs/guides/service.md#transport-tiers): the
        # negotiation runs per stream; this is the worker's policy knob.
        self._transport_mode = resolve_mode(transport)
        self._frame_pool = None  # armed in start() when shm is possible
        self._transport_streams = {"tcp": 0, "shm": 0}
        self._log = logger.bind(worker_id=self.worker_id)
        # Interned registry children (telemetry.metrics): typed, scrapeable
        # counters behind the legacy diagnostics snapshots.
        self._m_batches = WORKER_BATCHES_SENT.labels(self.worker_id)
        self._m_rows = WORKER_ROWS_SENT.labels(self.worker_id)
        self._m_credit_wait = WORKER_CREDIT_WAIT.labels(self.worker_id)
        self._m_active = WORKER_ACTIVE_STREAMS.labels(self.worker_id)
        self._m_decode = WORKER_DECODE_SECONDS.labels(self.worker_id)
        self._m_handoff = WORKER_HANDOFF_SECONDS.labels(self.worker_id)
        self._m_readers = WORKER_READERS_CONSTRUCTED.labels(self.worker_id)
        self._m_transform = WORKER_TRANSFORM_SECONDS.labels(self.worker_id)
        # row_vs_columnar accounting: batches served through the columnar
        # decode path vs batches a columnar request fell back to the row
        # path for (docs/guides/service.md#columnar-hot-path). Interned
        # here — the send path must not pay a labels() lookup per batch.
        self._m_columnar = {
            "columnar": COLUMNAR_BATCHES.labels(self.worker_id, "columnar"),
            "row_fallback": COLUMNAR_BATCHES.labels(self.worker_id,
                                                    "row_fallback"),
        }
        # Scrape-endpoint advertisement (satellite: --metrics-port 0 binds
        # ephemerally; the CLI hands the CHOSEN port here before start()
        # so registration carries it and `status` can surface it).
        self.metrics_port = (int(metrics_port)
                             if metrics_port is not None else None)
        # Fleet-clock alignment: NTP-style offset samples taken around
        # each heartbeat RPC (docs/guides/diagnostics.md#clock-alignment),
        # shipped with pushed trace rings so the dispatcher merges spans
        # onto one timeline.
        self._clock = OffsetEstimator()
        # True while the dispatcher's heartbeat replies say fleet tracing
        # is armed — this worker's collector records and its ring is
        # shipped-and-cleared (push or live scoop). Local-only arming
        # (an in-process scenario exporting its own trace) leaves this
        # False and the ring is then read without clearing.
        self._trace_armed_remote = False
        self._heartbeat_thread = None
        self._heartbeat_stop = threading.Event()
        self._heartbeat_paused = threading.Event()  # test hook: hung worker
        self._server = FramedServer(self._serve_connection, host=host,
                                    port=port,
                                    name=f"service-worker-{self.worker_id}")

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self.num_pieces = self._count_pieces()
        if self._transport_mode != "tcp" and self._batch_cache is not None:
            # Shared frame pool: cache entries materialize INTO it so a
            # warm piece's frames travel as (offset, len) references —
            # the zero-copy mapped-serve path. Armed before any fill so
            # cold epoch 1 already lands entries pool-side. Setup
            # failure (tmpfs pressure) is a degradation, not an error:
            # shm streams then serve inline (copied) frames.
            from petastorm_tpu.service.shm_ring import (
                FramePool,
                ShmSetupError,
            )

            try:
                self._frame_pool = FramePool()
            except ShmSetupError as exc:
                self._log.warning(
                    "shm frame pool setup failed — warm serves will copy "
                    "instead of map: %s", exc)
            else:
                self._batch_cache.set_frame_allocator(
                    self._frame_pool.allocate)
        self._server.start()
        if self._dispatcher_address is not None:
            self._register()
            if self._heartbeat_interval_s is not None:
                self._heartbeat_thread = threading.Thread(
                    target=self._heartbeat_loop, daemon=True,
                    name=f"service-worker-{self.worker_id}-heartbeat")
                self._heartbeat_thread.start()
        return self

    @property
    def address(self):
        return self._server.address

    def stop(self, drain_timeout_s=5.0):
        """Graceful teardown, in dependency order: stop accepting and close
        the listener + open connections FIRST (stream threads blocked in
        ``recv``/``send`` exit on the closed socket instead of raising into
        a half-torn worker), then drain in-flight stream threads with a
        bounded join, and only then stop any reader a straggler thread left
        behind — a stop during an active stream can't leak a thread or
        race reader teardown against a live send loop. The drain also
        releases every cache this worker owns: a straggler reader's
        row-group cache (``Reader.stop()`` cleans its own) and the
        decoded-batch cache's tiers — a restarted worker must not
        accumulate temp directories or spill files (a caller-provided
        disk-tier directory keeps its files: that persistence is the
        restart-warmth contract; only worker-private temp state goes)."""
        self._server.stopped.set()
        self._heartbeat_stop.set()
        self._server.stop()
        stragglers = self._server.join(timeout=drain_timeout_s)
        if stragglers:
            self._log.warning(
                "%d stream thread(s) still alive after the %.1fs stop "
                "drain — stopping their readers under them",
                len(stragglers), drain_timeout_s)
        with self._lock:
            readers = [entry["reader"] for entry in self._active.values()
                       if entry["reader"] is not None]
        for reader in readers:
            try:
                reader.stop()  # also cleans the reader's row-group cache
            except Exception:
                self._log.warning("straggler stream reader stop failed",
                                  exc_info=True)
        if self._batch_cache is not None:
            try:
                self._batch_cache.cleanup()
            except Exception:
                self._log.warning("batch cache cleanup failed",
                                  exc_info=True)
        if self._frame_pool is not None:
            # After cache cleanup: entries holding pool-backed buffers
            # must be dropped before the pool's mapping can unmap.
            self._batch_cache.set_frame_allocator(None)
            self._frame_pool.close()
            self._frame_pool = None
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=drain_timeout_s)
        if self._handoff_thread is not None:
            # The tier's cleanup (above) already closed what the handoff
            # pushes through; a straggling handoff thread ends on its
            # next failed RPC — the join is a bounded courtesy.
            self._handoff_thread.join(timeout=drain_timeout_s)
        if self._trace_armed_remote:
            # Balance the beacon's acquire — an in-process worker must
            # not leave the shared collector armed past its lifetime.
            self._trace_armed_remote = False
            tracing.COLLECTOR.release()

    def kill(self):
        """Abrupt failure injection (tests): drop every open connection
        without sending ``end``, then tear down — clients see a mid-stream
        :class:`ConnectionClosedError`, exactly like a worker host dying."""
        self._server.stopped.set()
        self._heartbeat_stop.set()
        self._server.close_connections()
        self.stop()

    def drop_connections(self):
        """Drop every open connection without stopping the server (fault
        injection: a network blip — clients reconnect and re-stream)."""
        self._server.close_connections()

    def pause_heartbeats(self):
        """Test hook: stop renewing the dispatcher lease while the server
        keeps running — simulates a hung-but-connected worker so lease
        expiry (not connection failure) is what evicts it."""
        self._heartbeat_paused.set()

    def resume_heartbeats(self):
        self._heartbeat_paused.clear()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()

    # -- registration / planning ------------------------------------------

    def _count_pieces(self):
        """Enumerate the dataset's row-group pieces with the same planning
        config every stream reader will use — the count the dispatcher's
        split plan is denominated in. The enumeration's (path, row_group)
        identities are kept as the cache key's content signature: a
        re-materialized dataset (new part-file names under the same url)
        must MISS the persistent disk tier, not serve yesterday's
        batches."""
        from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
        from petastorm_tpu.reader.reader import enumerate_row_group_pieces

        fs, path = get_filesystem_and_path_or_paths(
            self.dataset_url,
            storage_options=self._reader_kwargs.get("storage_options"),
            filesystem=self._reader_kwargs.get("filesystem"))
        pieces = enumerate_row_group_pieces(
            fs, path, self._reader_kwargs.get("filters"))
        self._piece_signatures = [(piece.path, piece.row_group)
                                  for piece in pieces]
        return len(pieces)

    def _register(self, re_register=False, retries=None):
        host, port = self.address
        payload = {
            "type": "register_worker",
            "worker_id": self.worker_id,
            "host": host,
            "port": port,
            "num_pieces": self.num_pieces,
            "re_register": re_register,
            "standby": self._standby,
            "corpus": self.corpus,
            # Fleet cache tier advertisement: journaled with the
            # registration, so the dispatcher's published cache-peer list
            # (and its replay) never guesses at who serves cache RPCs.
            "cache_fleet": self._fleet_tier is not None,
        }
        if self.metrics_port is not None:
            payload["metrics_port"] = self.metrics_port
        reply = self._control_rpc(
            payload, description=f"worker {self.worker_id} registration",
            retries=retries)
        if reply.get("type") != "ok":
            raise RuntimeError(
                f"dispatcher rejected registration: "
                f"{reply.get('error', reply)}")
        FLIGHT.set_context(role="worker", worker_id=self.worker_id,
                           fencing_epoch=reply.get("fencing_epoch"))
        FLIGHT.note("worker.registered", re_register=re_register,
                    state=reply.get("state"))
        if self._fleet_tier is not None \
                and reply.get("cache_peers") is not None:
            # Register-time ring seed; heartbeats keep it converged.
            try:
                self._fleet_tier.update_peers(reply["cache_peers"])
            except (ValueError, TypeError):
                self._log.warning("malformed cache_peers in registration "
                                  "reply — starting with an empty ring",
                                  exc_info=True)
        return reply

    def _control_rpc(self, header, description, retries=None):
        """One control request/reply against the dispatcher under the
        shared retry policy: bounded attempts, exponential backoff with
        jitter, and a total ``rpc_deadline_s`` budget. Heartbeat ticks
        pass ``retries=0`` — their loop IS the retry, and a stop() must
        not wait out a backoff budget against a dead dispatcher."""
        from petastorm_tpu.reader_impl.framed_socket import FramedConnection
        from petastorm_tpu.utils import retry_with_backoff

        # Propagated trace context: the dispatcher's RPC span records who
        # called, joining this worker's data-plane spans in the fleet
        # trace (docs/guides/diagnostics.md#fleet-tracing).
        header.setdefault("trace", {"peer": self.worker_id})

        def attempt():
            with FramedConnection.connect(self._dispatcher_address,
                                          timeout=10.0) as conn:
                reply, _ = conn.request(header)
            if reply.get("type") == "error" and reply.get("retryable"):
                # Degraded (read-only) dispatcher: transient by contract —
                # the next request's recovery snapshot may heal it, so a
                # worker registering during an ENOSPC window backs off
                # and retries instead of dying on a fatal rejection (the
                # client side does the same via DegradedDispatcherError).
                raise OSError(reply.get("error", "dispatcher degraded"))
            return reply

        return retry_with_backoff(
            attempt,
            retries=self._register_retries if retries is None else retries,
            base_delay=self._register_backoff,
            # ProtocolError = a desynced (torn) control reply: the conn
            # is gone either way, a fresh dial retries cleanly.
            retry_on=(OSError, ProtocolError),
            deadline_s=self._rpc_deadline_s,
            description=description)

    def _heartbeat_loop(self):
        """Renew the dispatcher lease every ``heartbeat_interval_s``; an
        ``unknown_worker`` reply (the dispatcher restarted without this
        worker's state, or evicted it) triggers re-registration under the
        same ``worker_id``. A dispatcher outage is just a missed tick —
        the loop keeps trying until the dispatcher returns."""
        while not self._heartbeat_stop.wait(self._heartbeat_interval_s):
            if self._heartbeat_paused.is_set():
                continue
            fp = failpoints.ACTIVE
            if fp is not None and fp.check("worker.heartbeat") == "drop":
                continue  # injected lost tick: the lease absorbs it (or
                #   expires and the re-registration path heals)
            try:
                # retries=0 → exactly one dial, so [t0, t1] brackets one
                # request/reply round trip: the NTP-style clock sample
                # (offset = dispatcher clock − RTT midpoint, error ≤
                # RTT/2) that aligns this worker's spans in the merged
                # fleet trace.
                t0 = time.perf_counter()
                reply = self._control_rpc(
                    {"type": "worker_heartbeat", "worker_id": self.worker_id,
                     # Overload signal feed: cumulative seconds the serve
                     # loops sat blocked on client flow control — the
                     # dispatcher's brownout evaluator diffs it per
                     # window (service/resilience.py).
                     "credit_wait_s": round(self._m_credit_wait.value, 4)},
                    description=f"worker {self.worker_id} heartbeat",
                    retries=0)
                t1 = time.perf_counter()
            except (OSError, ProtocolError):
                continue  # dispatcher down/desynced: retry next tick
            remote_us = reply.get("dispatcher_time_us")
            if remote_us is not None:
                self._clock.add(
                    tracing.COLLECTOR.ts_us((t0 + t1) / 2.0),
                    float(remote_us), (t1 - t0) * 1e6)
            self._sync_trace_arming(bool(reply.get("trace")))
            if self._fleet_tier is not None:
                peers = reply.get("cache_peers")
                if peers is not None:
                    try:
                        self._fleet_tier.update_peers(peers)
                    except (ValueError, TypeError):
                        self._log.warning(
                            "malformed cache_peers in heartbeat reply — "
                            "keeping the previous ring", exc_info=True)
                self._sync_fleet_state(reply.get("worker_state"))
            if "brownout_level" in reply:
                from petastorm_tpu.service.resilience import \
                    note_brownout_level

                note_brownout_level(reply["brownout_level"])
            if reply.get("type") == "unknown_worker" \
                    and not self._heartbeat_stop.is_set():
                self._log.warning(
                    "dispatcher no longer knows this worker — "
                    "re-registering",
                    fencing_epoch=reply.get("fencing_epoch"))
                try:
                    # retries=0 keeps the tick bounded by one dial: the
                    # loop itself is the retry, and stop() must not wait
                    # out a 30s backoff budget against a dead dispatcher.
                    self._register(re_register=True, retries=0)
                except (OSError, RuntimeError, ProtocolError):
                    continue  # registration retried on the next tick

    # -- fleet cache tier --------------------------------------------------

    def _sync_fleet_state(self, state):
        """Follow this worker's dispatcher-published lifecycle state. The
        serving→draining edge launches the warm handoff exactly once per
        drain: the memory tier ships to the peers inheriting this
        worker's keyspace BEFORE the drain completes, so the fleet
        re-decodes nothing (``docs/guides/caching.md#fleet-cache-tier``).
        Run on its own named thread — a handoff is entry-count × RPC
        long, and the heartbeat loop must keep renewing the lease that
        keeps this worker alive while it runs."""
        if state is None:
            return
        previous, self._fleet_state = self._fleet_state, state
        if (state == "draining" and previous not in (None, "draining")
                and self._fleet_tier is not None
                and (self._handoff_thread is None
                     or not self._handoff_thread.is_alive())):
            self._handoff_thread = threading.Thread(
                target=self._run_handoff, daemon=True,
                name=f"cache-peer-handoff-{self.worker_id}")
            self._handoff_thread.start()

    def _run_handoff(self):
        try:
            summary = self._fleet_tier.handoff()
        except Exception:
            self._log.warning("warm handoff failed — the inheriting "
                              "peers will cold-fill", exc_info=True)
            return
        self._log.info(
            "warm handoff shipped %d entries (%d bytes) to %d peer(s)"
            "%s", summary["entries"], summary["bytes"],
            len(summary["peers"]), " [torn]" if summary["torn"] else "")
        FLIGHT.note("worker.cache_handoff", **{
            k: summary[k] for k in ("entries", "bytes", "errors", "torn")})
        if self._dispatcher_address is None:
            return
        try:
            # Journaled like steals: the dispatcher appends a
            # cache_handoff WAL record, so the drain's warmth movement
            # replays with the rest of the fleet history.
            self._control_rpc(
                {"type": "cache_handoff", "worker_id": self.worker_id,
                 "entries": summary["entries"], "bytes": summary["bytes"],
                 "peers": summary["peers"], "errors": summary["errors"],
                 "torn": summary["torn"]},
                description=f"worker {self.worker_id} handoff report",
                retries=0)
        except (OSError, ProtocolError):
            self._log.warning("handoff report did not reach the "
                              "dispatcher (handoff itself completed)",
                              exc_info=True)

    # -- fleet tracing -----------------------------------------------------

    def _sync_trace_arming(self, armed):
        """Follow the dispatcher's heartbeat-borne tracing beacon: arm the
        local span collector when the fleet arms, push the accumulated
        ring (ship-and-clear, so nothing is ever sent twice) with the
        current clock offset each armed tick, release on disarm.
        Shipping is best-effort — a failed push loses that tick's spans,
        which the assembled trace's per-peer ``dropped`` does NOT count
        (the dispatcher never saw them); heartbeat cadence keeps the
        exposure to one tick."""
        if armed and not self._trace_armed_remote:
            self._trace_armed_remote = True
            tracing.COLLECTOR.acquire()
            FLIGHT.note("worker.trace_armed")
            self._log.info("fleet tracing armed by dispatcher beacon")
        elif not armed and self._trace_armed_remote:
            self._trace_armed_remote = False
            tracing.COLLECTOR.release()
            self._log.info("fleet tracing disarmed")
            return
        if not self._trace_armed_remote:
            return
        events, dropped = tracing.COLLECTOR.ship()
        if not events and not dropped:
            return
        try:
            self._control_rpc(
                {"type": "trace_push", "peer": self.worker_id,
                 "events": events, "dropped": dropped,
                 "offset_us": self._clock.offset_us(),
                 "min_rtt_us": self._clock.min_rtt_us()},
                description=f"worker {self.worker_id} trace push",
                retries=0)
        except (OSError, ProtocolError):
            pass  # best-effort: next tick ships the new ring

    def _trace_snapshot(self):
        """One live pull of this worker's span ring, for the dispatcher's
        ``trace collect`` scoop. Remote-armed: ship-and-clear (a later
        heartbeat push must not re-send these events). Only locally
        armed (a scenario exporting its own trace): read WITHOUT
        clearing — the scoop must not steal the local exporter's ring."""
        if self._trace_armed_remote:
            events, dropped = tracing.COLLECTOR.ship()
        else:
            events = tracing.COLLECTOR.events()
            dropped = tracing.COLLECTOR.dropped
        return {"type": "trace", "worker_id": self.worker_id,
                "events": events, "dropped": dropped,
                "offset_us": self._clock.offset_us(),
                "min_rtt_us": self._clock.min_rtt_us()}

    # -- serving -----------------------------------------------------------

    def _serve_connection(self, sock):
        reader = FramedReader(sock,  # buffered, per-connection
                              max_frame_bytes=self._max_frame_bytes)
        while not self._server.stopped.is_set():
            header, payload = reader.recv()
            kind = header.get("type")
            if kind == "stream":
                self._stream(sock, header, conn_reader=reader)
            elif kind == "credit":
                # A replenishment raced the stream's `end` (the client sends
                # credits as it consumes, and the tail of those can land
                # after the stream finished) — stale, not an error.
                pass
            elif kind == "diagnostics":
                send_framed(sock, {"type": "diagnostics",
                                   "worker_id": self.worker_id},
                            self.diagnostics_snapshot())
            elif kind == "cache_fetch":
                self._handle_cache_fetch(sock, header)
            elif kind == "cache_put":
                self._handle_cache_put(sock, header, payload)
            elif kind == "trace":
                send_framed(sock, self._trace_snapshot())
            elif kind == "ping":
                send_framed(sock, {"type": "pong",
                                   "worker_id": self.worker_id})
            else:
                send_framed(sock, {"type": "error",
                                   "error": f"unknown request {kind!r}"})

    def _handle_cache_fetch(self, sock, header):
        """A peer asking for a warm entry: reply with its meta + the ONE
        contiguous frame buffer (the cached bytes are the wire bytes), or
        a miss. Serving rides :func:`send_framed`'s scatter-gather — no
        decode, no re-serialization."""
        tier = self._fleet_tier
        if tier is None:
            send_framed(sock, {"type": "error",
                               "error": "fleet cache tier not armed"})
            return
        reply, payload = tier.serve_fetch(str(header.get("key")))
        send_framed(sock, reply, payload)

    def _handle_cache_put(self, sock, header, payload):
        """A peer shipping an entry here (write-through placement or a
        draining peer's warm handoff). Adoption validates meta against
        payload length — a torn transfer is refused, never published."""
        tier = self._fleet_tier
        if tier is None:
            send_framed(sock, {"type": "error",
                               "error": "fleet cache tier not armed"})
            return
        try:
            entry = tier.adopt(
                str(header.get("key")), header.get("meta") or [],
                (payload or {}).get("buf", b""),
                origin=str(header.get("origin", "placement")))
        except (ValueError, KeyError, TypeError) as exc:
            send_framed(sock, {"type": "error",
                               "error": f"cache_put refused: {exc}"})
            return
        send_framed(sock, {"type": "ok", "key": header.get("key"),
                           "rows": entry.rows})

    def _stream(self, sock, header, conn_reader):
        """Serve one ``stream`` request: batches of the named pieces, then
        ``end``. A reader/collation error becomes an ``error`` message (the
        client re-raises it — a bad plan is not a transient failure).

        Flow control: a ``credits`` field in the request bounds the window
        of un-acknowledged batches. Each ``batch`` send spends one credit;
        the client replenishes with ``credit`` messages as it consumes. Out
        of credits, the worker blocks reading the replenishment stream —
        per-worker in-flight batches stay <= the window instead of growing
        with the socket buffer (unbounded push) or collapsing to
        request/response lockstep. Without the field the stream is
        unbounded (pre-credit clients).

        Telemetry: each batch gets an id minted here
        (``<worker_id>:<stream>:<seq>``) and carried in the ``batch``
        header — the cross-process key batch-lifecycle tracing correlates
        spans on (decode/send worker-side; recv/queue/dispatch
        client-side). Decode and send times land in the registry whether or
        not tracing is armed.

        Caching: with a ``batch_cache`` armed, pieces are looked up (and
        filled) individually through ONE streaming piece engine per stream
        (:meth:`_stream_pieces_engine` — a cold fill costs one reader
        construction per stream, not per piece); pools without per-item
        completion attribution (process) fall back to the per-piece reader
        path. The uncached static path is byte-for-byte the pre-cache
        behavior (one reader over the whole piece set, batches collated
        across pieces).

        Dynamic mode (``dynamic: true`` in the request): pieces arrive as
        ``[piece, generation]`` (or ``[piece, generation, start]``) tuples
        and the same engine serves them from a queue the client edits
        mid-stream with ``extend``/``revoke``/``finish_pieces`` control
        frames — a work-stealing rebalance costs a queue edit instead of a
        reader construction (``docs/guides/service.md#sharding-modes``).

        Tagged static mode (``tagged: true``): the engine serves the named
        pieces piece-aligned, every ``batch`` frame carrying its piece and
        absolute batch ``ordinal``, each finished piece announced with a
        ``piece_done`` frame; a ``starts`` map (piece → first ordinal to
        send, the client's delivery watermark) makes re-serves idempotent
        — this is the exactly-once static path
        (``docs/guides/service.md#delivery-semantics``). Pool types
        without per-item completion attribution fall back to the legacy
        untagged serving; the client detects the untagged batches and
        keeps at-least-once bookkeeping for that worker."""
        from petastorm_tpu.service.resilience import (
            arrival_deadline, deadline_exceeded_reply, deadline_expired)

        # Deadline propagation (service/resilience.py): a stream request
        # whose caller-shipped budget expired before we got to it (accept
        # backlog on an overloaded worker) is refused retryable before a
        # reader is built — the client's retry/takeover machinery owns
        # the budget and will re-route.
        if deadline_expired(arrival_deadline(header)):
            send_framed(sock, deadline_exceeded_reply("worker.stream"))
            return
        dynamic = bool(header.get("dynamic"))
        tagged = bool(header.get("tagged"))
        # Worker-placement sequence packing: the stream request names the
        # spec; pieces are packed pre-serialization (cache entries hold
        # packed frames; ordinals/watermarks number packed batches).
        packing = None
        if header.get("packing") is not None:
            from petastorm_tpu.service.packing_stage import PackingSpec

            packing = PackingSpec.from_dict(header["packing"])
            if not (dynamic or tagged) or not self._engine_supported():
                send_framed(sock, {
                    "type": "error",
                    "error": "stream requested packing but this serving "
                             "path cannot pack: packing runs inside the "
                             "streaming piece engine (tagged/dynamic "
                             "protocols, reader_pool_type='thread') — "
                             "use static or dynamic sharding, or pack "
                             "trainer-side (packing_placement="
                             "'trainer')"})
                return
            if self._batch_transform is not None \
                    and header.get("transform_placement") != "local":
                send_framed(sock, {
                    "type": "error",
                    "error": "stream requested packing but this worker "
                             "has a batch_transform armed remote-side: "
                             "the transform is a row-batch stage and "
                             "packing changes the batch vocabulary — "
                             "run the transform trainer-side "
                             "(transform_placement='local') or drop "
                             "--batch-transform"})
                return
        # Graph-rewrite stream attributes (docs/guides/pipeline.md
        # #graph-rewrites) — all engine-path-only (tagged/dynamic, or the
        # untagged cache-armed engine stream):
        #
        # - ``fused``: collapse collate→transform(→pack)→serialize into
        #   the decode pool task (stage fusion; downgraded with a warning
        #   when the reader family cannot fuse — bytes identical either
        #   way);
        # - ``predicate`` (wire dict) / ``projection`` (field list): the
        #   hoisted row filter and column pruning, applied BELOW decode in
        #   the stream's reader — dropped rows never decode, pruned
        #   columns are never read;
        # - ``cache_stage``: where the batch cache sits relative to the
        #   batch transform ("post-transform" default / "post-decode").
        fused = bool(header.get("fused"))
        cache_stage = header.get("cache_stage") or "post-transform"
        stream_predicate = None
        if header.get("predicate") is not None:
            from petastorm_tpu.predicates import ColumnPredicate

            try:
                stream_predicate = ColumnPredicate.from_wire(
                    header["predicate"])
            except ValueError as exc:
                send_framed(sock, {"type": "error",
                                   "error": f"bad stream predicate: {exc}"})
                return
            if self._reader_kwargs.get("predicate") is not None:
                send_framed(sock, {
                    "type": "error",
                    "error": "stream carries a predicate but this worker "
                             "was constructed with reader_kwargs["
                             "'predicate'] — one row filter per stream: "
                             "drop one of the two"})
                return
        projection = ([str(f) for f in header["projection"]]
                      if header.get("projection") else None)
        if cache_stage not in ("post-transform", "post-decode"):
            send_framed(sock, {
                "type": "error",
                "error": f"unknown cache_stage {cache_stage!r} "
                         f"(post-transform|post-decode)"})
            return
        reader_family = header.get("reader_family")
        if reader_family not in (None, "row", "columnar"):
            send_framed(sock, {
                "type": "error",
                "error": f"unknown reader_family {reader_family!r} "
                         f"(row|columnar)"})
            return
        # row_vs_columnar rewrite: resolve the requested decode family
        # against what this worker can serve. Unlike the other rewrites an
        # unservable request never errors — it degrades to the constructed
        # family (decoded bytes identical either way) and the degradation
        # is visible as path="row_fallback" in
        # petastorm_columnar_batches_total, so the planner's probe sees no
        # phantom speedup and the operator's COL% column sees the miss.
        family_swap, effective_family = self._resolve_stream_family(
            reader_family,
            engine=((dynamic or tagged or self._batch_cache is not None)
                    and self._engine_supported()))
        columnar_path = None
        if effective_family == "columnar":
            columnar_path = "columnar"
        elif reader_family == "columnar":
            columnar_path = "row_fallback"
            self._log.warning(
                "stream requested reader_family='columnar' but this "
                "serving path cannot vectorize (constructed family %r); "
                "serving the row path — decoded bytes are identical",
                self._factory_name)
        needs_engine = (fused or stream_predicate is not None
                        or projection is not None
                        or cache_stage != "post-transform")
        if needs_engine and not (
                (dynamic or tagged or self._batch_cache is not None)
                and self._engine_supported()):
            send_framed(sock, {
                "type": "error",
                "error": "stream requested a graph rewrite (fused/"
                         "predicate/projection/cache_stage) but this "
                         "serving path cannot apply it: rewrites run "
                         "inside the streaming piece engine (tagged/"
                         "dynamic protocols, reader_pool_type='thread') "
                         "— use static or dynamic sharding"})
            return
        # Placement-flippable batch transform: "local" tells this worker
        # to SKIP its configured batch_transform — the client applies the
        # identical callable trainer-side (docs/guides/pipeline.md).
        transform_local = header.get("transform_placement") == "local"
        if header.get("transform_placement") == "remote" \
                and self._batch_transform is None:
            # The client armed a transform and expects THIS side to run
            # it; silently serving untransformed batches would train on
            # wrong data with no error anywhere — refuse the stream and
            # name the misconfiguration instead.
            send_framed(sock, {
                "type": "error",
                "error": "stream requested transform_placement='remote' "
                         "but this worker has no batch_transform armed — "
                         "start it with --batch-transform module:attr "
                         "(the same callable the client's transform= "
                         "uses), or run the client with "
                         "transform_placement='local'"})
            return
        transform_fn = None
        if self._batch_transform is not None and not transform_local:
            batch_transform = self._batch_transform
            observe = self._m_transform.observe

            def transform_fn(batch):
                t0 = time.perf_counter()
                out = batch_transform(batch)
                observe(time.perf_counter() - t0)
                return out
        # Serve-time shuffle: the client forwards the dispatcher's
        # shuffle_seed so the engine can compose the per-epoch intra-piece
        # batch permutation at serve time (cached bytes stay canonical and
        # seed-invariant — docs/guides/caching.md#shuffle-compatible-serving).
        shuffle_seed = header.get("shuffle_seed")
        shuffle_seed = int(shuffle_seed) if shuffle_seed is not None else None
        starts = {int(p): int(s)
                  for p, s in (header.get("starts") or {}).items()}
        if dynamic:
            pieces = [(int(t[0]), int(t[1]),
                       int(t[2]) if len(t) > 2 else 0)
                      for t in header["pieces"]]
        else:
            pieces = [int(p) for p in header["pieces"]]
        credits = header.get("credits")
        credits = int(credits) if credits is not None else None
        # Multi-tenant attribution: the stream request's job_id buckets
        # this stream's rows and cache lookups per job ("job" rides in
        # flow, so completed-stream diagnostics carry it too).
        job = header.get("job_id")
        job = str(job) if job else None
        flow = {"credits_window": credits, "credits_left": credits,
                "batches_sent": 0, "credit_wait_s": 0.0}
        if job is not None:
            flow["job"] = job
        if columnar_path is not None:
            # Read per batch in _send_stream_batch: every batch of this
            # stream counts under one resolved path label.
            flow["columnar_path"] = columnar_path
        stream_key = f"{uuid.uuid4().hex[:8]}"
        # The stream's mutable serving state: the cached path swaps
        # per-piece readers through "reader" (None while serving from
        # cache); diagnostics snapshots read it under the lock.
        state = {"reader": None, "flow": flow}
        # "aborted" covers the early returns (worker stop mid-stream, no
        # `end` frame sent); only the `end` send flips it to "completed".
        outcome = "aborted"
        with self._lock:
            self._active[stream_key] = state
        self._m_active.inc()
        rewrites = {"fused": fused, "predicate": stream_predicate,
                    "projection": projection, "cache_stage": cache_stage,
                    "family": family_swap}
        tx = None
        early_frames = []
        try:
            # Transport negotiation (transport.py): shm when the client
            # advertised it AND shares this host AND the arena sets up —
            # every other case (including mid-negotiation failure) is the
            # TCP tier on this same request. From here down the serve
            # paths write to `tx`, never the socket; client->worker
            # control traffic (credits, dynamic edits) stays on TCP.
            from petastorm_tpu.service.transport import negotiate_worker_tx

            tx, extra_credits, early_frames = negotiate_worker_tx(
                sock, conn_reader, header, self._transport_mode,
                pool=self._frame_pool)
            if credits is not None and extra_credits:
                flow["credits_left"] += extra_credits
            with self._lock:
                self._transport_streams[tx.transport] += 1
            if dynamic:
                rows_sent = self._stream_dynamic(
                    tx, conn_reader, state, pieces, flow, credits,
                    stream_key, epoch=header.get("epoch"),
                    shuffle_seed=shuffle_seed, transform_fn=transform_fn,
                    job=job, packing=packing, rewrites=rewrites,
                    early_frames=early_frames)
            elif tagged and self._engine_supported():
                rows_sent = self._stream_pieces_tagged(
                    tx, conn_reader, state, pieces, flow, credits,
                    stream_key, starts, epoch=header.get("epoch"),
                    shuffle_seed=shuffle_seed, transform_fn=transform_fn,
                    job=job, packing=packing, rewrites=rewrites)
            elif self._batch_cache is not None and self._engine_supported():
                rows_sent = self._stream_pieces_engine(
                    tx, conn_reader, state, pieces, flow, credits,
                    stream_key, epoch=header.get("epoch"),
                    shuffle_seed=shuffle_seed, transform_fn=transform_fn,
                    job=job, rewrites=rewrites)
            else:
                if shuffle_seed is not None:
                    # This serving path cannot compose the serve-time
                    # batch permutation: say why instead of silently
                    # serving canonical order every epoch. Two distinct
                    # causes land here — diagnose the right one.
                    if not self._engine_supported():
                        reason = (
                            f"reader pool "
                            f"{self._reader_kwargs.get('reader_pool_type')!r}"
                            f" has no per-item completion attribution — "
                            f"use reader_pool_type='thread'")
                    else:
                        reason = ("the stream is untagged and no batch "
                                  "cache is armed, so it serves through "
                                  "the plain whole-set reader, not the "
                                  "streaming engine")
                    self._log.warning(
                        "stream requested shuffle_seed=%s but intra-piece "
                        "batches will serve in canonical order: %s",
                        shuffle_seed, reason)
                if self._batch_cache is not None:
                    rows_sent = self._stream_pieces_cached(
                        tx, conn_reader, state, pieces, flow, credits,
                        stream_key, epoch=header.get("epoch"),
                        transform_fn=transform_fn, job=job)
                else:
                    rows_sent = self._stream_pieces_direct(
                        tx, conn_reader, state, pieces, flow, credits,
                        stream_key, transform_fn=transform_fn, job=job)
            if rows_sent is None:
                return  # worker stopped mid-stream
            tx.send({"type": "end", "rows": rows_sent,
                     "pieces": pieces})
            outcome = "completed"
        except (ConnectionClosedError, OSError):
            outcome = "disconnected"
            raise  # client hung up — nothing to tell it
        except ProtocolError:
            # The client side of this socket desynced (torn control
            # frame): framing is lost, so the connection is dead — treat
            # it like a hangup (the client's broken-stream recovery
            # re-serves pending pieces at their watermarks), NOT like a
            # stream error (which would raise into the training loop).
            outcome = "disconnected"
            raise
        except Exception as exc:
            outcome = "error"
            self._log.exception("stream failed", stream=stream_key,
                                pieces=pieces)
            # Through tx: once an shm offer went out, the client reads
            # the ring — an error frame on the socket would never arrive.
            if tx is not None:
                tx.send({"type": "error", "error": str(exc)})
            else:
                send_framed(sock, {"type": "error", "error": str(exc)})
        finally:
            if tx is not None:
                # The ring arena is per-STREAM: detach (the consumer
                # drains every committed record first, so a clean `end`
                # is never lost) and unmap. TCP tx close is a no-op.
                try:
                    tx.close()
                except Exception:
                    self._log.warning("stream transport close failed",
                                      exc_info=True)
            with self._lock:
                self._active.pop(stream_key, None)
                reader = state["reader"]
                snapshot = (dict(reader.diagnostics)
                            if reader is not None else {})
                self._completed[stream_key] = dict(snapshot, **flow)
                while len(self._completed) > _COMPLETED_SNAPSHOTS_KEPT:
                    self._completed.pop(next(iter(self._completed)))
                if job is not None and flow.get("job_batches"):
                    # LRU fold (pop + reinsert = touch): only jobs idle
                    # longest age out of the bounded attribution — an
                    # actively-streaming tenant must never have its
                    # fairness counters silently reset by newer jobs.
                    counts = self._jobs_served.pop(
                        job, {"rows": 0, "batches": 0})
                    counts["rows"] += flow.get("job_rows", 0)
                    counts["batches"] += flow["job_batches"]
                    self._jobs_served[job] = counts
                    while len(self._jobs_served) > self._JOBS_TRACKED_KEPT:
                        old_job = next(iter(self._jobs_served))
                        self._jobs_served.pop(old_job)
                        FLEET_JOB_ROWS.remove(old_job)
            self._m_active.dec()
            WORKER_STREAMS.labels(self.worker_id, outcome).inc()
            if reader is not None:
                reader.stop()
                reader.join()

    def _stream_pieces_direct(self, tx, conn_reader, state, pieces, flow,
                              credits, stream_key, transform_fn=None,
                              job=None):
        """Uncached serving: one reader over the whole piece set, batches
        collated across piece boundaries. Returns rows sent, or ``None``
        when the worker stopped mid-stream."""
        from petastorm_tpu.jax_utils.batcher import batch_iterator

        collector = tracing.COLLECTOR
        # cur_shard=0/shard_count=1 pins sharding OFF: the factory
        # defaults would silently fill jax.process_index()/count() on a
        # host with multi-process JAX initialized, dropping (N-1)/N of
        # the assigned pieces AFTER piece_indices selection — the
        # dispatcher's plan is the only sharding a worker applies.
        reader = self._make_stream_reader(pieces)
        with self._lock:
            state["reader"] = reader
        rows_sent = 0
        batches = iter(batch_iterator(reader, self._batch_size,
                                      last_batch="keep"))
        while True:
            # Manual iteration so the pull itself (read + collate) is
            # a measured decode span, attributable per batch id.
            t_decode = time.perf_counter()
            batch = next(batches, None)
            t_decoded = time.perf_counter()
            if batch is None:
                return rows_sent
            self._m_decode.observe(t_decoded - t_decode)
            bid = f"{self.worker_id}:{stream_key}:{flow['batches_sent']}"
            if collector.enabled:
                collector.record_span("worker.decode", t_decode,
                                      t_decoded, bid=bid)
            if transform_fn is not None:
                batch = transform_fn(batch)
            n = self._batch_rows(batch)
            fmt, frames = encode_payload(batch)
            if not self._send_stream_batch(tx, conn_reader, flow, credits,
                                           bid, n, fmt, frames, collector):
                return None
            rows_sent += n

    def _stream_pieces_cached(self, tx, conn_reader, state, pieces, flow,
                              credits, stream_key, epoch=None,
                              transform_fn=None, job=None):
        """Cache-armed serving, piece by piece: a warm piece's batches are
        scatter-gathered straight out of cache memory (zero decode, zero
        re-serialization — ``send_framed_frames``); a cold piece is decoded
        through a per-piece reader, each batch serialized ONCE and both
        sent and written through to the cache. Per-piece keying means a
        re-partitioned plan (worker takeover, fleet resize) still hits on
        every piece both plans share, and the disk tier re-serves warm
        pieces across worker restarts. Returns rows sent, or ``None`` when
        the worker stopped mid-stream (the partially-filled piece entry is
        discarded, never published)."""
        from petastorm_tpu.jax_utils.batcher import batch_iterator

        cache = self._batch_cache
        collector = tracing.COLLECTOR
        rows_sent = 0
        for piece in pieces:
            key = self._piece_cache_key(
                piece, transformed=transform_fn is not None)
            entry = cache.get(key)
            self._note_cache_lookup(epoch, hit=entry is not None, job=job)
            if entry is not None:
                for cached in entry.batches():
                    bid = (f"{self.worker_id}:{stream_key}:"
                           f"{flow['batches_sent']}")
                    if not self._send_stream_batch(
                            tx, conn_reader, flow, credits, bid,
                            cached.rows, cached.fmt, cached.frames,
                            collector):
                        return None
                    rows_sent += cached.rows
                continue
            reader = self._make_stream_reader([piece])
            with self._lock:
                state["reader"] = reader
            builder = cache.begin_fill(key)
            try:
                batches = iter(batch_iterator(reader, self._batch_size,
                                              last_batch="keep"))
                while True:
                    t_decode = time.perf_counter()
                    batch = next(batches, None)
                    t_decoded = time.perf_counter()
                    if batch is None:
                        break
                    self._m_decode.observe(t_decoded - t_decode)
                    bid = (f"{self.worker_id}:{stream_key}:"
                           f"{flow['batches_sent']}")
                    if collector.enabled:
                        collector.record_span("worker.decode", t_decode,
                                              t_decoded, bid=bid)
                    if transform_fn is not None:
                        batch = transform_fn(batch)
                    n, fmt, frames = builder.add_batch(batch)
                    if not self._send_stream_batch(
                            tx, conn_reader, flow, credits, bid, n, fmt,
                            frames, collector):
                        return None
                    rows_sent += n
                builder.commit()
            finally:
                with self._lock:
                    state["reader"] = None
                reader.stop()
                reader.join()
        return rows_sent

    # -- streaming piece engine paths --------------------------------------

    def _engine_supported(self):
        """The streaming engine needs per-item completion attribution,
        which only the thread and dummy reader pools provide."""
        return self._reader_kwargs.get(
            "reader_pool_type", "thread") in ("thread", "dummy")

    def _resolve_stream_family(self, requested, engine):
        """Resolve a stream's requested decode family (the
        ``row_vs_columnar`` rewrite) against what this worker can serve.

        Returns ``(swap, effective)``: ``swap`` is the factory name the
        engine's per-piece readers must be built with (``None`` when the
        constructed factory already satisfies the request, or the request
        cannot be honored), ``effective`` the family that will actually
        decode this stream. Fallback rules
        (``docs/guides/service.md#columnar-hot-path``): the swap needs the
        streaming engine (readers are built per stream there — the
        direct/cached legacy paths reuse the constructed factory); a
        "batch"-family worker has no unischema decode contract to
        vectorize; ngram readers and row-granularity ``transform_spec``
        callables are per-row by definition, so a columnar request
        degrades to the row path for them.
        """
        constructed = self._factory_name
        if requested is None or requested == constructed:
            return None, constructed
        if not engine or constructed not in ("row", "columnar"):
            return None, constructed
        if requested == "columnar" and (
                self._reader_kwargs.get("ngram") is not None
                or self._reader_kwargs.get("transform_spec") is not None):
            return None, constructed
        return requested, requested

    def _make_engine(self, epoch, shuffle_seed=None, transform_fn=None,
                     job=None, allow_quarantine=False, packing=None,
                     rewrites=None):
        """ONE dynamic-ventilation reader + engine for a whole stream —
        the piece queue is fed (and edited) afterwards, so a stream (or a
        cold cache fill) over N pieces costs one reader construction, one
        dataset enumeration, one pool spinup, instead of N. The reader is
        built lazily on the first cache MISS: a fully-warm stream
        constructs none at all (``readers_constructed_total`` stays flat).

        ``shuffle_seed`` arms serve-time intra-piece batch shuffling: the
        permutation derives ONLY from ``seedtree.batch_permutation(seed,
        epoch, piece, n)`` — pure, so any re-serve (takeover, retry,
        kill-resume) replays the same permuted order against the same
        watermarks, warm or cold."""
        from petastorm_tpu.service.piece_engine import StreamingPieceEngine
        from petastorm_tpu.service.seedtree import batch_permutation

        rewrites = dict(rewrites or {})
        stream_predicate = rewrites.get("predicate")
        projection = rewrites.get("projection")
        fused = bool(rewrites.get("fused"))
        cache_stage = rewrites.get("cache_stage") or "post-transform"
        # row_vs_columnar: a resolved family swap rebuilds this stream's
        # per-piece readers through the other factory (vectorized
        # per-column decode vs per-row) — decoded bytes are identical, but
        # cache entries are keyed by the EFFECTIVE family below so the two
        # families never serve each other's frames.
        family = rewrites.get("family")
        factory = _resolve_factory(family) if family else self._factory
        family_name = family or self._factory_name
        reader_kwargs = dict(self._reader_kwargs)
        if stream_predicate is not None:
            # The hoisted row filter: applied in the reader's two-phase
            # predicate read, BELOW decode — dropped rows never decode.
            reader_kwargs["predicate"] = stream_predicate
        if projection is not None:
            # Hoisted column pruning: only the projected fields are read
            # (and decoded) at all; overrides any construction-time view.
            reader_kwargs["schema_fields"] = list(projection)

        def build_reader():
            self._m_readers.inc()
            return factory(self.dataset_url, dynamic_ventilation=True,
                           num_epochs=1, shuffle_row_groups=False,
                           cur_shard=0, shard_count=1,
                           **reader_kwargs)

        permute_fn = None
        if shuffle_seed is not None:
            seed, epoch_number = int(shuffle_seed), int(epoch or 0)

            def permute_fn(piece, n):
                return batch_permutation(seed, epoch_number, piece, n)

        cache = self._batch_cache
        # Post-decode cache placement stores PRE-transform bytes, so the
        # key must say "untransformed" — which is also exactly why a
        # placement flip re-fills instead of serving the other placement's
        # bytes (the two placements' keys differ).
        transformed = (transform_fn is not None
                       and cache_stage == "post-transform")
        packer_factory = None
        if packing is not None:
            from petastorm_tpu.service.packing_stage import StreamPacker

            packer_factory = (
                lambda: StreamPacker(packing, placement="worker"))
        return StreamingPieceEngine(
            build_reader, self._batch_size, cache=cache,
            cache_key_fn=(
                (lambda piece: self._piece_cache_key(
                    piece, transformed=transformed, packing=packing,
                    predicate=stream_predicate, projection=projection,
                    family=family_name))
                if cache is not None else None),
            cache_note_fn=(
                (lambda hit: self._note_cache_lookup(epoch, hit, job=job))
                if cache is not None else None),
            permute_fn=permute_fn, transform_fn=transform_fn,
            packer_factory=packer_factory,
            fused=fused, cache_stage=cache_stage,
            columnar_collate=(family_name == "columnar"),
            handoff_note_fn=self._m_handoff.inc,
            # Quarantine needs a frame vocabulary that can SAY
            # "piece_failed": only the tagged/dynamic protocols have one —
            # a legacy plain/fcfs stream keeps failing loudly.
            on_piece_error=(self._on_piece_error if allow_quarantine
                            else "fail"))

    def _note_engine_decode(self, collector, decode_s, bid):
        """Engine events carry decode DURATION, not absolute span times
        (the pull happened inside ``next_event``); anchor the trace span
        to end at the dequeue so the per-bid chain stays completion-
        ordered (decode ends before this batch's send starts)."""
        if not decode_s:
            return
        self._m_decode.observe(decode_s)
        if collector.enabled:
            t_now = time.perf_counter()
            collector.record_span("worker.decode", t_now - decode_s, t_now,
                                  bid=bid)

    def _stream_pieces_engine(self, tx, conn_reader, state, pieces, flow,
                              credits, stream_key, epoch=None,
                              shuffle_seed=None, transform_fn=None,
                              job=None, rewrites=None):
        """Cache-armed serving through the streaming engine: warm pieces
        scatter-gather straight from cache memory, cold pieces decode
        through the stream's ONE shared pipeline and fill the cache — the
        PR 5 per-piece reader spinup is gone. Batch boundaries stay
        piece-aligned, exactly like the per-piece cached path. Same serve
        loop as :meth:`_stream_pieces_tagged`, minus the tags (a legacy
        plain stream carries no piece/ordinal headers and no
        ``piece_done`` frames)."""
        return self._stream_pieces_tagged(tx, conn_reader, state, pieces,
                                          flow, credits, stream_key, {},
                                          epoch=epoch, tagged=False,
                                          shuffle_seed=shuffle_seed,
                                          transform_fn=transform_fn,
                                          job=job, rewrites=rewrites)

    def _stream_pieces_tagged(self, tx, conn_reader, state, pieces, flow,
                              credits, stream_key, starts, epoch=None,
                              tagged=True, shuffle_seed=None,
                              transform_fn=None, job=None, packing=None,
                              rewrites=None):
        """Exactly-once static serving: piece-aligned batches through the
        streaming engine, every ``batch`` frame tagged with its piece and
        absolute ``ordinal``, every finished piece announced with a
        ``piece_done`` frame — the static analogue of the dynamic stream's
        event vocabulary, minus the queue edits. ``starts`` holds the
        client's per-piece delivery watermarks: the engine skip-scans (or
        frame-seeks, warm) past already-delivered batches, so a takeover
        or reconnect re-serve duplicates nothing. ``tagged=False`` serves
        the same loop as the legacy untagged engine stream (no tags, no
        markers)."""
        collector = tracing.COLLECTOR
        engine = self._make_engine(epoch, shuffle_seed, transform_fn,
                                   job=job, allow_quarantine=tagged,
                                   packing=packing, rewrites=rewrites)
        with self._lock:
            # The engine is Reader-shaped for lifecycle and snapshots
            # (diagnostics / stop / join): the teardown block stops it,
            # which stops whatever reader it lazily built.
            state["reader"] = engine
        for piece in pieces:
            engine.enqueue(piece, 0, start=starts.get(int(piece), 0))
        engine.finish()
        rows_sent = 0
        while True:
            if self._server.stopped.is_set():
                return None
            event = engine.next_event(timeout=0.1)
            if event is None:
                if engine.finished:
                    return rows_sent
                continue
            if event[0] == "batch":
                _, piece, _gen, ordinal, rows, fmt, frames, decode_s = event
                bid = (f"{self.worker_id}:{stream_key}:"
                       f"{flow['batches_sent']}")
                self._note_engine_decode(collector, decode_s, bid)
                if not self._send_stream_batch(
                        tx, conn_reader, flow, credits, bid, rows, fmt,
                        frames, collector,
                        extra_header=({"piece": piece, "ordinal": ordinal}
                                      if tagged else None)):
                    return None
                rows_sent += rows
            elif event[0] == "piece_failed":
                # Quarantine (tagged-only by construction: the engine runs
                # policy "fail" on plain streams): the poison piece is
                # reported in place of its batches; the stream survives.
                _, piece, _gen, error = event
                tx.send({"type": "piece_failed", "piece": piece,
                         "error": error})
            elif tagged:  # piece_done: plain streams carry no such frame
                _, piece, _gen, rows = event
                tx.send({"type": "piece_done", "piece": piece,
                         "rows": rows})

    def _stream_dynamic(self, tx, conn_reader, state, pieces, flow,
                        credits, stream_key, epoch=None, shuffle_seed=None,
                        transform_fn=None, job=None, packing=None,
                        rewrites=None, early_frames=()):
        """Dynamic-mode serving: the engine's piece queue is the worker's
        deque, edited in-band mid-stream — ``extend`` appends steal
        grants, ``revoke`` removes not-yet-sent pieces (acked with the
        subset actually removed, which is what makes the client's
        revoke-then-extend steal handshake exactly-once), and
        ``finish_pieces`` closes the queue so the stream ends once
        everything drained. Every ``batch`` frame carries its piece and
        ownership generation; each finished piece is announced with a
        ``piece_done`` frame."""
        if not self._engine_supported():
            raise ValueError(
                "dynamic streams need the streaming piece engine, which "
                "requires reader_pool_type='thread' (or 'dummy') — this "
                f"worker runs "
                f"{self._reader_kwargs.get('reader_pool_type')!r}")
        collector = tracing.COLLECTOR
        engine = self._make_engine(epoch, shuffle_seed, transform_fn,
                                   job=job, allow_quarantine=True,
                                   packing=packing, rewrites=rewrites)
        with self._lock:
            # The engine is Reader-shaped for lifecycle and snapshots
            # (diagnostics / stop / join): the teardown block stops it,
            # which stops whatever reader it lazily built.
            state["reader"] = engine
        for piece, gen, start in pieces:
            engine.enqueue(piece, gen, start=start)

        def on_frame(msg):
            kind = msg.get("type")
            if kind == "extend":
                for entry in msg.get("pieces", []):
                    engine.enqueue(int(entry[0]), int(entry[1]),
                                   start=(int(entry[2])
                                          if len(entry) > 2 else 0))
            elif kind == "revoke":
                removed = engine.revoke(
                    int(p) for p in msg.get("pieces", []))
                tx.send({"type": "revoked", "pieces": removed,
                         "req": msg.get("req")})
            elif kind == "finish_pieces":
                engine.finish()

        # Queue edits that raced the shm ack (negotiation buffered them
        # so the credit drain below never sees them out of order).
        for msg in early_frames:
            on_frame(msg)
        rows_sent = 0
        while True:
            if self._server.stopped.is_set():
                return None
            while conn_reader.data_pending():
                msg, _ = conn_reader.recv()
                if msg.get("type") == "credit":
                    flow["credits_left"] += int(msg.get("n", 1))
                else:
                    on_frame(msg)
            event = engine.next_event(timeout=0.02)
            if event is None:
                if engine.finished:
                    return rows_sent
                continue
            if event[0] == "batch":
                _, piece, gen, ordinal, rows, fmt, frames, decode_s = event
                bid = (f"{self.worker_id}:{stream_key}:"
                       f"{flow['batches_sent']}")
                self._note_engine_decode(collector, decode_s, bid)
                if not self._send_stream_batch(
                        tx, conn_reader, flow, credits, bid, rows, fmt,
                        frames, collector,
                        extra_header={"piece": piece, "generation": gen,
                                      "ordinal": ordinal},
                        on_frame=on_frame):
                    return None
                rows_sent += rows
            elif event[0] == "piece_failed":
                _, piece, gen, error = event
                tx.send({"type": "piece_failed", "piece": piece,
                         "generation": gen, "error": error})
            else:  # piece_done
                _, piece, gen, rows = event
                tx.send({"type": "piece_done", "piece": piece,
                         "generation": gen, "rows": rows})

    #: Credit-starved streams poll for replenishment on this period so the
    #: wait stays interruptible (stop flag, dead-peer teardown) — TCP
    #: keepalive still detects the silent-host case underneath.
    CREDIT_POLL_S = 1.0

    _CACHE_EPOCHS_KEPT = 64
    #: Distinct jobs whose rows/cache attribution is retained (evicted
    #: oldest-first beyond it, along with their labeled metric series) —
    #: a shared fleet outliving thousands of short jobs must not grow
    #: its diagnostics and /metrics cardinality forever.
    _JOBS_TRACKED_KEPT = 64

    def _note_cache_lookup(self, epoch, hit, job=None):
        """Bucket one cache lookup by the requesting stream's epoch —
        exact cold-vs-warm attribution for the per-epoch breakdown — and
        by its JOB (multi-tenant sharing economics: N jobs over one
        dataset should fill once and hit ever after)."""
        key = "hits" if hit else "misses"
        if job is not None:
            FLEET_JOB_CACHE_LOOKUPS.labels(
                job, "hit" if hit else "miss").inc()
            with self._lock:
                bucket = self._cache_jobs.pop(job,
                                              {"hits": 0, "misses": 0})
                bucket[key] += 1
                self._cache_jobs[job] = bucket  # pop+reinsert = LRU touch
                while len(self._cache_jobs) > self._JOBS_TRACKED_KEPT:
                    old_job = next(iter(self._cache_jobs))
                    self._cache_jobs.pop(old_job)
                    FLEET_JOB_CACHE_LOOKUPS.remove(old_job, "hit")
                    FLEET_JOB_CACHE_LOOKUPS.remove(old_job, "miss")
        if epoch is None:
            return
        with self._lock:
            bucket = self._cache_epochs.setdefault(
                int(epoch), {"hits": 0, "misses": 0})
            bucket[key] += 1
            while len(self._cache_epochs) > self._CACHE_EPOCHS_KEPT:
                self._cache_epochs.pop(min(self._cache_epochs))

    def cache_stats_by_epoch(self):
        """``{epoch: {"hits", "misses"}}`` for recent epochs (empty when
        uncached) — the ``service`` scenario's per-epoch hit rates."""
        with self._lock:
            return {epoch: dict(bucket)
                    for epoch, bucket in self._cache_epochs.items()}

    def cache_stats_by_job(self):
        """``{job: {"hits", "misses"}}`` — per-tenant attribution of the
        shared decoded-batch cache (empty when uncached or untagged)."""
        with self._lock:
            return {job: dict(bucket)
                    for job, bucket in self._cache_jobs.items()}

    def rows_by_job(self):
        """``{job: {"rows", "batches"}}`` served per job — the fairness
        measurement surface (the ``multi_tenant`` bench leg reads it)."""
        with self._lock:
            return {job: dict(counts)
                    for job, counts in self._jobs_served.items()}

    def _make_stream_reader(self, pieces):
        self._m_readers.inc()
        return self._factory(self.dataset_url, piece_indices=pieces,
                             num_epochs=1, shuffle_row_groups=False,
                             cur_shard=0, shard_count=1,
                             **self._reader_kwargs)

    def _piece_cache_key(self, piece, transformed=False, packing=None,
                         predicate=None, projection=None, family=None):
        from petastorm_tpu.cache_impl import (
            batch_fingerprint,
            predicate_ingredient,
        )

        kwargs = self._reader_kwargs
        # Content signature: the piece's (path, row_group) identity, not
        # just its index — re-materializing the dataset under the same url
        # (fresh part-file names, same row-group count) must miss the
        # persistent disk tier. (In-place overwrites that keep identical
        # file names remain invisible — docs/guides/caching.md.)
        signature = (self._piece_signatures[int(piece)]
                     if self._piece_signatures is not None
                     and int(piece) < len(self._piece_signatures)
                     else int(piece))
        extra = {"filters": kwargs.get("filters"),
                 "predicate": repr(kwargs.get("predicate")),
                 "piece_index": int(piece),
                 "num_pieces": self.num_pieces,
                 "last_batch": "keep"}
        if self._batch_transform is not None:
            # Placement-aware keying: entries hold POST-transform bytes
            # when the stage ran here, pre-transform bytes when the client
            # runs it — the two must never serve each other. Workers
            # without a batch_transform keep the legacy key (old disk
            # entries stay warm).
            extra["batch_transform"] = (
                _transform_identity(self._batch_transform)
                if transformed else None)
        if packing is not None:
            # Packed entries hold a different vocabulary entirely
            # ([slots, slot_len] frames whose batch count is a function
            # of the length distribution): key on the full geometry so
            # they can never serve an unpacked stream — or a different
            # slot shape — and vice versa.
            extra["packing"] = packing.key_dict()
        if predicate is not None:
            # Hoisted stream-level row filter: entries hold only the
            # SURVIVING rows, so the filter is part of the content
            # identity (canonical wire form — stable across worker
            # restarts, unlike a live object's repr).
            extra["stream_predicate"] = predicate_ingredient(predicate)
        fields = kwargs.get("schema_fields")
        if projection is not None:
            # Hoisted column pruning: the projected field set supersedes
            # any construction-time view for this stream's entries.
            fields = sorted(projection)
        return batch_fingerprint(
            self.dataset_url, [signature], self._batch_size,
            fields=fields,
            transform=kwargs.get("transform_spec"),
            # The EFFECTIVE decode family for this stream, not the
            # constructed one: a row_vs_columnar swap re-keys (and
            # re-fills) rather than serving frames produced by the other
            # family's collator.
            factory=family or self._factory_name,
            extra=extra)

    def _send_stream_batch(self, tx, conn_reader, flow, credits, bid,
                           rows, fmt, frames, collector,
                           extra_header=None, on_frame=None):
        # NB ``flow["job"]`` (set by _stream from the request's job_id)
        # drives per-job delivery attribution below.
        """The shared per-batch send step: honor stop, drain/await credits,
        apply fault-injection pacing, scatter-gather the frames, account.
        Returns ``False`` when the worker stopped (caller aborts the
        stream without an ``end`` frame). ``on_frame`` handles non-credit
        control frames encountered while draining (dynamic streams carry
        ``extend``/``revoke``/``finish_pieces`` queue edits in-band — they
        must not be lost to a credit wait); ``extra_header`` merges into
        the ``batch`` frame header (piece/generation tags)."""
        if self._server.stopped.is_set():
            return False
        if credits is not None:
            # Drain replenishments OPPORTUNISTICALLY every batch,
            # not only when starved: un-read credit messages would
            # otherwise pile up in the TCP buffers all stream long
            # until the client's blocking ack send wedges against
            # this worker's blocking batch send (a four-way
            # distributed deadlock on long streams).
            while conn_reader.data_pending():
                reply, _ = conn_reader.recv()
                if reply.get("type") == "credit":
                    flow["credits_left"] += int(reply.get("n", 1))
                elif on_frame is not None:
                    on_frame(reply)
                # anything else mid-stream is out of protocol; skip
            if flow["credits_left"] <= 0:
                t0 = time.perf_counter()
                while flow["credits_left"] <= 0:
                    if self._server.stopped.is_set():
                        return False
                    # Bounded wait, not a timeout-less recv: a client HOST
                    # that vanished without FIN/RST must not pin this
                    # stream thread forever — the poll re-checks the stop
                    # flag every CREDIT_POLL_S (the blocking-recv audit;
                    # recv itself only runs once bytes are readable, so
                    # framing is never torn by a timeout mid-message).
                    if not conn_reader.wait_data(self.CREDIT_POLL_S):
                        continue
                    reply, _ = conn_reader.recv()
                    if reply.get("type") == "credit":
                        flow["credits_left"] += int(reply.get("n", 1))
                    elif on_frame is not None:
                        on_frame(reply)
                waited = time.perf_counter() - t0
                flow["credit_wait_s"] += waited
                self._m_credit_wait.inc(waited)
        if self._batch_delay_s:
            time.sleep(self._batch_delay_s)
        fp = failpoints.ACTIVE
        if fp is not None:
            # Straggler injection: "delay" stalls THIS worker's batch
            # send — the slow-but-alive peer the hedged re-serve exists
            # for. Keyed by worker_id so a targeted schedule (the
            # overload_tail bench) pins the slowness to one worker.
            fp.fire("slow-peer", key=self.worker_id)
        t_send = time.perf_counter()
        header = {"type": "batch", "rows": rows, "bid": bid}
        if extra_header:
            header.update(extra_header)
        tx.send_frames(header, fmt, frames)
        if collector.enabled:
            collector.record_span("worker.send", t_send,
                                  time.perf_counter(), bid=bid)
        flow["batches_sent"] += 1
        self._m_batches.inc()
        self._m_rows.inc(rows)
        columnar_path = flow.get("columnar_path")
        if columnar_path is not None:
            # Resolved once per stream in _stream; children interned at
            # construction — per-batch cost is one counter inc.
            self._m_columnar[columnar_path].inc()
        if flow.get("job") is not None:
            # Per-batch: only the registry child's own fine-grained lock
            # (the labels()-per-batch idiom the client counters use).
            # Worker-level attribution accumulates lock-free in the flow
            # dict and folds into _jobs_served ONCE at stream teardown —
            # the send path must not serialize every tenant's batches on
            # the worker's global lock.
            FLEET_JOB_ROWS.labels(flow["job"]).inc(rows)
            flow["job_rows"] = flow.get("job_rows", 0) + rows
            flow["job_batches"] = flow.get("job_batches", 0) + 1
        if credits is not None:
            flow["credits_left"] -= 1
        return True

    @staticmethod
    def _batch_rows(batch):
        from petastorm_tpu.cache_impl.batch_cache import batch_rows

        return batch_rows(batch)

    def diagnostics_snapshot(self):
        """``Reader.diagnostics`` of every active stream (merged with its
        flow-control state — credits window/left, batches sent, seconds
        blocked waiting for replenishment) plus the final snapshot of
        recently finished ones — what a remote client sees. The
        ``metrics`` block carries this worker's lifetime registry counters
        (monotonic, so two probes give fleet rates — what ``python -m
        petastorm_tpu.service status --watch`` renders; cache hit/miss
        totals ride along when a batch cache is armed, so the watch view
        can render a live hit rate). ``cache`` carries the batch cache's
        own stats block (tiers, bytes, evictions)."""
        with self._lock:
            # A cache-armed stream serving a warm piece has no live reader.
            active = {key: dict((entry["reader"].diagnostics
                                 if entry["reader"] is not None else {}),
                                **entry["flow"])
                      for key, entry in self._active.items()}
            completed = {key: dict(diag)
                         for key, diag in self._completed.items()}
            jobs_served = {job: dict(counts)
                           for job, counts in self._jobs_served.items()}
            cache_jobs = {job: dict(bucket)
                          for job, bucket in self._cache_jobs.items()}
            transport_streams = dict(self._transport_streams)
        metrics = {
            "batches_sent_total": self._m_batches.value,
            "rows_sent_total": self._m_rows.value,
            "credit_wait_seconds_total": self._m_credit_wait.value,
            "active_streams": self._m_active.value,
            "readers_constructed_total": self._m_readers.value,
            # Which tier this worker's streams negotiated (the `service
            # status --watch` TRANSPORT column renders shm/tcp/mixed).
            "transport_streams_tcp_total": transport_streams["tcp"],
            "transport_streams_shm_total": transport_streams["shm"],
            # row_vs_columnar accounting (the status --watch COL% column):
            # batches decoded by vectorized columnar kernels vs batches a
            # columnar request degraded to the row path for.
            "columnar_batches_total": self._m_columnar["columnar"].value,
            "row_fallback_batches_total":
                self._m_columnar["row_fallback"].value,
        }
        out = {
            "worker_id": self.worker_id,
            "num_pieces": self.num_pieces,
            "active_streams": active,
            "completed_streams": completed,
            "metrics": metrics,
        }
        if jobs_served:
            out["jobs"] = jobs_served
        if cache_jobs:
            out["cache_by_job"] = cache_jobs
        if self._batch_cache is not None:
            stats = self._batch_cache.stats()
            metrics["cache_hits_total"] = stats["hits"]
            metrics["cache_misses_total"] = stats["misses"]
            metrics["cache_permuted_serves_total"] = stats["permuted_serves"]
            # Fleet-tier visibility (the status --watch CACHE column):
            # which tier this worker's cache is, how many entries it
            # holds, and how much of its warmth arrived remotely.
            metrics["cache_tier"] = stats.get("tier", "local")
            metrics["cache_entries_mem"] = stats["entries_mem"]
            metrics["cache_entries_disk"] = stats["entries_disk"]
            if "remote_hits" in stats:
                metrics["cache_remote_hits_total"] = stats["remote_hits"]
            out["cache"] = stats
        return out

    def cache_stats(self):
        """The batch cache's stats block, or ``None`` when uncached —
        what the ``service`` scenario samples at epoch boundaries."""
        return (self._batch_cache.stats()
                if self._batch_cache is not None else None)
