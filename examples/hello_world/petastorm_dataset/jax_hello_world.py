"""Read the hello-world dataset into JAX device arrays — the TPU-native path.

No reference analogue (the reference has no JAX surface); this is the
framework's headline addition.
"""

import argparse

from petastorm_tpu import make_jax_dataloader, make_reader


def jax_hello_world(dataset_url):
    reader = make_reader(dataset_url, schema_fields=["id", "image1"],
                         num_epochs=1)
    loader = make_jax_dataloader(reader, batch_size=4, last_batch="pad")
    with loader:
        for batch in loader:
            # batch["image1"] is a jax.Array already resident on the device
            print(type(batch["image1"]).__name__, batch["image1"].shape,
                  batch["image1"].dtype)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default="file:///tmp/hello_world_dataset")
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)
