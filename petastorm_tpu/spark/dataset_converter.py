"""One-call path from a DataFrame to TF / Torch / JAX input pipelines.

Reference parity: ``petastorm/spark/spark_dataset_converter.py``
(``make_spark_converter``, ``SparkDatasetConverter`` with
``make_tf_dataset`` / ``make_torch_dataloader`` / ``.delete()``, cache-dir
management, dedup, ref-counting, atexit cleanup) — SURVEY.md §2.5, §7 stage 7
and hard-part #7. Differences, by design:

- engine is pyarrow: input is a pandas DataFrame or ``pa.Table`` (a pyspark
  DataFrame is accepted and converted via ``toPandas()`` when pyspark is
  importable) and materialization is ``pq.write_table`` — no JVM;
- dedup is **content-hash** based (``pd.util.hash_pandas_object`` over the
  materialized data + write options) instead of Spark's query-plan hash —
  the reference hashes the plan because re-evaluating a Spark DF is
  expensive; here the data is already local so hashing content is exact;
- ``make_jax_dataloader`` is first-class alongside the TF/Torch surfaces.

The parent cache dir comes from (in priority order) the explicit argument,
:func:`set_parent_cache_dir_url`, or ``$PETASTORM_TPU_CACHE_DIR`` — standing
in for the reference's Spark conf key
``petastorm.spark.converter.parentCacheDirUrl``.
"""

from __future__ import annotations

import atexit
import hashlib
import logging
import os
import shutil
import threading
import uuid

import numpy as np

logger = logging.getLogger(__name__)

_parent_cache_dir_url = None
_cache_lock = threading.Lock()
#: content-hash -> CachedDataFrameMeta (reference: ``_cache_df_meta_list``)
_cache_registry = {}


def set_parent_cache_dir_url(url):
    """Set the parent directory under which materialized caches are created
    (reference conf key ``petastorm.spark.converter.parentCacheDirUrl``)."""
    global _parent_cache_dir_url
    _parent_cache_dir_url = url


def _resolve_parent_cache_dir(explicit):
    url = explicit or _parent_cache_dir_url \
        or os.environ.get("PETASTORM_TPU_CACHE_DIR")
    if not url:
        raise ValueError(
            "No cache directory configured: pass parent_cache_dir_url=, call "
            "set_parent_cache_dir_url(), or set $PETASTORM_TPU_CACHE_DIR "
            "(reference conf key petastorm.spark.converter.parentCacheDirUrl)")
    return url


class CachedDataFrameMeta:
    """Bookkeeping for one materialized cache dir (ref-counted)."""

    def __init__(self, cache_key, dir_url, row_count):
        self.cache_key = cache_key
        self.dir_url = dir_url
        self.row_count = row_count
        self.ref_count = 0


def _to_arrow_table(df, dtype=None):
    """pandas / pyarrow / pyspark input → pa.Table (+optional float cast)."""
    import pyarrow as pa

    if hasattr(df, "toPandas"):  # pyspark DataFrame (optional shim)
        df = df.toPandas()
    if isinstance(df, pa.Table):
        table = df
    else:
        import pandas as pd

        if not isinstance(df, pd.DataFrame):
            raise TypeError(
                f"Unsupported input {type(df)}; expected pandas DataFrame, "
                f"pyarrow Table, or pyspark DataFrame")
        table = pa.Table.from_pandas(df, preserve_index=False)
    if dtype:
        target = pa.from_numpy_dtype(np.dtype(dtype))
        cast_fields = [
            pa.field(f.name, target) if pa.types.is_floating(f.type) else f
            for f in table.schema]
        table = table.cast(pa.schema(cast_fields))
    return table


class _HashingSink:
    """File-like sink that feeds written bytes straight into a hasher —
    lets us hash an Arrow IPC stream without materializing a copy."""

    def __init__(self, hasher):
        self._hasher = hasher

    def write(self, data):
        self._hasher.update(data)
        return len(data)

    def flush(self):
        pass

    @property
    def closed(self):
        return False

    def close(self):
        pass


def _content_hash(table, row_group_size_bytes, compression_codec, dtype):
    """Content hash of the materialized bytes-to-be (dedup key).

    Hashes the table's Arrow IPC serialization, which normalizes away
    zero-copy slicing at EVERY nesting level (IPC truncates buffers to the
    slice): ``table.slice`` views and ListArray children sliced from a shared
    buffer hash by logical content, never by parent-buffer identity. Tables
    with identical logical content but different chunking can still hash
    differently; that only costs an extra cache dir, never wrong reuse.
    """
    import pyarrow as pa

    hasher = hashlib.sha256()
    hasher.update(str(table.schema).encode("utf-8"))
    hasher.update(f"{row_group_size_bytes}|{compression_codec}|{dtype}|"
                  f"{table.num_rows}".encode("utf-8"))
    with pa.ipc.new_stream(_HashingSink(hasher), table.schema) as writer:
        writer.write_table(table)
    return hasher.hexdigest()[:32]


def make_spark_converter(df, parquet_row_group_size_bytes=32 * 1024 * 1024,
                         compression_codec="snappy", dtype="float32",
                         parent_cache_dir_url=None):
    """Materialize ``df`` once (dedup by content hash) and return a converter.

    Reference parity: ``make_spark_converter(df, parquet_row_group_size_bytes,
    compression_codec, dtype)``. ``dtype`` casts floating columns (the
    reference's precision conversion); pass ``None`` to keep exact dtypes.
    """
    import pyarrow.parquet as pq

    parent = _resolve_parent_cache_dir(parent_cache_dir_url)
    parent_path = parent[7:] if parent.startswith("file://") else parent
    table = _to_arrow_table(df, dtype=dtype)
    cache_key = _content_hash(table, parquet_row_group_size_bytes,
                              compression_codec, dtype)
    dir_path = os.path.join(parent_path, f"cache-{cache_key}")
    # Materialize OUTSIDE the lock (a multi-second write must not serialize
    # unrelated conversions); tmp-dir + atomic rename makes concurrent
    # writers of the same content converge on one published dir.
    if not os.path.isdir(dir_path):
        os.makedirs(parent_path, exist_ok=True)
        tmp_path = dir_path + f".tmp-{uuid.uuid4().hex[:8]}"
        os.makedirs(tmp_path)
        rows_per_group = max(
            1, int(parquet_row_group_size_bytes
                   // max(table.nbytes // max(table.num_rows, 1), 1)))
        pq.write_table(table,
                       os.path.join(tmp_path, "part-00000.parquet"),
                       row_group_size=rows_per_group,
                       compression=compression_codec)
        try:
            os.rename(tmp_path, dir_path)  # atomic publish
        except OSError:  # another writer published first
            shutil.rmtree(tmp_path, ignore_errors=True)
    else:
        logger.info("Reusing existing cache dir %s", dir_path)
    with _cache_lock:
        meta = _cache_registry.get(cache_key)
        if meta is None:
            meta = CachedDataFrameMeta(cache_key, f"file://{dir_path}",
                                       table.num_rows)
            _cache_registry[cache_key] = meta
        meta.ref_count += 1
    return DatasetConverter(meta)


class DatasetConverter:
    """Handle to a materialized cache dir; builds input pipelines over it.

    Reference parity: ``SparkDatasetConverter`` — ``make_tf_dataset``,
    ``make_torch_dataloader``, ``__len__``, ``.delete()``; plus the new
    ``make_jax_dataloader``.
    """

    def __init__(self, cached_meta):
        self._meta = cached_meta
        self.cache_dir_url = cached_meta.dir_url

    def __len__(self):
        return self._meta.row_count

    # -- pipeline factories (context managers, reference shape) -----------

    def _make_batch_reader(self, reader_kwargs):
        from petastorm_tpu import make_batch_reader

        return make_batch_reader(self.cache_dir_url, **(reader_kwargs or {}))

    def make_tf_dataset(self, batch_size=None, num_epochs=None,
                        workers_count=None, shuffle_row_groups=True,
                        **reader_kwargs):
        reader_kwargs.setdefault("shuffle_row_groups", shuffle_row_groups)
        if num_epochs is not None:
            reader_kwargs["num_epochs"] = num_epochs
        if workers_count is not None:
            reader_kwargs["workers_count"] = workers_count
        return _TFDatasetContextManager(
            self._make_batch_reader(reader_kwargs), batch_size)

    def make_torch_dataloader(self, batch_size=32, num_epochs=None,
                              workers_count=None, shuffling_queue_capacity=0,
                              **reader_kwargs):
        if num_epochs is not None:
            reader_kwargs["num_epochs"] = num_epochs
        if workers_count is not None:
            reader_kwargs["workers_count"] = workers_count
        reader = self._make_batch_reader(reader_kwargs)
        from petastorm_tpu.pytorch import BatchedDataLoader

        return _ClosingContextManager(
            BatchedDataLoader(reader, batch_size=batch_size,
                              shuffling_queue_capacity=shuffling_queue_capacity))

    def make_jax_dataloader(self, batch_size=32, num_epochs=None,
                            workers_count=None, loader_kwargs=None,
                            **reader_kwargs):
        if num_epochs is not None:
            reader_kwargs["num_epochs"] = num_epochs
        if workers_count is not None:
            reader_kwargs["workers_count"] = workers_count
        reader = self._make_batch_reader(reader_kwargs)
        from petastorm_tpu.jax_utils import make_jax_dataloader

        return _ClosingContextManager(
            make_jax_dataloader(reader, batch_size, **(loader_kwargs or {})))

    # -- lifecycle ---------------------------------------------------------

    def delete(self):
        """Drop this handle's reference; removes the cache dir when the last
        reference goes (reference ``.delete()`` semantics)."""
        with _cache_lock:
            meta = self._meta
            meta.ref_count -= 1
            if meta.ref_count <= 0:
                _cache_registry.pop(meta.cache_key, None)
                path = meta.dir_url[7:] if meta.dir_url.startswith("file://") \
                    else meta.dir_url
                shutil.rmtree(path, ignore_errors=True)


#: Reference import-compat alias.
SparkDatasetConverter = DatasetConverter
# Reference conf-key name, kept as a documented constant for parity.
SparkDatasetConverter.PARENT_CACHE_DIR_URL_CONF = \
    "petastorm.spark.converter.parentCacheDirUrl"


class _ClosingContextManager:
    """``with converter.make_torch_dataloader() as loader:`` — closes the
    loader (and its reader) on exit (reference context-manager shape)."""

    def __init__(self, loader):
        self._loader = loader

    def __enter__(self):
        return self._loader.__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        return self._loader.__exit__(exc_type, exc_val, exc_tb)


class _TFDatasetContextManager:
    """Yields a ``tf.data.Dataset``; closes the reader on exit."""

    def __init__(self, reader, batch_size):
        self._reader = reader
        self._batch_size = batch_size

    def __enter__(self):
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        dataset = make_petastorm_dataset(self._reader)
        if self._batch_size:
            dataset = dataset.unbatch().batch(self._batch_size)
        return dataset

    def __exit__(self, exc_type, exc_val, exc_tb):
        self._reader.stop()
        self._reader.join()


@atexit.register
def _cleanup_remaining_caches():
    """Best-effort removal of still-referenced caches at interpreter exit
    (reference registers the same kind of atexit hook)."""
    with _cache_lock:
        for meta in list(_cache_registry.values()):
            path = meta.dir_url[7:] if meta.dir_url.startswith("file://") \
                else meta.dir_url
            shutil.rmtree(path, ignore_errors=True)
        _cache_registry.clear()
