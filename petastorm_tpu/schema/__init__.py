"""Tensor-aware schema + column codecs (the reference's L1 data model).

Reference parity: ``petastorm/unischema.py``, ``petastorm/codecs.py``,
``petastorm/transform.py`` (see SURVEY.md §2.1).
"""

from petastorm_tpu.schema.codecs import (  # noqa: F401
    CompressedImageCodec,
    CompressedNdarrayCodec,
    DataframeColumnCodec,
    NdarrayCodec,
    ScalarCodec,
)
from petastorm_tpu.schema.transform import TransformSpec, transform_schema  # noqa: F401
from petastorm_tpu.schema.unischema import (  # noqa: F401
    Unischema,
    UnischemaField,
    insert_explicit_nulls,
    match_unischema_fields,
)
