"""Long-context decoder LM — the round's capstone composition.

Every TPU-first piece of the framework in ONE training loop:

- ragged token documents in Parquet (static shapes on disk, true length as
  data) stream through ``make_columnar_reader``;
- ``make_packed_jax_dataloader`` packs them end-to-end per batch row
  (≈ full slot utilization vs padding) and stages batches with the
  split decode/staging producer;
- the decoder's attention is the **flash-local ring**
  (``ring_attention(local_attn="flash")``): sequence-parallel over the
  mesh's ``"sp"`` axis, causal, packed ``segment_ids`` riding the K/V
  ring — no ``[T, T]`` or even ``[L, L]`` score block materializes,
  forward or backward;
- position embeddings index the packer's WITHIN-document positions, and
  the next-token loss stops at document boundaries.

Run: ``python -m examples.long_context_lm.train_lm``.
"""

from __future__ import annotations

import numpy as np

VOCAB = 64


def generate_corpus(dataset_url, docs=512, max_len=48):
    """Ragged integer-token documents (padded on disk + length column)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("LmCorpus", [
        UnischemaField("tokens", np.int32, (max_len,), NdarrayCodec(),
                       False),
        UnischemaField("length", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(17)

    def rows():
        for _ in range(docs):
            n = int(rng.randint(8, max_len + 1))
            toks = np.zeros((max_len,), np.int32)
            # A learnable pattern: a random walk over the vocab — the next
            # token is predictable from the current one.
            toks[:n] = (np.cumsum(rng.randint(0, 3, n)) + rng.randint(VOCAB)
                        ) % VOCAB
            yield {"tokens": toks, "length": np.int32(n)}

    materialize_rows(dataset_url, schema, rows(), rows_per_row_group=128)
    return dataset_url


def init_lm_params(rng, d_model=64, num_heads=4, num_layers=2,
                   slot_len=128, vocab=VOCAB):
    """Embed + stacked decoder blocks (attention + FFN) + tied head."""
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(rng, 2 + 5 * num_layers)
    s = lambda fan: 1.0 / np.sqrt(fan)  # noqa: E731
    params = {
        "embed": jax.random.normal(keys[0], (vocab, d_model)) * 0.05,
        "pos": jax.random.normal(keys[1], (slot_len, d_model)) * 0.02,
        "blocks": [],
    }
    for i in range(num_layers):
        k = keys[2 + 5 * i:7 + 5 * i]
        params["blocks"].append({
            "wq": jax.random.normal(k[0], (d_model, d_model)) * s(d_model),
            "wk": jax.random.normal(k[1], (d_model, d_model)) * s(d_model),
            "wv": jax.random.normal(k[2], (d_model, d_model)) * s(d_model),
            "wo": jax.random.normal(k[3], (d_model, d_model)) * s(d_model),
            "ffn": jax.random.normal(k[4], (d_model, d_model)) * s(d_model),
        })
    return params


def apply_lm(params, tokens, positions, segment_ids, num_heads, mesh=None,
             attn_axis="sp", batch_axis=None, local_attn="flash"):
    """``tokens``/``positions``/``segment_ids`` [B, T] int → logits
    [B, T, vocab] f32. With a mesh the attention is the sequence-parallel
    flash-local ring; without, the dense causal oracle (the parity check).
    """
    import jax.numpy as jnp

    from petastorm_tpu.models.sequence_model import (attention_reference,
                                                     ring_attention)

    b, t = tokens.shape
    h = params["embed"][tokens] + params["pos"][positions]
    d_model = h.shape[-1]
    dh = d_model // num_heads
    for blk in params["blocks"]:
        def split(w):
            return (h @ w).reshape(b, t, num_heads, dh)

        q, k, v = split(blk["wq"]), split(blk["wk"]), split(blk["wv"])
        if mesh is not None:
            attn = ring_attention(q, k, v, mesh, attn_axis,
                                  batch_axis=batch_axis, causal=True,
                                  segment_ids=segment_ids,
                                  local_attn=local_attn)
        else:
            attn = attention_reference(q, k, v, causal=True,
                                       segment_ids=segment_ids)
        h = h + attn.reshape(b, t, d_model) @ blk["wo"]
        h = h + jnp.tanh(h @ blk["ffn"])
    return (h @ params["embed"].T).astype(jnp.float32)


def make_lm_train_step(num_heads, mesh=None, attn_axis="sp",
                       batch_axis=None, learning_rate=1.0):
    """``step(params, tokens, positions, segment_ids) -> (params, loss)``:
    next-token cross-entropy, valid only where the next position continues
    the SAME document."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, tokens, positions, segment_ids):
        logits = apply_lm(params, tokens, positions, segment_ids,
                          num_heads, mesh=mesh, attn_axis=attn_axis,
                          batch_axis=batch_axis)
        logp = jax.nn.log_softmax(logits[:, :-1])
        nll = -jnp.take_along_axis(
            logp, tokens[:, 1:, None].astype(jnp.int32), axis=2)[..., 0]
        cont = ((segment_ids[:, 1:] == segment_ids[:, :-1])
                & (segment_ids[:, 1:] >= 0)).astype(jnp.float32)
        return (nll * cont).sum() / jnp.maximum(cont.sum(), 1.0)

    def step(params, tokens, positions, segment_ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                  positions, segment_ids)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g, params, grads)
        return new_params, loss

    return step


def train_lm(dataset_url, slot_len=128, slots=4, steps=12, num_heads=4,
             epochs=8):
    """The full loop; returns ``(first_loss, final_loss, logit_parity)``
    where ``logit_parity`` is the max |sharded - dense| logit difference on
    the last batch (the ring must match the dense oracle exactly)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import (PACK_POSITION_KEY,
                                         PACK_SEGMENT_KEY,
                                         make_packed_jax_dataloader)

    n_dev = len(jax.devices())
    sp = 8 if n_dev >= 8 else (2 if n_dev >= 2 else 1)
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",)) if sp > 1 else None

    params = init_lm_params(jax.random.PRNGKey(0), slot_len=slot_len,
                            num_heads=num_heads)
    step = jax.jit(make_lm_train_step(num_heads, mesh=mesh))

    reader = make_columnar_reader(dataset_url, num_epochs=epochs,
                                  shuffle_row_groups=True)
    loader = make_packed_jax_dataloader(reader, slot_len=slot_len,
                                        slots=slots,
                                        sequence_fields=["tokens"],
                                        length_field="length",
                                        max_batches=steps,
                                        stage_to_device=False)
    losses, last = [], None
    with loader:
        for packed in loader:
            tokens = jnp.asarray(packed["tokens"])
            pos = jnp.asarray(packed[PACK_POSITION_KEY])
            seg = jnp.asarray(packed[PACK_SEGMENT_KEY])
            params, loss = step(params, tokens, pos, seg)
            losses.append(float(loss))
            last = (tokens, pos, seg)

    # Parity: the sequence-parallel flash ring vs the dense single-device
    # oracle on the SAME final params and batch. Meaningless without a mesh
    # (the "sharded" arm would BE the dense path) — report None then.
    if mesh is None:
        return losses[0], losses[-1], None
    tokens, pos, seg = last
    sharded = apply_lm(params, tokens, pos, seg, num_heads, mesh=mesh)
    dense = apply_lm(params, tokens, pos, seg, num_heads, mesh=None)
    parity = float(jnp.abs(sharded - dense).max())
    return losses[0], losses[-1], parity


def main(dataset_url=None):
    import shutil
    import tempfile

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="long_context_lm_")
        dataset_url = f"file://{tmpdir}/corpus"
        generate_corpus(dataset_url)
    try:
        first, final, parity = train_lm(dataset_url)
        parity_note = ("single device — ring not exercised"
                       if parity is None else f"{parity:.2e}")
        print(f"long-context LM: loss {first:.4f} -> {final:.4f}, "
              f"ring-vs-dense logit parity {parity_note}")
        return final
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    main()
