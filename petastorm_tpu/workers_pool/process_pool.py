"""Multi-process worker pool over zmq PUSH/PULL/PUB.

Reference parity: ``petastorm/workers_pool/process_pool.py::ProcessPool`` —
SURVEY.md §2.2, §7 hard-part #1. Topology (all host-local ``ipc://`` sockets):

- ventilation: main PUSH  →  worker PULL   (load-balanced work items)
- results:     worker PUSH →  main PULL    (serialized payloads + control frames)
- control:     main PUB   →  worker SUB    (stop broadcast)

Workers are fresh interpreters (``exec_in_new_process``), not forks — fork
safety matters on a TPU host where the parent holds the JAX/TPU runtime.
Backpressure comes from zmq high-water marks on the results sockets.
Payloads cross the boundary through a pluggable serializer (pickle or
Arrow IPC — ``petastorm_tpu/reader_impl/*_serializer.py``).

Frame types on the results socket:
``READY`` (startup sync), ``RESULT`` (payload), ``DONE`` (one ventilated item
finished), ``EXC`` (worker exception + traceback), ``EXIT`` (clean shutdown).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
import time
from collections import deque

from petastorm_tpu.telemetry.metrics import (
    POOL_ITEMS_PROCESSED,
    POOL_ITEMS_VENTILATED,
)
from petastorm_tpu.workers_pool import (
    DEFAULT_TIMEOUT_S,
    EmptyResultError,
    TimeoutWaitingForResultError,
)
from petastorm_tpu.workers_pool.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers_pool.thread_pool import WorkerException
from petastorm_tpu.reader_impl.pickle_serializer import PickleSerializer

_FRAME_READY = b"READY"
_FRAME_RESULT = b"RESULT"
_FRAME_DONE = b"DONE"
_FRAME_EXC = b"EXC"
_FRAME_EXIT = b"EXIT"
_CTRL_STOP = b"STOP"

_STARTUP_TIMEOUT_S = 60


class ProcessPool:
    def __init__(self, workers_count, serializer=None, zmq_copy_buffers=True,
                 results_queue_size=50):
        self._workers_count = workers_count
        self._serializer = serializer or PickleSerializer()
        self._zmq_copy_buffers = zmq_copy_buffers
        self._results_queue_size = results_queue_size

        self._context = None
        self._vent_socket = None
        self._results_socket = None
        self._control_socket = None
        self._ipc_dir = None
        self._processes = []
        self._ventilator = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._exited_workers = 0
        self._stopped = False
        # Locally buffered (kind, frames) messages already pulled off the zmq
        # socket — makes results_qsize a real depth (zmq's internal queue is
        # not introspectable) and lets diagnostics see pending results.
        # zmq sockets are NOT thread-safe: every poll/recv on the results
        # socket happens under _socket_lock so a diagnostics read from a
        # monitoring thread cannot race the consuming thread's recv.
        self._pending_frames = deque()
        self._socket_lock = threading.Lock()

    @property
    def workers_count(self):
        return self._workers_count

    @property
    def diagnostics(self):
        """Live pool counters (reference ``Reader.diagnostics`` parity:
        ventilated/processed items and results-queue depth — SURVEY.md §5)."""
        return {
            "items_ventilated": self._ventilated_items,
            "items_processed": self._completed_items,
            "items_in_flight": self._ventilated_items - self._completed_items,
            "results_queue_size": self.results_qsize(),
            "workers_count": self._workers_count,
            "exited_workers": self._exited_workers,
            "zmq_copy_buffers": self._zmq_copy_buffers,
        }

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        import zmq

        if self._context is not None:
            raise RuntimeError("ProcessPool already started")
        self._context = zmq.Context()
        self._ipc_dir = tempfile.mkdtemp(prefix="petastorm_tpu_pool_")
        vent_endpoint = f"ipc://{self._ipc_dir}/ventilator"
        results_endpoint = f"ipc://{self._ipc_dir}/results"
        control_endpoint = f"ipc://{self._ipc_dir}/control"

        self._vent_socket = self._context.socket(zmq.PUSH)
        self._vent_socket.setsockopt(zmq.LINGER, 0)
        self._vent_socket.bind(vent_endpoint)

        self._results_socket = self._context.socket(zmq.PULL)
        self._results_socket.setsockopt(zmq.LINGER, 0)
        self._results_socket.setsockopt(zmq.RCVHWM, self._results_queue_size)
        self._results_socket.bind(results_endpoint)

        self._control_socket = self._context.socket(zmq.PUB)
        self._control_socket.setsockopt(zmq.LINGER, 0)
        self._control_socket.bind(control_endpoint)

        import cloudpickle

        for worker_id in range(self._workers_count):
            process = exec_in_new_process(
                _worker_process_main,
                worker_id,
                cloudpickle.dumps((worker_class, worker_setup_args)),
                cloudpickle.dumps(self._serializer),
                vent_endpoint,
                results_endpoint,
                control_endpoint,
                self._results_queue_size,
                self._zmq_copy_buffers,
            )
            self._processes.append(process)

        # Startup sync: wait until every worker's PULL is connected before
        # ventilating, so PUSH load-balancing sees all peers.
        ready = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while ready < self._workers_count:
            if not self._results_socket.poll(200):
                dead = [p for p in self._processes if p.poll() is not None]
                if dead or time.monotonic() > deadline:
                    codes = [p.poll() for p in self._processes]
                    self._emergency_shutdown()
                    raise RuntimeError(
                        f"Only {ready}/{self._workers_count} pool workers came "
                        f"up (exit codes: {codes}, timeout {_STARTUP_TIMEOUT_S}s)"
                    )
                continue
            frames = self._results_socket.recv_multipart()
            if frames[0] == _FRAME_READY:
                ready += 1
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        import cloudpickle

        # cloudpickle: work items may carry lambdas (e.g. in_lambda predicates)
        payload = cloudpickle.dumps((args, kwargs))
        self._ventilated_items += 1
        POOL_ITEMS_VENTILATED.inc()
        self._vent_socket.send(payload)

    def _recv_frames(self):
        """Receive one multipart message off the socket → ``(kind, frames)``."""
        if self._zmq_copy_buffers:
            # copy=False: RESULT payload frames stay in zmq-owned memory
            # and deserialization views them directly (arrays keep the
            # frames alive via the buffer protocol).
            zmq_frames = self._results_socket.recv_multipart(copy=False)
            return zmq_frames[0].bytes, zmq_frames
        frames = self._results_socket.recv_multipart()
        return frames[0], frames

    def _drain_socket_locked(self):
        # Caller must hold _socket_lock. Bounded: local buffer + zmq RCVHWM
        # together cap pending results at ~2x results_queue_size. Draining
        # past the cap would unblock workers stuck on their SNDHWM and defeat
        # the memory backpressure the HWM exists to provide (a monitoring
        # loop polling results_qsize must not grow host memory unboundedly).
        while (self._results_socket is not None
               and len(self._pending_frames) < self._results_queue_size
               and self._results_socket.poll(0)):
            self._pending_frames.append(self._recv_frames())

    def get_results(self, timeout=DEFAULT_TIMEOUT_S):
        deadline = time.monotonic() + timeout
        while True:
            error = getattr(self._ventilator, "error", None) if self._ventilator else None
            if error is not None:
                raise RuntimeError(f"Ventilation failed: {error!r}") from error
            if self._all_done():
                raise EmptyResultError()
            received = None
            with self._socket_lock:
                if self._pending_frames:
                    received = self._pending_frames.popleft()
                elif self._results_socket.poll(100):
                    received = self._recv_frames()
            if received is None:
                self._check_worker_liveness()
                if time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError(
                        f"No results for {timeout}s; ventilated="
                        f"{self._ventilated_items} completed={self._completed_items}"
                    )
                continue
            kind, frames = received
            if kind == _FRAME_RESULT:
                if self._zmq_copy_buffers and hasattr(
                        self._serializer, "deserialize_from_frames"):
                    return self._serializer.deserialize_from_frames(
                        [f.buffer for f in frames[1:]])
                payload_frames = [getattr(f, "bytes", f) for f in frames[1:]]
                payload = (b"".join(payload_frames)
                           if len(payload_frames) > 1 else payload_frames[0])
                return self._serializer.deserialize(payload)
            if kind == _FRAME_DONE:
                self._completed_items += 1
                POOL_ITEMS_PROCESSED.inc()
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if kind == _FRAME_EXC:
                exc_repr, tb = pickle.loads(getattr(frames[1], "bytes",
                                                    frames[1]))
                raise WorkerException(RuntimeError(exc_repr), tb)
            if kind == _FRAME_EXIT:
                self._exited_workers += 1
                continue
            if kind == _FRAME_READY:  # late duplicate; harmless
                continue

    def _all_done(self):
        ventilation_over = self._ventilator is None or self._ventilator.completed()
        if not (ventilation_over
                and self._ventilated_items == self._completed_items):
            return False
        with self._socket_lock:
            return (not self._pending_frames
                    and not self._results_socket.poll(0))

    def _check_worker_liveness(self):
        for process in self._processes:
            code = process.poll()
            if code is not None and code != 0 and not self._stopped:
                raise WorkerException(
                    RuntimeError(f"Pool worker pid={process.pid} died with exit "
                                 f"code {code}"),
                    "(no traceback; the worker process terminated abnormally)",
                )

    def results_qsize(self):
        """Number of RESULT payloads ready for :meth:`get_results`.

        zmq's internal queue is not introspectable, so pending messages are
        pulled into a local buffer (still zero-copy under
        ``zmq_copy_buffers``) and counted there.
        """
        if self._results_socket is None:
            return 0
        with self._socket_lock:
            self._drain_socket_locked()
            return sum(1 for kind, _ in self._pending_frames
                       if kind == _FRAME_RESULT)

    def stop(self):
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        if self._control_socket is not None:
            self._control_socket.send(_CTRL_STOP)

    def join(self):
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in self._processes):
                break
            # Re-broadcast stop: PUB/SUB slow joiners may have missed the first,
            # and drain results so workers blocked on a full HWM can exit.
            if self._control_socket is not None:
                self._control_socket.send(_CTRL_STOP)
            if self._results_socket is not None:
                with self._socket_lock:
                    self._pending_frames.clear()
                    while self._results_socket.poll(0):
                        self._results_socket.recv_multipart()
            time.sleep(0.05)
        for process in self._processes:
            if process.poll() is None:  # pragma: no cover - stragglers only
                process.terminate()
                try:
                    process.wait(timeout=5)
                except Exception:
                    process.kill()
        self._close_sockets()

    def _emergency_shutdown(self):
        for process in self._processes:
            if process.poll() is None:
                process.terminate()
        self._close_sockets()

    def _close_sockets(self):
        for sock in (self._vent_socket, self._results_socket, self._control_socket):
            if sock is not None:
                sock.close(linger=0)
        self._vent_socket = self._results_socket = self._control_socket = None
        if self._context is not None:
            self._context.term()
            self._context = None
        if self._ipc_dir:
            shutil.rmtree(self._ipc_dir, ignore_errors=True)
            self._ipc_dir = None


class _WorkerStopped(Exception):
    """Raised inside a worker when the stop broadcast arrives mid-publish."""


def _worker_process_main(worker_id, worker_class_payload, serializer_payload,
                         vent_endpoint, results_endpoint, control_endpoint,
                         results_queue_size, zmq_copy_buffers=True):
    """Entry point of one pool worker process (runs in a fresh interpreter)."""
    import zmq

    worker_class, worker_setup_args = pickle.loads(worker_class_payload)
    serializer = pickle.loads(serializer_payload)

    context = zmq.Context()
    vent_socket = context.socket(zmq.PULL)
    vent_socket.setsockopt(zmq.LINGER, 0)
    vent_socket.connect(vent_endpoint)
    results_socket = context.socket(zmq.PUSH)
    results_socket.setsockopt(zmq.LINGER, 0)
    results_socket.setsockopt(zmq.SNDHWM, results_queue_size)
    results_socket.connect(results_endpoint)
    control_socket = context.socket(zmq.SUB)
    control_socket.setsockopt(zmq.LINGER, 0)
    control_socket.setsockopt(zmq.SUBSCRIBE, b"")
    control_socket.connect(control_endpoint)

    stop_requested = False

    def _stop_seen():
        nonlocal stop_requested
        if stop_requested:
            return True
        if control_socket.poll(0):
            control_socket.recv()
            stop_requested = True
        return stop_requested

    def _send(frames, copy=True):
        """Send with backpressure that stays responsive to the stop broadcast."""
        while True:
            try:
                results_socket.send_multipart(frames, flags=zmq.NOBLOCK,
                                              copy=copy)
                return
            except zmq.Again:
                if _stop_seen():
                    raise _WorkerStopped() from None
                time.sleep(0.005)

    use_frames = zmq_copy_buffers and hasattr(serializer,
                                              "serialize_to_frames")

    def publish(data):
        if use_frames:
            # Zero-copy: payload buffers (raw array memory) ride as their own
            # zmq frames; copy=False hands zmq references instead of copies
            # (zmq keeps them alive until the frames are flushed).
            _send([_FRAME_RESULT] + serializer.serialize_to_frames(data),
                  copy=False)
        else:
            _send([_FRAME_RESULT, serializer.serialize(data)])

    worker = worker_class(worker_id, publish, worker_setup_args)
    _send([_FRAME_READY, str(worker_id).encode()])

    poller = zmq.Poller()
    poller.register(vent_socket, zmq.POLLIN)
    poller.register(control_socket, zmq.POLLIN)
    try:
        while not stop_requested:
            events = dict(poller.poll(100))
            if control_socket in events:
                control_socket.recv()
                break
            if vent_socket not in events:
                continue
            args, kwargs = pickle.loads(vent_socket.recv())
            try:
                worker.process(*args, **kwargs)
            except _WorkerStopped:
                break
            except Exception as exc:  # noqa: BLE001 - forwarded to the consumer
                import traceback

                _send([_FRAME_EXC, pickle.dumps((repr(exc),
                                                 traceback.format_exc()))])
            # Failed items count as processed too (keeps the ventilation
            # window moving); send outside the try so a stop during the
            # EXC send doesn't double-fault.
            _send([_FRAME_DONE])
    except _WorkerStopped:
        pass
    finally:
        worker.shutdown()
        try:
            results_socket.send_multipart([_FRAME_EXIT], flags=zmq.NOBLOCK)
        except Exception:  # pragma: no cover
            pass
        vent_socket.close(linger=0)
        results_socket.close(linger=0)
        control_socket.close(linger=0)
        context.term()
        os._exit(0)
