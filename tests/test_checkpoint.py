"""Joint model + input-pipeline checkpointing: orbax arrays + reader state
restore together, and training resumes at-least-once."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.jax_utils import (make_jax_dataloader,
                                     restore_training_state,
                                     save_training_state)


def test_roundtrip_arrays_and_input_state(tmp_path, petastorm_dataset):
    import jax.numpy as jnp

    params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3)}
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 10, stage_to_device=False)
    it = iter(loader)
    consumed = [int(i) for i in next(it)["id"]]
    ckpt = save_training_state(tmp_path / "ckpt", params, loader=loader)
    loader.stop(); loader.join(); reader.stop(); reader.join()

    arrays, state = restore_training_state(ckpt)
    np.testing.assert_array_equal(np.asarray(arrays["w"]),
                                  np.arange(6.0).reshape(2, 3))
    assert state is not None

    # resume: the remaining rows are delivered at-least-once
    reader2 = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                          num_epochs=1, shuffle_row_groups=False,
                          resume_state=state)
    loader2 = make_jax_dataloader(reader2, 10, stage_to_device=False)
    resumed = []
    with loader2:
        for batch in loader2:
            resumed.extend(int(i) for i in batch["id"])
    all_ids = {int(r.id) for r in _all_rows(petastorm_dataset.url)}
    assert set(consumed) | set(resumed) == all_ids


def _all_rows(url):
    with make_reader(url, reader_pool_type="dummy", num_epochs=1,
                     shuffle_row_groups=False) as r:
        return list(r)


def test_save_rejects_both_loader_and_state(tmp_path):
    with pytest.raises(ValueError, match="loader OR input_state"):
        save_training_state(tmp_path / "c", {"x": np.zeros(2)},
                            loader=object(), input_state={})


def test_restore_without_input_state(tmp_path):
    ckpt = save_training_state(tmp_path / "c", {"x": np.arange(4.0)})
    arrays, state = restore_training_state(ckpt)
    np.testing.assert_array_equal(np.asarray(arrays["x"]), np.arange(4.0))
    assert state is None
