"""Measure Reader rows/sec (and input-stall %) under pool/worker configs.

Reference parity: ``petastorm/benchmark/throughput.py::reader_throughput`` →
``BenchmarkResult`` — SURVEY.md §2.6. Additions over the reference: an
optional ``spawn_new_process``-free JAX-loader mode that reports the
north-star input-stall % alongside rows/sec.
"""

from __future__ import annotations

import time
from collections import namedtuple

BenchmarkResult = namedtuple(
    "BenchmarkResult",
    ["rows_per_second", "rows_count", "duration_s", "input_stall_pct"])


def reader_throughput(dataset_url, field_regex=None,
                      warmup_cycles_count=200, measure_cycles_count=1000,
                      pool_type="thread", loaders_count=3,
                      read_method="python",
                      shuffle_row_groups=True,
                      apply_jax_loader=False, jax_batch_size=128,
                      **reader_kwargs):
    """Read ``warmup_cycles_count`` rows off the clock, then time
    ``measure_cycles_count`` rows.

    :param field_regex: list of field-name regexes to read (None = all).
    :param pool_type: 'thread' | 'process' | 'dummy'.
    :param loaders_count: workers_count for the pool.
    :param read_method: 'python' (make_reader) or 'arrow' (make_batch_reader —
        cycles then count record batches, as upstream).
    :param apply_jax_loader: measure through ``make_jax_dataloader`` (cycles
        count batches of ``jax_batch_size``); reports stall %.
    """
    from petastorm_tpu.reader.reader import make_batch_reader, make_reader

    factory = {"python": make_reader, "arrow": make_batch_reader}.get(read_method)
    if factory is None:
        raise ValueError(f"Unknown read_method {read_method!r}")
    reader = factory(dataset_url,
                     schema_fields=field_regex,
                     reader_pool_type=pool_type,
                     workers_count=loaders_count,
                     shuffle_row_groups=shuffle_row_groups,
                     num_epochs=None,
                     **reader_kwargs)
    try:
        if apply_jax_loader:
            return _loader_throughput(reader, warmup_cycles_count,
                                      measure_cycles_count, jax_batch_size)
        return _raw_throughput(reader, warmup_cycles_count,
                               measure_cycles_count)
    finally:
        reader.stop()
        reader.join()


def _raw_throughput(reader, warmup, measure):
    it = iter(reader)
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(measure):
        next(it)
    duration = time.perf_counter() - t0
    return BenchmarkResult(rows_per_second=measure / duration,
                           rows_count=measure, duration_s=duration,
                           input_stall_pct=None)


def _loader_throughput(reader, warmup, measure, batch_size):
    from petastorm_tpu.jax_utils import make_jax_dataloader

    loader = make_jax_dataloader(reader, batch_size,
                                 non_tensor_policy="drop",
                                 max_batches=warmup + measure)
    it = iter(loader)
    for _ in range(warmup):
        next(it)
    rows = 0
    t0 = time.perf_counter()
    for _ in range(measure):
        batch = next(it)
        rows += next(v.shape[0] for v in batch.values() if hasattr(v, "shape"))
    duration = time.perf_counter() - t0
    # The generator is suspended at its last yield; wall_s/input_stall_pct are
    # only computed in its finally block, so close it before reading them.
    it.close()
    loader.stop()
    loader.join()
    return BenchmarkResult(rows_per_second=rows / duration, rows_count=rows,
                           duration_s=duration,
                           input_stall_pct=loader.diagnostics["input_stall_pct"])
