"""Batch lifecycle tracing: spans across the worker/client process boundary.

A batch's journey through the disaggregated service crosses threads and (in
real deployments) processes: worker decode → framed send → client stream
reader → shared ready-queue → loader device dispatch → consumer yield. Rates
tell you *that* delivery is slow; only per-batch spans tell you *where one
batch* spent its time. The scheme:

- the worker mints a **batch id** at decode time
  (``<worker_id>:<stream>:<seq>``) and carries it in the ``batch`` frame
  header — the only cross-process plumbing needed;
- every stage records a span against that id into the process-wide
  :class:`TraceCollector` (begin/end event pairs);
- the collector exports Chrome ``trace_event`` JSON
  (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
  — load it in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``
  and follow one ``bid`` across rows.

Collection is **off by default** and costs one attribute read per call
site when off (``record_span`` returns immediately); arming it is
``JaxDataLoader(trace_path=...)``, the service scenario's ``--trace-out``,
or :func:`enable` directly. In a loopback run all stages share one process
and land in one file; multi-process deployments export one file per process
and merge on the bid (Perfetto overlays multiple files by pid).
"""

from __future__ import annotations

import json
import os
import threading
import time

#: Bounded event buffer: at ~10 spans per batch a 200k-event ring covers
#: ~10k batches — hours of tracing at training rates — while bounding a
#: forgotten trace flag to ~50 MB instead of eating the heap forever.
DEFAULT_MAX_EVENTS = 200_000


class TraceCollector:
    """Process-wide span sink (Chrome ``trace_event`` semantics).

    ``enabled`` is a plain bool read without the lock — producers check it
    before computing timestamps, so a disabled collector costs one
    attribute read per potential span.
    """

    def __init__(self, max_events=DEFAULT_MAX_EVENTS):
        self.enabled = False
        self._max_events = max_events
        self._lock = threading.Lock()
        self._events = []
        self._dropped = 0
        self._armers = 0  # acquire/release refcount (scoped arming)
        # trace_event ts is microseconds; perf_counter gives the monotonic
        # duration math, the wall anchor makes traces from different
        # processes of one run line up on a shared axis (close enough for
        # eyeballing; exact alignment needs a shared clock anyway).
        self._epoch = time.time() - time.perf_counter()

    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        with self._lock:
            self._armers = 0

    def acquire(self):
        """Scoped arming for components that share the process collector
        (e.g. a train loader and a mid-epoch eval loader, both with
        ``trace_path``): the FIRST armer clears the buffer, later armers
        join the running trace instead of wiping it, and collection stays
        on until the last armer releases. Pair with :meth:`release`."""
        with self._lock:
            self._armers += 1
            if self._armers == 1:
                self._events = []
                self._dropped = 0
        self.enabled = True
        return self

    def release(self):
        with self._lock:
            self._armers = max(0, self._armers - 1)
            if self._armers == 0:
                self.enabled = False

    def clear(self):
        with self._lock:
            self._events = []
            self._dropped = 0

    def _ts_us(self, t):
        return (self._epoch + t) * 1e6

    def ts_us(self, t):
        """A ``time.perf_counter()`` reading in this collector's trace
        timebase (wall-anchored microseconds) — the unit every event's
        ``ts`` is denominated in. Public so clock alignment can convert
        RPC midpoints into the same axis the merged trace renders on."""
        return self._ts_us(t)

    def now_us(self):
        """The current instant in the trace timebase. Shipped in control
        replies (``dispatcher_time_us``) so peers can estimate their
        offset against the dispatcher's axis NTP-style."""
        return self._ts_us(time.perf_counter())

    def record_span(self, name, t_start, t_end, bid=None, args=None,
                    tid=None):
        """One completed span as a B/E event pair. ``t_start``/``t_end``
        are ``time.perf_counter()`` readings; ``bid`` is the batch id the
        span belongs to (lands in ``args.bid`` so Perfetto's query/search
        finds every stage of one batch)."""
        if not self.enabled:
            return
        span_args = dict(args or {})
        if bid is not None:
            span_args["bid"] = bid
        pid = os.getpid()
        tid = tid if tid is not None else threading.get_ident() % 1_000_000
        begin = {"name": name, "cat": "petastorm", "ph": "B",
                 "ts": self._ts_us(t_start), "pid": pid, "tid": tid,
                 "args": span_args}
        end = {"name": name, "cat": "petastorm", "ph": "E",
               "ts": self._ts_us(t_end), "pid": pid, "tid": tid}
        with self._lock:
            if len(self._events) + 2 > self._max_events:
                self._dropped += 2
                return
            self._events.append(begin)
            self._events.append(end)

    def instant(self, name, t, bid=None, args=None):
        """A zero-duration marker (``ph: i``) — queue handoffs, fences,
        control-plane lifecycle decisions (breaker trips, brownout
        stages, fencing bumps carry their detail in ``args``)."""
        if not self.enabled:
            return
        event_args = dict(args or {})
        if bid is not None:
            event_args["bid"] = bid
        event = {"name": name, "cat": "petastorm", "ph": "i", "s": "t",
                 "ts": self._ts_us(t), "pid": os.getpid(),
                 "tid": threading.get_ident() % 1_000_000,
                 "args": event_args}
        with self._lock:
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append(event)

    def events(self):
        with self._lock:
            return list(self._events)

    def ship(self):
        """Atomically take-and-clear the buffered events (with the drop
        count) — the trace-shipping primitive: an armed peer pushes its
        ring to the dispatcher on each heartbeat tick and keeps
        recording into an empty buffer, so no event is ever shipped
        twice and the ring never grows past one tick's production."""
        with self._lock:
            events, self._events = self._events, []
            dropped, self._dropped = self._dropped, 0
        return events, dropped

    @property
    def dropped(self):
        with self._lock:
            return self._dropped

    def export(self, path):
        """Write the buffered events as Perfetto-loadable trace JSON."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"producer": "petastorm_tpu.telemetry",
                             "dropped_events": dropped}}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return len(events)


#: The process-default collector every producer records into.
COLLECTOR = TraceCollector()


def enable():
    return COLLECTOR.enable()


def disable():
    COLLECTOR.disable()


def record_span(name, t_start, t_end, bid=None, args=None):
    COLLECTOR.record_span(name, t_start, t_end, bid=bid, args=args)


def export(path):
    return COLLECTOR.export(path)


def wall_us():
    """The process's current wall-anchored trace timestamp (µs) from the
    default collector — the one sanctioned wall-clock read outside this
    module (the flight recorder stamps its ring entries with it so dumps
    from different processes correlate on one axis)."""
    return COLLECTOR.now_us()
