"""Read the hello-world dataset as a tf.data.Dataset.

Reference analogue: ``examples/hello_world/petastorm_dataset/tensorflow_hello_world.py``.
"""

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def tensorflow_hello_world(dataset_url):
    with make_reader(dataset_url, schema_fields=["id", "image1"]) as reader:
        dataset = make_petastorm_dataset(reader)
        for tensor in dataset.take(3):
            print(int(tensor.id.numpy()), tensor.image1.shape)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default="file:///tmp/hello_world_dataset")
    args = parser.parse_args()
    tensorflow_hello_world(args.dataset_url)
