"""Materialize an ImageNet-pattern petastorm dataset from an image directory.

Reference analogue: ``examples/imagenet/generate_petastorm_imagenet.py``.
Expects ``<input-dir>/<noun_id>/*.jpg`` layout; with ``--synthetic`` writes
random image rows instead (no corpus in this environment).
"""

import argparse
import os

import numpy as np

from examples.imagenet.schema import ImagenetSchema
from petastorm_tpu.etl.metadata import materialize_rows


def _synthetic_rows(count):
    rng = np.random.RandomState(0)
    for i in range(count):
        yield {"noun_id": f"n{i:08d}",
               "text": f"synthetic noun {i}",
               "image": rng.randint(0, 255, (375, 500, 3), dtype=np.uint8)}


def _directory_rows(input_dir):
    import cv2

    for noun_id in sorted(os.listdir(input_dir)):
        noun_dir = os.path.join(input_dir, noun_id)
        if not os.path.isdir(noun_dir):
            continue
        for name in sorted(os.listdir(noun_dir)):
            image = cv2.imread(os.path.join(noun_dir, name))
            if image is None:
                continue
            image = cv2.resize(image, (500, 375))
            yield {"noun_id": noun_id, "text": noun_id, "image": image}


def generate_petastorm_imagenet(output_url, input_dir=None, count=32):
    rows = _directory_rows(input_dir) if input_dir else _synthetic_rows(count)
    materialize_rows(output_url, ImagenetSchema, rows, row_group_size_mb=64)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--output-url", default="file:///tmp/imagenet_petastorm")
    parser.add_argument("--input-dir", default=None)
    parser.add_argument("--count", type=int, default=32)
    args = parser.parse_args()
    generate_petastorm_imagenet(args.output_url, args.input_dir, args.count)
    print(f"Dataset written to {args.output_url}")
