"""Decoded-batch cache tests (ISSUE 5): fingerprinting, tiered storage,
eviction budgets, and the two hot-path integrations — the service worker's
per-piece decode bypass and the JAX loader's epoch replay.

Correctness bar (the ISSUE acceptance): batches served from cache are
byte-identical to freshly decoded batches (same order under static
sharding), eviction respects the memory budget under concurrent streams,
and the chaos ``worker-kill`` run with ``mem+disk`` caching preserves the
zero-lost delivery invariant while re-serving from the shared disk tier.
"""

import os
import threading

import numpy as np
import pytest

from petastorm_tpu.cache_impl import (
    BatchCache,
    CacheConfig,
    batch_fingerprint,
    live_cache_dirs,
)
from petastorm_tpu.jax_utils.batcher import batch_iterator
from petastorm_tpu.reader_impl.framed_socket import (
    FramedConnection,
    encode_payload,
)
from petastorm_tpu.service import BatchWorker

pytestmark = pytest.mark.service


def _batches_equal(a, b):
    assert sorted(a) == sorted(b)
    for name in a:
        left, right = np.asarray(a[name]), np.asarray(b[name])
        assert left.dtype == right.dtype, name
        if left.dtype == object:
            assert len(left) == len(right)
            for x, y in zip(left, right):
                if isinstance(x, np.ndarray):
                    np.testing.assert_array_equal(x, y)
                else:
                    assert x == y, name
        else:
            np.testing.assert_array_equal(left, right, err_msg=name)


def _make_batch(seed, kb=8):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(kb * 128).astype(np.float64),  # kb KiB
            "i": np.arange(4, dtype=np.int64)}


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def test_fingerprint_stable_and_sensitive():
    base = dict(dataset_url="file:///ds", pieces=[3], batch_size=64,
                fields=["a", "b"], transform=None, factory="batch",
                extra={"filters": None})
    key = batch_fingerprint(**base)
    assert key == batch_fingerprint(**base)  # deterministic
    for mutated in (
            dict(base, dataset_url="file:///other"),
            dict(base, pieces=[4]),
            dict(base, batch_size=65),
            dict(base, fields=["a"]),
            dict(base, transform="TransformSpec(f)"),
            dict(base, factory="row"),
            dict(base, extra={"filters": [("day", "=", 1)]})):
        assert batch_fingerprint(**mutated) != key, mutated


# ---------------------------------------------------------------------------
# tiers, eviction, persistence
# ---------------------------------------------------------------------------

def test_mem_roundtrip_is_byte_identical():
    cache = BatchCache(mem_budget_bytes=8 << 20)
    batches = [_make_batch(0), _make_batch(1)]
    cache.put_batches("k", batches)
    entry = cache.get("k")
    # True byte identity: the cached contiguous buffer IS the freshly
    # re-encoded frame stream of the same batches.
    fresh = b"".join(bytes(memoryview(frame))
                     for batch in batches
                     for frame in encode_payload(batch)[1])
    assert bytes(entry.buf) == fresh
    for got, want in zip(cache.get_batches("k"), batches):
        _batches_equal(got, want)
    assert cache.stats()["hits_mem"] == 2
    cache.cleanup()


def test_mem_budget_lru_eviction():
    cache = BatchCache(mem_budget_bytes=64 << 10)  # 64 KiB
    for i in range(12):  # ~8KiB entries: 12 > budget
        cache.put_batches(f"k{i}", [_make_batch(i)])
    stats = cache.stats()
    assert stats["bytes_mem"] <= 64 << 10
    assert stats["evictions_mem"] > 0
    assert cache.get("k0") is None            # LRU went first
    assert cache.get("k11") is not None       # newest survives
    cache.cleanup()


def test_mem_budget_respected_under_concurrent_streams():
    """Many threads filling and reading at once (the worker serves streams
    concurrently): resident bytes never exceed the budget and every
    lookup returns either None or the exact stored content."""
    cache = BatchCache(mem_budget_bytes=96 << 10)
    errors = []

    def stream(tid):
        try:
            for i in range(10):
                key = f"t{tid}-{i}"
                cache.put_batches(key, [_make_batch(tid * 100 + i)])
                assert cache.stats()["bytes_mem"] <= 96 << 10
                entry = cache.get(key)
                if entry is not None:
                    _batches_equal(entry.to_dicts()[0],
                                   _make_batch(tid * 100 + i))
        except Exception as exc:  # surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=stream, args=(t,)) for t in range(6)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert not errors, errors
    assert cache.stats()["bytes_mem"] <= 96 << 10
    cache.cleanup()


def test_disk_tier_survives_restart(tmp_path):
    """Write-through + a fresh instance on the same directory = the
    restart-warmth contract (a restarted worker re-serves from disk)."""
    cache_dir = str(tmp_path / "tier")
    first = BatchCache(mem_budget_bytes=8 << 20, cache_dir=cache_dir,
                       spill_to_disk=True)
    batches = [_make_batch(7), _make_batch(8)]
    first.put_batches("k", batches)
    stats = first.stats()
    assert stats["entries_disk"] == 1 and stats["bytes_disk"] > 0
    first.cleanup()  # "restart": memory tier gone, directory persists
    assert first.stats()["entries_disk"] == 0  # gauge contribution retracted

    second = BatchCache(mem_budget_bytes=8 << 20, cache_dir=cache_dir,
                        spill_to_disk=True)
    got = second.get_batches("k")
    assert got is not None
    for got_batch, want in zip(got, batches):
        _batches_equal(got_batch, want)
    stats = second.stats()
    assert stats["hits_disk"] == 1
    assert second.get("k") is not None  # promoted to memory
    assert second.stats()["hits_mem"] == 1
    second.cleanup()


def test_disk_budget_evicts_lru_files(tmp_path):
    cache = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=str(tmp_path / "tier"),
                       spill_to_disk=True,
                       disk_budget_bytes=48 << 10)
    for i in range(10):  # ~8KiB files: 10 > the 48KiB budget
        cache.put_batches(f"k{i}", [_make_batch(i)])
    from petastorm_tpu.cache_impl.batch_cache import ENTRY_SUFFIX
    from petastorm_tpu.cache_impl.eviction import dir_size

    assert dir_size(str(tmp_path / "tier"), ENTRY_SUFFIX) <= 48 << 10
    assert cache.stats()["evictions_disk"] > 0
    cache.cleanup()


def test_corrupt_disk_entry_is_a_miss_not_an_error(tmp_path):
    cache = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=str(tmp_path / "tier"), spill_to_disk=True)
    cache.put_batches("k", [_make_batch(1)])
    path = cache._entry_path("k")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)  # torn write
    fresh = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=cache.cache_dir, spill_to_disk=True)
    assert fresh.get("k") is None
    assert not os.path.exists(path)  # the bad file was removed
    fresh.cleanup()
    cache.cleanup()


def test_ephemeral_disk_tier_tracked_and_removed_on_cleanup():
    cache = BatchCache(mem_budget_bytes=1 << 20, spill_to_disk=True)
    assert cache.cache_dir in live_cache_dirs()
    assert os.path.isdir(cache.cache_dir)
    cache.cleanup()
    assert cache.cache_dir not in live_cache_dirs()
    assert not os.path.exists(cache.cache_dir)


def test_cache_config_builds_modes(tmp_path):
    assert CacheConfig(mode="off").build() is None
    mem = CacheConfig(mode="mem", mem_mb=1).build()
    assert mem is not None and mem.cache_dir is None
    disk = CacheConfig(mode="mem+disk", mem_mb=1,
                       cache_dir=str(tmp_path / "d")).build()
    assert disk.cache_dir == str(tmp_path / "d")
    mem.cleanup()
    disk.cleanup()
    with pytest.raises(ValueError, match="cache mode"):
        CacheConfig(mode="bogus")
    # A dir with a memory-only mode is a misconfiguration (the operator
    # asked for persistence they would silently not get), not a no-op.
    with pytest.raises(ValueError, match="mem\\+disk"):
        CacheConfig(mode="mem", cache_dir=str(tmp_path / "x"))


# ---------------------------------------------------------------------------
# service worker integration
# ---------------------------------------------------------------------------

def _stream_worker(worker, pieces, **request_extra):
    """Stream ``pieces`` from a directly-addressed worker; returns the
    batch dicts in arrival order. ``request_extra`` merges into the
    stream request header (epoch, shuffle_seed, tagged, starts...)."""
    batches = []
    with FramedConnection.connect(worker.address, timeout=5) as conn:
        conn.send({"type": "stream", "pieces": pieces, "epoch": 0,
                   **request_extra})
        while True:
            header, payload = conn.recv()
            if header["type"] == "end":
                return batches
            if header["type"] == "piece_done":
                continue
            assert header["type"] == "batch", header
            batches.append(payload)


def test_worker_cached_epoch_skips_reader_and_matches_decode(
        petastorm_dataset):
    """Epoch 2 of a cache-armed worker constructs ZERO readers and serves
    batches identical (values, dtypes, order) to the decode epoch; the
    cold epoch costs ONE reader for the whole stream (the streaming piece
    engine), not one per missed piece.

    Order identity needs the serial dummy pool: a concurrent pool with
    the engine's lookahead may interleave the cold epoch's cross-piece
    emission order, while the warm epoch always stages pieces in queue
    order. Batches are piece-tagged either way, so delivery invariants
    (per-piece content, the epoch multiset) do not depend on it."""
    cache = BatchCache(mem_budget_bytes=64 << 20)
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"reader_pool_type": "dummy"},
                         batch_cache=cache).start()
    constructed = []
    real_factory = worker._factory
    worker._factory = lambda *a, **kw: (constructed.append(1)
                                        or real_factory(*a, **kw))
    try:
        epoch1 = _stream_worker(worker, [0, 1, 2])
        assert len(constructed) == 1  # one engine reader per stream
        epoch2 = _stream_worker(worker, [0, 1, 2])
        assert len(constructed) == 1  # warm epoch: no readers at all
        assert len(epoch1) == len(epoch2)
        for cold, warm in zip(epoch1, epoch2):
            _batches_equal(cold, warm)
        stats = cache.stats()
        assert stats["misses"] == 3 and stats["hits"] == 3
        rows = sum(len(next(iter(b.values()))) for b in epoch2)
        assert rows == 30
    finally:
        worker.stop()


def test_worker_cached_piece_byte_identical_to_uncached(petastorm_dataset):
    """Per-piece streams from a cached and an uncached worker deliver the
    same batch sequence (single-piece streams share batch boundaries, so
    this is an exact order + content comparison)."""
    cache = BatchCache(mem_budget_bytes=64 << 20)
    cached_worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                                reader_kwargs={"reader_pool_type": "dummy"},
                                batch_cache=cache).start()
    plain_worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                               reader_kwargs={"reader_pool_type": "dummy"}
                               ).start()
    try:
        for piece in (0, 1, 2):
            plain = _stream_worker(plain_worker, [piece])
            filled = _stream_worker(cached_worker, [piece])   # miss path
            warm = _stream_worker(cached_worker, [piece])     # hit path
            assert len(plain) == len(filled) == len(warm)
            for want, miss, hit in zip(plain, filled, warm):
                _batches_equal(want, miss)
                _batches_equal(want, hit)
    finally:
        cached_worker.stop()
        plain_worker.stop()


def test_worker_stop_cleans_ephemeral_cache_dir(petastorm_dataset):
    worker = BatchWorker(
        petastorm_dataset.url, batch_size=4,
        reader_kwargs={"reader_pool_type": "dummy"},
        batch_cache=CacheConfig(mode="mem+disk", mem_mb=4).build()).start()
    cache_dir = worker._batch_cache.cache_dir
    try:
        _stream_worker(worker, [0])
        assert cache_dir in live_cache_dirs()
    finally:
        worker.stop()
    assert cache_dir not in live_cache_dirs()
    assert not os.path.exists(cache_dir)


def test_worker_restart_re_serves_from_disk_tier(petastorm_dataset,
                                                 tmp_path):
    """Kill a cache-armed worker, start a replacement on the same cache
    directory: the warm pieces come back from the disk tier (hits, no
    re-decode) with identical content — the PR 3 re-registration story
    composed with the disk tier."""
    cache_dir = str(tmp_path / "shared_tier")

    def make_worker():
        return BatchWorker(
            petastorm_dataset.url, batch_size=4,
            reader_kwargs={"reader_pool_type": "dummy"},
            batch_cache=CacheConfig(mode="mem+disk", mem_mb=64,
                                    cache_dir=cache_dir).build()).start()

    first = make_worker()
    try:
        cold = _stream_worker(first, [0, 1, 2])
    finally:
        first.kill()
    second = make_worker()
    try:
        warm = _stream_worker(second, [0, 1, 2])
        stats = second._batch_cache.stats()
        assert stats["hits_disk"] == 3 and stats["misses"] == 0
        assert len(cold) == len(warm)
        for want, got in zip(cold, warm):
            _batches_equal(want, got)
    finally:
        second.stop()


def test_worker_cache_key_signs_piece_content_identity(petastorm_dataset):
    """The per-piece key folds in the piece's (path, row_group) identity:
    a re-materialized dataset (new part-file names, same piece count)
    changes the key, so the persistent disk tier misses instead of
    serving yesterday's batches."""
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"reader_pool_type": "dummy"},
                         batch_cache=BatchCache(mem_budget_bytes=1 << 20))
    worker.num_pieces = worker._count_pieces()
    key = worker._piece_cache_key(0)
    assert key == worker._piece_cache_key(0)  # stable across lookups
    path, row_group = worker._piece_signatures[0]
    worker._piece_signatures[0] = (path + ".rewritten", row_group)
    assert worker._piece_cache_key(0) != key
    worker._batch_cache.cleanup()


def test_worker_diagnostics_carry_cache_stats(petastorm_dataset):
    cache = BatchCache(mem_budget_bytes=64 << 20)
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"reader_pool_type": "dummy"},
                         batch_cache=cache).start()
    try:
        _stream_worker(worker, [0])
        _stream_worker(worker, [0])
        snapshot = worker.diagnostics_snapshot()
        assert snapshot["metrics"]["cache_hits_total"] == 1
        assert snapshot["metrics"]["cache_misses_total"] == 1
        assert snapshot["cache"]["hit_rate"] == 0.5
        assert worker.cache_stats()["hits"] == 1
    finally:
        worker.stop()


# ---------------------------------------------------------------------------
# shuffle-compatible serving (worker tier)
# ---------------------------------------------------------------------------

def test_worker_shuffled_warm_epoch_multiset_and_reshuffle(
        petastorm_dataset):
    """The shuffle-compatible serving contract at the worker: a warm
    shuffled epoch delivers the byte-identical batch MULTISET of an
    unshuffled run, per-epoch orders differ across epochs and seeds, and
    the same (seed, epoch) replays identically — all at 100% hit rate
    after the fill."""
    cache = BatchCache(mem_budget_bytes=64 << 20)
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"reader_pool_type": "dummy"},
                         batch_cache=cache).start()
    plain_worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                               reader_kwargs={"reader_pool_type": "dummy"}
                               ).start()
    try:
        # Piece-by-piece streams share the cached paths' piece-aligned
        # batch boundaries (the whole-set uncached stream collates across
        # pieces — a different batching, not a different multiset).
        plain = _batch_digests([b for piece in (0, 1, 2)
                                for b in _stream_worker(plain_worker,
                                                        [piece])])
        # Cold shuffled epoch 0: fills canonically, serves permuted.
        epoch0 = _batch_digests(
            _stream_worker(worker, [0, 1, 2], shuffle_seed=7))
        assert cache.stats()["misses"] == 3
        # Warm epochs: 100% hit rate, fresh permutation per epoch.
        epoch1 = _batch_digests(
            _stream_worker(worker, [0, 1, 2], epoch=1, shuffle_seed=7))
        epoch1_again = _batch_digests(
            _stream_worker(worker, [0, 1, 2], epoch=1, shuffle_seed=7))
        epoch1_seed9 = _batch_digests(
            _stream_worker(worker, [0, 1, 2], epoch=1, shuffle_seed=9))
        stats = cache.stats()
        assert stats["misses"] == 3 and stats["hits"] == 9
        # Every WARM piece serve went out permuted (cold fills decode —
        # they are misses, not cache serves).
        assert stats["permuted_serves"] == 9
        # Multiset identity vs the unshuffled run: bytes are canonical,
        # only the serve order moved.
        for shuffled in (epoch0, epoch1, epoch1_seed9):
            assert sorted(shuffled) == sorted(plain)
        # Orders: differ across epochs and seeds, replay per (seed, epoch).
        assert epoch0 != epoch1
        assert epoch1 != epoch1_seed9
        assert epoch1 == epoch1_again
    finally:
        worker.stop()
        plain_worker.stop()


def test_worker_shuffled_cold_warm_same_order_and_watermark_seek(
        petastorm_dataset):
    """The permutation is a pure function of (seed, epoch, piece, n) —
    NOT of cache state: the cold fill epoch and a warm re-serve of the
    same (seed, epoch) emit the identical permuted order, and a
    ``starts`` re-grant (the watermark re-serve path) resumes that order
    at the exact permuted position, warm or cold."""
    seed = 11

    def fresh_worker():
        return BatchWorker(petastorm_dataset.url, batch_size=4,
                           reader_kwargs={"reader_pool_type": "dummy"},
                           batch_cache=BatchCache(mem_budget_bytes=64 << 20)
                           ).start()

    worker = fresh_worker()
    try:
        cold = _batch_digests(_stream_worker(worker, [0], tagged=True,
                                             shuffle_seed=seed))
        warm = _batch_digests(_stream_worker(worker, [0], tagged=True,
                                             shuffle_seed=seed))
        assert cold == warm  # warm-vs-cold order identity (same epoch)
        # Warm watermark seek: re-grant at start=2 serves the tail.
        tail = _batch_digests(_stream_worker(worker, [0], tagged=True,
                                             shuffle_seed=seed,
                                             starts={"0": 2}))
        assert tail == warm[2:]
    finally:
        worker.stop()
    # Cold watermark seek: a FRESH worker (empty cache) re-granted at
    # start=2 re-decodes the piece and resumes the same permuted order.
    worker = fresh_worker()
    try:
        cold_tail = _batch_digests(_stream_worker(worker, [0], tagged=True,
                                                  shuffle_seed=seed,
                                                  starts={"0": 2}))
        assert cold_tail == warm[2:]
    finally:
        worker.stop()


def test_worker_cache_key_invariant_to_shuffle_and_epoch(petastorm_dataset):
    """Golden invariance (the CI satellite): the worker's per-piece cache
    key has no seed/epoch/shuffle ingredient at all — its inputs are the
    piece's content identity and the decode-shaping config, so epoch 1's
    fill hits on every later epoch and any other seed by construction.
    The fingerprint API enforces the exclusion for future ingredients."""
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"reader_pool_type": "dummy"},
                         batch_cache=BatchCache(mem_budget_bytes=1 << 20))
    worker.num_pieces = worker._count_pieces()
    key = worker._piece_cache_key(0)
    assert key == worker._piece_cache_key(0)
    worker._batch_cache.cleanup()
    # Enforcement: an order-dependent ingredient cannot reach a key.
    for bad in ({"shuffle_seed": 7}, {"epoch": 1}, {"Shuffle": True},
                {"nested": {"row_order": [1, 2]}}):
        with pytest.raises(ValueError, match="order-dependent"):
            batch_fingerprint("file:///ds", [0], 64, extra=bad)
    # Golden pin: the key derivation itself (sha256 over the canonical
    # payload) must not drift — a silent change would cold every
    # persistent disk tier (or worse, alias old entries).
    assert batch_fingerprint(
        "file:///ds", [("part-0.parquet", 3)], 64, fields=["a", "b"],
        factory="batch", extra={"filters": None, "last_batch": "keep"},
    ) == ("03de5f50d5cd1bc291b1b1947230d38777bde51218accf0197b5380e8b41"
          "9adc")


# ---------------------------------------------------------------------------
# entry format versioning
# ---------------------------------------------------------------------------

def test_old_format_entries_evicted_as_version_mismatch(tmp_path):
    """An entry written by an older format (PR 5/8 magics) is detected,
    counted as a VERSION eviction (not corruption), deleted, and reported
    as a miss — the degrade path of the frame-index format change."""
    cache = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=str(tmp_path / "tier"), spill_to_disk=True)
    cache.put_batches("k", [_make_batch(1)])
    path = cache._entry_path("k")
    blob = open(path, "rb").read()
    for old_magic in (b"PTBCACHE1\n", b"PTBCACHE2\n"):
        with open(path, "wb") as f:
            f.write(old_magic + blob[len(old_magic):])
        fresh = BatchCache(mem_budget_bytes=8 << 20,
                           cache_dir=cache.cache_dir, spill_to_disk=True)
        assert fresh.get("k") is None
        stats = fresh.stats()
        assert stats["version_evicted"] == 1
        assert stats["corrupt_entries"] == 0  # NOT the corrupt path
        assert not os.path.exists(path)
        fresh.cleanup()
        # Refill for the next magic round.
        cache.put_batches("k", [_make_batch(1)])
    cache.cleanup()


def test_damaged_headers_fuzz_never_error(tmp_path):
    """Fuzz-style sweep over damaged entry files — truncations at every
    region boundary, garbage magics, a meta format field that disagrees
    with the magic, flipped payload bits: every case is a clean miss
    (counted corrupt or version-evicted), never an exception, and the
    bad file is gone afterwards."""
    import json as json_mod
    import struct as struct_mod

    from petastorm_tpu.cache_impl.batch_cache import _LEN, _MAGIC

    cache = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=str(tmp_path / "tier"), spill_to_disk=True)
    cache.put_batches("k", [_make_batch(1), _make_batch(2)])
    path = cache._entry_path("k")
    good = open(path, "rb").read()
    meta_len = _LEN.unpack_from(good, len(_MAGIC))[0]
    payload_off = len(_MAGIC) + _LEN.size + meta_len

    def mutate_meta(**overrides):
        meta = json_mod.loads(
            good[len(_MAGIC) + _LEN.size:payload_off].decode())
        meta.update(overrides)
        raw = json_mod.dumps(meta).encode()
        return (_MAGIC + struct_mod.pack("!Q", len(raw)) + raw
                + good[payload_off:])

    cases = [
        good[:5],                            # torn inside the magic
        good[:len(_MAGIC) + 3],              # torn inside the length
        good[:payload_off - 4],              # torn inside the meta json
        good[:payload_off + 7],              # torn inside the payload
        b"",                                 # empty file
        b"GARBAGE!!\n" + good[10:],          # unknown magic
        good[:-3] + b"\xff\xff\xff",         # flipped payload tail
        mutate_meta(format=999),             # meta/magic version disagree
        mutate_meta(crc32=12345),            # checksum mismatch
    ]
    for blob in cases:
        with open(path, "wb") as f:
            f.write(blob)
        fresh = BatchCache(mem_budget_bytes=8 << 20,
                           cache_dir=cache.cache_dir, spill_to_disk=True)
        assert fresh.get("k") is None, blob[:16]
        stats = fresh.stats()
        assert (stats["corrupt_entries"] + stats["version_evicted"]) == 1
        assert not os.path.exists(path), blob[:16]
        fresh.cleanup()
        cache.put_batches("k", [_make_batch(1), _make_batch(2)])
    # And the pristine file still loads (the fuzz loop's refill is valid).
    fresh = BatchCache(mem_budget_bytes=8 << 20,
                       cache_dir=cache.cache_dir, spill_to_disk=True)
    assert fresh.get("k") is not None
    fresh.cleanup()
    cache.cleanup()


# ---------------------------------------------------------------------------
# JAX loader integration
# ---------------------------------------------------------------------------

def test_loader_replays_epoch_from_cache(petastorm_dataset):
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    cache = BatchCache(mem_budget_bytes=64 << 20)
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=False)
    loader = JaxDataLoader(reader, 7, last_batch="keep",
                           stage_to_device=False, batch_cache=cache)
    with loader:
        epoch1 = list(loader)
        # The num_epochs=1 reader is exhausted — without the cache this
        # second pass would yield nothing.
        epoch2 = list(loader)
        epoch3 = list(loader)
    assert len(epoch1) == len(epoch2) == len(epoch3) == 5
    for want, got in zip(epoch1, epoch2):
        _batches_equal(want, got)
    for want, got in zip(epoch1, epoch3):
        _batches_equal(want, got)
    stats = cache.stats()
    assert stats["misses"] == 1 and stats["hits"] == 2
    cache.cleanup()


def test_loader_partial_iteration_never_commits(petastorm_dataset):
    """Abandoning the consumer mid-epoch must not publish a truncated
    entry that later replays as a 'complete' epoch — not on the abandoned
    pass, and not on a LATER pass either (the reader then resumes from an
    unknown mid-stream position, so a re-iteration's batches are a tail:
    they stream through uncached and are never committed)."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    cache = BatchCache(mem_budget_bytes=64 << 20)
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=False)
    loader = JaxDataLoader(reader, 7, last_batch="keep",
                           stage_to_device=False, batch_cache=cache)
    with loader:
        for _ in loader:
            break  # abandon after one batch
        assert cache.stats()["entries_mem"] == 0
        # Re-iterating a spoiled loader serves the reader's remainder
        # uncached; nothing may ever be committed under the epoch key.
        tail = list(loader)
        assert len(tail) < 5  # strictly a tail, not the full 5-batch epoch
        assert cache.stats()["entries_mem"] == 0
        assert list(loader) == []  # exhausted, still nothing committed
    assert cache.stats()["entries_mem"] == 0
    cache.cleanup()


def test_loader_cache_rejects_batch_source(petastorm_dataset):
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    cache = BatchCache(mem_budget_bytes=1 << 20)
    with pytest.raises(ValueError, match="decode bypass"):
        JaxDataLoader(None, 4, batch_source=lambda: iter([]),
                      stage_to_device=False, batch_cache=cache)
    with pytest.raises(ValueError, match="cache_resume"):
        JaxDataLoader(object(), 4, stage_to_device=False,
                      cache_resume={"kind": "cache_replay",
                                    "cache_epoch": 0})
    cache.cleanup()


def _batch_digests(batches):
    """Order-sensitive per-batch content digests (sorted → the multiset)."""
    import hashlib

    out = []
    for batch in batches:
        h = hashlib.blake2b(digest_size=16)
        for name in sorted(batch):
            col = np.asarray(batch[name])
            h.update(name.encode())
            if col.dtype == object:
                for item in col:
                    item = np.asarray(item)
                    h.update(item.tobytes() if item.dtype != object
                             else repr(item.tolist()).encode())
            else:
                h.update(col.tobytes())
        out.append(h.hexdigest())
    return out


def test_loader_cache_shuffled_replay_permutes_per_epoch(petastorm_dataset):
    """Shuffle-compatible loader caching: every pass serves the SAME batch
    multiset (canonical cached bytes) in a DIFFERENT order (serve-time
    permutation), deterministically — a loader re-built with the same
    seed replays the same orders, a different seed orders differently."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    def run(seed, passes=3):
        cache = BatchCache(mem_budget_bytes=64 << 20)
        reader = make_reader(petastorm_dataset.url,
                             reader_pool_type="dummy", num_epochs=1,
                             shuffle_row_groups=False)
        loader = JaxDataLoader(reader, 7, last_batch="keep",
                               stage_to_device=False, batch_cache=cache,
                               shuffle_seed=seed)
        with loader:
            epochs = [_batch_digests(list(loader)) for _ in range(passes)]
        stats = cache.stats()
        cache.cleanup()
        return epochs, stats

    epochs_a, stats = run(7)
    assert stats["misses"] == 1 and stats["hits"] == 2
    assert stats["permuted_serves"] == 3  # fill pass serves permuted too
    # Same multiset every pass, different order each pass.
    assert all(sorted(e) == sorted(epochs_a[0]) for e in epochs_a)
    assert len({tuple(e) for e in epochs_a}) == 3
    # Deterministic across runs; a different seed draws different orders.
    epochs_b, _ = run(7)
    assert epochs_a == epochs_b
    epochs_c, _ = run(8)
    assert sorted(epochs_c[0]) == sorted(epochs_a[0])
    assert epochs_c != epochs_a


def test_loader_cache_shuffled_multiset_matches_unshuffled(
        petastorm_dataset):
    """The shuffled cache serves the byte-identical batch MULTISET of an
    unshuffled run — what proves the bytes are canonical and only the
    serve order moved (the fill ignores the shuffle knobs)."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    def epoch(shuffle_seed):
        cache = BatchCache(mem_budget_bytes=64 << 20)
        reader = make_reader(petastorm_dataset.url,
                             reader_pool_type="dummy", num_epochs=1,
                             shuffle_row_groups=False)
        loader = JaxDataLoader(reader, 7, last_batch="keep",
                               stage_to_device=False, batch_cache=cache,
                               shuffle_seed=shuffle_seed)
        with loader:
            digests = _batch_digests(list(loader))
        cache.cleanup()
        return digests

    plain, shuffled = epoch(None), epoch(7)
    assert shuffled != plain            # order moved
    assert sorted(shuffled) == sorted(plain)  # bytes did not


def test_loader_cache_key_invariant_to_shuffle_config(petastorm_dataset):
    """The loader's cache key excludes every shuffle ingredient: seed,
    buffer, and row-group flag — epoch 1's fill hits on any other seed
    (the cross-job "decode once" contract)."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    def key(**loader_kwargs):
        reader = make_reader(petastorm_dataset.url,
                             reader_pool_type="dummy", num_epochs=1,
                             shuffle_row_groups=False)
        loader = JaxDataLoader(reader, 7, last_batch="keep",
                               stage_to_device=False,
                               batch_cache=BatchCache(
                                   mem_budget_bytes=1 << 20),
                               **loader_kwargs)
        out = loader._reader_cache_key()
        loader._batch_cache.cleanup()
        reader.stop()
        reader.join()
        return out

    base = key()
    assert key(shuffle_seed=7) == base
    assert key(shuffle_seed=8) == base
    assert key(shuffle_buffer_size=16, shuffle_seed=3) == base


def test_loader_cache_resume_mid_permuted_epoch(petastorm_dataset):
    """state_dict() mid-shuffled-replay + cache_resume= resumes the pass
    at the exact permuted position: the resumed tail equals the
    uninterrupted pass's tail, and later passes line up too."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    def make_loader(cache, resume=None):
        reader = make_reader(petastorm_dataset.url,
                             reader_pool_type="dummy", num_epochs=1,
                             shuffle_row_groups=False)
        return JaxDataLoader(reader, 7, last_batch="keep",
                             stage_to_device=False, batch_cache=cache,
                             shuffle_seed=7, cache_resume=resume)

    cache = BatchCache(mem_budget_bytes=64 << 20)
    with make_loader(cache) as loader:
        full = [_batch_digests(list(loader)) for _ in range(2)]

    cache2 = BatchCache(mem_budget_bytes=64 << 20)
    with make_loader(cache2) as loader:
        iterator = iter(loader)
        first = _batch_digests([next(iterator) for _ in range(2)])
        state = loader.state_dict()
        assert state["kind"] == "cache_replay"
        assert state["batches_yielded"] == 2
    # "Restore": a fresh loader over a fresh reader (same construction)
    # resumes the permuted pass mid-epoch; the next pass continues the
    # epoch sequence.
    with make_loader(cache2, resume=state) as loader:
        rest = _batch_digests(list(loader))
        nxt = _batch_digests(list(loader))
    assert first == full[0][:2]
    assert rest == full[0][2:]
    assert nxt == full[1]
    cache.cleanup()
    cache2.cleanup()


def test_loader_cache_resume_at_pass_boundary_and_seed_mismatch(
        petastorm_dataset):
    """Two resume edge cases: a state_dict() taken AFTER a pass completes
    snapshots the NEXT pass's start (resuming must not serve an empty
    epoch or replay the finished one), and resuming under a different
    shuffle seed raises instead of silently skipping a prefix of the
    wrong permutation."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    def make_loader(cache, seed=7, resume=None):
        reader = make_reader(petastorm_dataset.url,
                             reader_pool_type="dummy", num_epochs=1,
                             shuffle_row_groups=False)
        return JaxDataLoader(reader, 7, last_batch="keep",
                             stage_to_device=False, batch_cache=cache,
                             shuffle_seed=seed, cache_resume=resume)

    cache = BatchCache(mem_budget_bytes=64 << 20)
    with make_loader(cache) as loader:
        full = [_batch_digests(list(loader)) for _ in range(2)]

    cache2 = BatchCache(mem_budget_bytes=64 << 20)
    with make_loader(cache2) as loader:
        first = _batch_digests(list(loader))  # pass 0, fully consumed
        state = loader.state_dict()
    assert first == full[0]
    # The completed pass rolled forward: resume serves pass 1 in full.
    assert state["cache_epoch"] == 1 and state["batches_yielded"] == 0
    with make_loader(cache2, resume=state) as loader:
        assert _batch_digests(list(loader)) == full[1]
    # Seed mismatch: the resume position indexes seed 7's permutation.
    with make_loader(cache2, seed=8, resume=state) as loader:
        with pytest.raises(ValueError, match="shuffle_seed"):
            list(loader)
    # Unseeded shuffled reader: the fill order is not reproducible, so a
    # cold-cache resume could seek into the wrong sequence — refused.
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=True)
    with pytest.raises(ValueError, match="shard_seed"):
        JaxDataLoader(reader, 7, last_batch="keep", stage_to_device=False,
                      batch_cache=cache2, cache_resume=state)
    reader.stop()
    reader.join()
    cache.cleanup()
    cache2.cleanup()


def test_loader_cache_accepts_shuffled_reader(petastorm_dataset):
    """A shuffle_row_groups reader is accepted: the fill order is the
    reader's first-pass order (canonical for this cache), replays permute
    per pass, and the row multiset always matches the dataset."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.reader.reader import make_reader

    cache = BatchCache(mem_budget_bytes=64 << 20)
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1, shuffle_row_groups=True,
                         shard_seed=3)
    loader = JaxDataLoader(reader, 7, last_batch="keep",
                           stage_to_device=False, batch_cache=cache)
    with loader:
        epoch1 = list(loader)
        epoch2 = list(loader)
    ids1 = sorted(int(i) for b in epoch1 for i in np.asarray(b["id"]))
    ids2 = sorted(int(i) for b in epoch2 for i in np.asarray(b["id"]))
    want = sorted(int(r["id"]) for r in petastorm_dataset.rows)
    assert ids1 == want and ids2 == want
    assert sorted(_batch_digests(epoch1)) == sorted(_batch_digests(epoch2))
    assert _batch_digests(epoch1) != _batch_digests(epoch2)
    assert cache.stats()["hits"] == 1
    cache.cleanup()


# ---------------------------------------------------------------------------
# scenario: per-epoch breakdown + warm-epoch acceptance
# ---------------------------------------------------------------------------

def test_service_scenario_epoch_breakdown_and_warm_hit_rate(tmp_path):
    """Tier-1 scale of the ISSUE acceptance A/B: 2 workers, 2 epochs,
    cache=mem — the per-epoch breakdown lands in --json-out, epoch 2 is
    served ≥95% from cache, and both epochs deliver every row."""
    import json

    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    json_out = tmp_path / "bench.jsonl"
    result = service_loopback_scenario(rows=2000, days=4, workers=2,
                                       batch_size=128, epochs=2,
                                       cache="mem",
                                       json_out=str(json_out))
    detail = result["epochs_detail"]
    assert [d["epoch"] for d in detail] == [0, 1]
    assert all(d["rows"] == 2000 for d in detail)
    assert all(d["rows_per_s"] > 0 for d in detail)
    assert detail[1]["cache_hit_rate"] >= 0.95
    assert detail[1]["cache_misses"] == 0
    assert result["cache"]["hits"] == result["cache"]["misses"] == 4
    line = json.loads(json_out.read_text().splitlines()[0])
    assert line["epochs_detail"] == detail


def test_service_scenario_shuffled_cache_hits_and_digest_purity(tmp_path):
    """Tier-1 scale of the ISSUE 9 acceptance: shuffle + cache compose —
    a 2-epoch shuffled run with the worker cache armed hits 100% on the
    warm epoch AND delivers the byte-identical ordered stream of an
    uncached run (the serve-time permutation is pure: cache state never
    changes the bytes or the order), with permuted serves counted."""
    import json

    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    def run(cache):
        return service_loopback_scenario(
            rows=2000, days=4, workers=2, batch_size=128, epochs=2,
            cache=cache, shuffle_seed=7, ordered=True,
            json_out=str(tmp_path / f"bench-{cache}.jsonl"))

    cached = run("mem")
    detail = cached["epochs_detail"]
    assert all(d["rows"] == 2000 for d in detail)
    assert detail[1]["cache_hit_rate"] == 1.0
    assert detail[1]["cache_misses"] == 0
    assert cached["cache"]["permuted_serves"] > 0
    assert cached["duplicates_dropped"] == 0
    uncached = run("off")
    assert cached["stream_digest"] == uncached["stream_digest"]
    line = json.loads(
        (tmp_path / "bench-mem.jsonl").read_text().splitlines()[0])
    assert line["cache"]["permuted_serves"] == \
        cached["cache"]["permuted_serves"]


@pytest.mark.slow
def test_chaos_worker_kill_with_disk_cache_keeps_invariants():
    """Satellite: chaos worker-kill under mem+disk caching — the PR 3
    zero-lost invariant holds (duplicates allowed: at-least-once), and the
    shared disk tier serves hits (the takeover re-serves warm pieces
    without a full re-decode)."""
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    result = service_loopback_scenario(rows=4000, days=4, workers=3,
                                       batch_size=32, epochs=2,
                                       cache="mem+disk",
                                       chaos="worker-kill",
                                       chaos_interval_s=5.0)
    assert result["lost_rows"] == 0
    assert result["chaos_events"], "no chaos event landed inside the run"
    assert result["cache"]["hits"] > 0
