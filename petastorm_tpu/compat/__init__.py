"""Optional-dependency shims.

The reference insulates itself against pyarrow API churn in
``petastorm/compat.py``; this build targets pyarrow>=16 ``pyarrow.dataset``
natively, so the only compat surface left is the optional Spark shim
(:mod:`petastorm_tpu.compat.spark_shim`).
"""
