"""Test-session configuration.

JAX runs on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md §4 "implication for the rebuild").
Env vars must be set before jax is first imported anywhere in the test run.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
