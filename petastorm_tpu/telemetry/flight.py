"""Crash-safe flight recorder: the last N structured events, always on.

Post-mortems of distributed failures die on one question: *what was this
process doing right before it fell over?* Logs answer it only if someone
turned verbosity up BEFORE the crash. The flight recorder answers it
always: every process keeps a small bounded ring of recent structured
events (control RPCs, lifecycle decisions, failpoint fires, invariant
checks), cheap enough to leave armed permanently, and DUMPS the ring to
disk when something goes wrong:

- an invariant violation (the caller dumps explicitly — the loopback
  scenario and the chaos fuzzer do);
- an unhandled exception on any service thread (a chained
  ``threading.excepthook``);
- ``SIGUSR2`` (operator-triggered snapshot of a live, wedged process).

Dumps from different processes of one incident correlate on the fields
the ring carries: every entry is stamped with the process's wall-anchored
trace timestamp (``tracing.wall_us()`` — the same axis the fleet trace
merges on), and callers thread the fencing epoch and batch ids through
``set_context``/``note`` fields, so "which process saw the fence first"
is a sort, not an archaeology dig.

The ring records UNCONDITIONALLY (no arming): a recorder that must be
switched on is a logbook, not a flight recorder. Cost per ``note`` is
one lock + dict build at control-plane rates (per-RPC, per-decision —
never per-row).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading

from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.metrics import FLIGHT_DUMPS, FLIGHT_EVENTS

#: Ring capacity: ~2k control-plane events cover minutes of fleet
#: activity while keeping a dump small enough to attach to a fuzz report.
DEFAULT_CAPACITY = 2048

#: Dump directory override; default is the system temp dir.
DUMP_DIR_ENV = "PETASTORM_FLIGHT_DIR"


class FlightRecorder:
    """Bounded in-memory event ring with on-demand disk dumps."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._events = []
        self._seq = 0  # total notes ever (dump shows how much rolled off)
        self._context = {}
        self._dumps = 0

    def set_context(self, **fields):
        """Merge correlation fields (fencing epoch, role, worker id…)
        into the recorder's context — stamped on every later dump, and
        the cross-process join keys of an incident's dumps. ``None``
        removes a key."""
        with self._lock:
            for key, value in fields.items():
                if value is None:
                    self._context.pop(key, None)
                else:
                    self._context[key] = value

    def note(self, event, **fields):
        """Append one structured event to the ring (always on)."""
        entry = {"t_us": tracing.wall_us(), "event": str(event)}
        entry.update(fields)
        with self._lock:
            self._seq += 1
            self._events.append(entry)
            if len(self._events) > self._capacity:
                self._events.pop(0)
        FLIGHT_EVENTS.inc()

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def dump(self, reason, path=None):
        """Write the ring (plus context) to ``path`` — default
        ``$PETASTORM_FLIGHT_DIR`` or the temp dir, named by pid+reason so
        concurrent processes of one incident never clobber each other.
        Returns the path, or ``None`` if even the dump write failed (a
        recorder must never raise out of a crash path)."""
        with self._lock:
            events = list(self._events)
            context = dict(self._context)
            seq = self._seq
            self._dumps += 1
        doc = {"reason": str(reason), "pid": os.getpid(),
               "context": context, "total_events": seq,
               "events": events}
        if path is None:
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(reason))[:60]
            directory = os.environ.get(DUMP_DIR_ENV) \
                or tempfile.gettempdir()
            path = os.path.join(
                directory, f"flight-{os.getpid()}-{safe}.json")
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=repr)
        except OSError:
            FLIGHT_DUMPS.labels("write_failed").inc()
            return None
        FLIGHT_DUMPS.labels(str(reason).split(":", 1)[0][:40] or
                            "unspecified").inc()
        return path


#: The process-default recorder every service component notes into.
RECORDER = FlightRecorder()

_installed = False
_prev_excepthook = None


def _thread_excepthook(hook_args):
    """Chained ``threading.excepthook``: an unhandled exception on ANY
    thread dumps the ring (the crash's own postmortem), then defers to
    the previously-installed hook (default: traceback to stderr)."""
    exc_type = getattr(hook_args, "exc_type", None)
    name = getattr(getattr(hook_args, "thread", None), "name", "?")
    RECORDER.note("unhandled_thread_exception", thread=name,
                  error=(exc_type.__name__ if exc_type else "?"))
    RECORDER.dump(f"thread-crash:{name}")
    if _prev_excepthook is not None:
        _prev_excepthook(hook_args)


def _sigusr2_handler(signum, frame):
    path = RECORDER.dump("sigusr2")
    print(f"flight recorder dump: {path}", file=sys.stderr, flush=True)


def install(capture_signals=True):
    """Arm the crash hooks: chain ``threading.excepthook`` and (from the
    main thread, when asked) a ``SIGUSR2`` dump handler. Idempotent;
    signal installation failures (non-main thread, restricted env) are
    tolerated — the excepthook and explicit dumps still work."""
    global _installed, _prev_excepthook
    if _installed:
        return RECORDER
    _prev_excepthook = threading.excepthook
    threading.excepthook = _thread_excepthook
    if capture_signals:
        try:
            signal.signal(signal.SIGUSR2, _sigusr2_handler)
        except (ValueError, OSError, AttributeError):
            pass  # non-main thread / no SIGUSR2 on this platform
    _installed = True
    return RECORDER
