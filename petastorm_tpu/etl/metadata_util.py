"""Inspect a petastorm dataset's metadata: schema, row groups, indexes.

Reference parity: ``petastorm/etl/metadata_util.py`` (argparse inspector).
"""

from __future__ import annotations

import argparse
import sys

from petastorm_tpu.errors import PetastormMetadataError
from petastorm_tpu.fs_utils import FilesystemResolver


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Print schema / row-group / index info for a dataset")
    parser.add_argument("dataset_url")
    parser.add_argument("--schema", action="store_true",
                        help="print the Unischema fields")
    parser.add_argument("--index", action="store_true",
                        help="print rowgroup index summary")
    parser.add_argument("--print-values", action="store_true",
                        help="with --index: print indexed values")
    parser.add_argument("--skip-index", nargs="*", default=[],
                        help="index names to omit")
    args = parser.parse_args(argv)

    resolver = FilesystemResolver(args.dataset_url)
    fs = resolver.filesystem()
    path = resolver.get_dataset_path()

    from petastorm_tpu.etl import metadata as etl_metadata

    pieces = etl_metadata.load_row_groups(fs, path)
    files = {p.path for p in pieces}
    counts = [p.num_rows for p in pieces]
    rows = sum(counts) if all(c is not None for c in counts) else "unknown"
    print(f"Dataset: {args.dataset_url}")
    print(f"Files: {len(files)}  Row groups: {len(pieces)}  Rows: {rows}")

    if args.schema:
        try:
            schema = etl_metadata.get_schema(fs, path)
            print(f"\nUnischema: {schema._name}")
            for name, field in schema.fields.items():
                print(f"  {name}: dtype={field.numpy_dtype}, "
                      f"shape={field.shape}, codec={type(field.codec).__name__ if field.codec else None}, "
                      f"nullable={field.nullable}")
        except PetastormMetadataError as exc:
            print(f"\nNo Unischema metadata: {exc}")

    if args.index:
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes

        try:
            indexes = get_row_group_indexes(fs, path)
        except PetastormMetadataError as exc:
            print(f"\nNo rowgroup index: {exc}")
            return 0
        print("\nRowgroup indexes:")
        for name, indexer in indexes.items():
            if name in args.skip_index:
                continue
            print(f"  {name}: columns={indexer.column_names}, "
                  f"values={len(indexer.indexed_values)}")
            if args.print_values:
                for value in indexer.indexed_values:
                    print(f"    {value!r} -> "
                          f"{sorted(indexer.get_row_group_indexes(value))}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
