"""Pallas TPU flash attention: tiled online-softmax attention in VMEM.

The hot op of the sequence model (``models/sequence_model.py`` — NGram
``[B, T, H, D]`` windows). The reference has no accelerator code; this is
the TPU-native answer to "where do the FLOPs go": Q/K/V tiles stream
HBM → VMEM block by block, scores hit the MXU per tile
(``preferred_element_type=f32``), and the online softmax keeps running
``(max, sum, acc)`` statistics in VMEM scratch so the [T, T] score matrix is
NEVER materialized — memory O(block_q × block_k) instead of O(T²).

Layout/tiling choices (pallas_guide.md):

- grid = (batch·heads, Tq/block_q, Tk/block_k) — the last axis iterates
  innermost and sequentially on TPU, which is what makes scratch
  accumulation across K blocks valid;
- softmax statistics live in ``(block_q, 128)`` f32 scratch (lane-broadcast:
  min tile is 8×128, a [block_q]-vector would not tile);
- block sizes default to 128 to match the MXU's 128×128 systolic array; the
  head dim should be a multiple of 128 for full MXU rate (Mosaic pads
  smaller dims at reduced efficiency);
- sequence lengths that don't divide the block are zero-padded in the
  wrapper and masked to -inf inside the kernel via a 2D
  ``broadcasted_iota`` (1D iota does not lower on TPU).

Backward: hand-tiled flash-2 style ``jax.custom_vjp`` — the forward emits
the per-row log-sum-exp as a residual, and two Pallas kernels recompute the
probabilities per (Q-block, K-block) tile from (q, k, lse): one sweep
accumulates dQ over K blocks, the other accumulates dK/dV over Q blocks.
Like the forward, no kernel ever materializes the [T, T] score matrix, so
training memory is O(block_q × block_k) + O(T·D) residuals — not O(T²).
The pre-round-4 recompute-through-the-reference backward is kept as a
correctness oracle behind ``bwd_impl="reference"``.

Off-TPU (tests, CPU dev) the kernel runs in interpret mode; the Mosaic
lowering is exercised on real TPU by the driver benchmark's flash legs
(``bench.py`` ``flash_numerics``: forward + backward for causal /
kv_lengths / segment_ids / with_lse vs a float64 dense oracle, and
``flash_memsweep``: the O(block²)-vs-O(T²) training-memory claim as
measured OOM ceilings — ``BENCH_r05.json`` ``flash_kernel``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128  # TPU lane width: scratch min-tile last dim


def _attention_reference(q, k, v, causal=False):
    """Unfused oracle over ``[B, T, H, D]`` (same numerics contract as the
    kernel); used by the recompute backward."""
    scale = 1.0 / jnp.sqrt(jnp.array(q.shape[-1], jnp.float32))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    row_valid = None
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        row = jnp.arange(t_q)[:, None] + (t_k - t_q)  # align last positions
        mask = jnp.arange(t_k)[None, :] <= row
        # Rows with no valid key (t_q > t_kv suffix alignment) must produce
        # ZERO output, nan-free in both forward and vjp: substitute finite
        # scores for those rows, then zero their probabilities.
        row_valid = mask.any(axis=-1, keepdims=True)
        scores = jnp.where(mask, scores, -jnp.inf)
        scores = jnp.where(row_valid, scores, 0.0)
    probs = jax.nn.softmax(scores, axis=-1)
    if row_valid is not None:
        probs = jnp.where(row_valid, probs, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _dot_precision(dtype):
    """MXU multiply precision: f32 inputs get the full-precision passes
    (DEFAULT is single-pass bf16 — ~1e-2 relative error that softmax's exp
    amplifies); bf16 inputs are exact at DEFAULT (they started as bf16)."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _kv_limit(lens_ref, kv_len):
    """Effective key-count bound for the column mask: the static padded-KV
    bound, or — with per-example lengths — this batch·head's dynamic count
    read as an SMEM scalar (broadcasts against the (block_q, block_k) ids
    exactly like the static int)."""
    if lens_ref is None:
        return kv_len
    from jax.experimental import pallas as pl

    return jnp.minimum(lens_ref[pl.program_id(0)], kv_len)


def _flash_kernel(*refs, sm_scale, block_q, block_k, kv_len, causal_offset,
                  emit_lse, has_lens, has_segs, precision):
    from jax.experimental import pallas as pl

    if has_lens:
        q_ref, k_ref, v_ref, lens_ref = refs[:4]
        rest = refs[4:]
    else:
        q_ref, k_ref, v_ref = refs[:3]
        lens_ref = None
        rest = refs[3:]
    if has_segs:
        qseg_ref, kvseg_ref = rest[:2]
        rest = rest[2:]
    else:
        qseg_ref = kvseg_ref = None
    o_ref = rest[0]
    rest = rest[1:]
    if emit_lse:
        lse_ref, m_scratch, l_scratch, acc_scratch = rest
    else:
        lse_ref = None
        m_scratch, l_scratch, acc_scratch = rest

    qb = pl.program_id(1)
    kb = pl.program_id(2)
    last_kb = pl.num_programs(2) - 1
    # Read outside the pl.when wrapper: program_id inside a when-body does
    # not lower in interpret mode.
    kv_limit = _kv_limit(lens_ref, kv_len)

    @pl.when(kb == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    def compute_block():
        q = q_ref[0].astype(jnp.float32)          # [block_q, d]
        k = k_ref[0].astype(jnp.float32)          # [block_k, d]
        v = v_ref[0].astype(jnp.float32)

        s = _masked_scores(q, k, kb, qb, sm_scale=sm_scale, block_q=block_q,
                           block_k=block_k, kv_len=kv_limit,
                           causal_offset=causal_offset,
                           precision=precision,
                           q_seg=None if qseg_ref is None else qseg_ref[0],
                           kv_seg=(None if kvseg_ref is None
                                   else kvseg_ref[0, :1]))

        m_prev = m_scratch[...][:, :1]            # [block_q, 1]
        l_prev = l_scratch[...][:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        # A row can be fully masked in this block (causal + partial-overlap
        # K blocks): m_new stays -inf and the raw exponent would be
        # (-inf) - (-inf) = nan.
        fully_masked = m_new == -jnp.inf
        m_safe = jnp.where(fully_masked, 0.0, m_new)
        alpha = jnp.where(fully_masked, 1.0, jnp.exp(m_prev - m_safe))
        p = jnp.exp(s - m_safe)               # [block_q, block_k]; -inf -> 0
        l_new = alpha * l_prev + p.sum(axis=1, keepdims=True)

        acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
                                 precision=precision)
        m_scratch[...] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[...] = jnp.broadcast_to(l_new, l_scratch.shape)

    if causal_offset is None:
        compute_block()
    else:
        # Skip K blocks that lie entirely above the causal boundary for this
        # Q block (the grid's last axis runs sequentially, so scratch state
        # carries across the skipped steps) — ~2x compute saved at large T.
        last_valid_col = qb * block_q + causal_offset + block_q - 1
        pl.when(kb * block_k <= last_valid_col)(compute_block)

    @pl.when(kb == last_kb)
    def _emit():
        l = l_scratch[...][:, :1]
        o_ref[0] = (acc_scratch[...] / jnp.maximum(l, 1e-30)) \
            .astype(o_ref.dtype)
        if lse_ref is not None:
            # Per-row log-sum-exp residual for the flash backward. Rows with
            # no valid key (causal cross-length) have l == 0: +inf makes the
            # backward's exp(s - lse) an exact zero with no inf-inf nan.
            lf = l_scratch[...]
            lse_ref[0] = jnp.where(
                lf > 0.0,
                m_scratch[...] + jnp.log(jnp.maximum(lf, 1e-37)),
                jnp.inf)


def _to_bh(x):
    """[B, T, H, D] → [B·H, T, D] (attention is independent per batch·head)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bh(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _pad_t(x, block):
    pad = (-x.shape[1]) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _lens_to_bh(kv_lengths, b, h):
    """[B] int lengths → per-batch·head [B·H] int32 (bh index is
    batch-major, matching :func:`_to_bh`); consumed as SMEM scalars."""
    return jnp.repeat(kv_lengths.astype(jnp.int32), h)


def _lens_spec(pl, pltpu, n_bh):
    # The whole [B·H] vector in SMEM every step (rank-1 blocks must be the
    # full array); the kernel indexes it with program_id(0). A scalar read
    # broadcasts natively in the comparison against the id tiles (a VMEM
    # (1, 1) tile would need a both-axes broadcast Mosaic doesn't
    # implement).
    return pl.BlockSpec((n_bh,), lambda bh, i, j: (0,),
                        memory_space=pltpu.SMEM)


_SUBLANES = 8  # TPU sublane width: kv-segment-id second-to-last dim


def _pad_seg_row(segment_ids, block):
    """[B, T] int segment ids → [B, T_padded] int32. The pad value is
    irrelevant to masking (padded KV columns die on the kv_len mask, padded
    Q rows are sliced off), it only has to exist."""
    seg = jnp.asarray(segment_ids, jnp.int32)
    pad = (-seg.shape[1]) % block
    if pad:
        seg = jnp.pad(seg, ((0, 0), (0, pad)), constant_values=-1)
    return seg


def _split_segment_ids(segment_ids):
    """``segment_ids`` is one [B, T] array (self-attention over a packed
    batch) or a ``(q_ids [B, Tq], kv_ids [B, Tkv])`` pair (the flash ring:
    resident K/V blocks carry their own ids)."""
    if isinstance(segment_ids, (tuple, list)):
        q_ids, kv_ids = segment_ids
        return q_ids, kv_ids
    return segment_ids, segment_ids


def _check_segment_ids(segment_ids, t_q, t_kv):
    """Per-side length validation for both segment-id forms — a mismatched
    array would silently mis-mask (ids sliced/padded against the wrong
    positions), so it must raise instead."""
    if isinstance(segment_ids, (tuple, list)):
        q_ids, kv_ids = segment_ids
        for name, ids in (("q_ids", q_ids), ("kv_ids", kv_ids)):
            if len(jnp.shape(ids)) != 2:
                raise ValueError(
                    f"segment_ids {name} must be [B, T] (batch axis "
                    f"included), got shape {jnp.shape(ids)}")
        if jnp.shape(q_ids)[1] != t_q or jnp.shape(kv_ids)[1] != t_kv:
            raise ValueError(
                f"segment_ids pair shapes {jnp.shape(q_ids)} / "
                f"{jnp.shape(kv_ids)} do not match T_q={t_q} / "
                f"T_kv={t_kv} (is the (q_ids, kv_ids) order swapped?)")
    else:
        if len(jnp.shape(segment_ids)) != 2:
            raise ValueError(
                f"segment_ids must be [B, T] (batch axis included — "
                f"per-token ids alone are ambiguous across the batch), "
                f"got shape {jnp.shape(segment_ids)}")
        if t_q != t_kv:
            raise ValueError(
                f"a single segment_ids array requires T_q == T_kv "
                f"(self-attention over a packed batch), got {t_q} vs "
                f"{t_kv}; pass a (q_ids, kv_ids) pair for cross-length "
                "attention")
        if jnp.shape(segment_ids)[1] != t_q:
            raise ValueError(
                f"segment_ids shape {jnp.shape(segment_ids)} does not "
                f"match the sequence length T={t_q}")


def _q_segs_arr(segment_ids, block_q):
    """[B, T] → lane-broadcast [B, Tq_pad, 128]: a (block_q, 128) tile
    satisfies the TPU min-tile rule where a (1, block_q) row would not."""
    seg = _pad_seg_row(segment_ids, block_q)
    return jax.lax.broadcast_in_dim(
        seg, (seg.shape[0], seg.shape[1], _LANES), (0, 1))


def _kv_segs_arr(segment_ids, block_k):
    """[B, T] → sublane-broadcast [B, 8, Tkv_pad]: an (8, block_k) tile
    keeps the ids on the LANE axis, where the kernel compares them against
    the lane-major score columns without a transpose."""
    seg = _pad_seg_row(segment_ids, block_k)
    return jax.lax.broadcast_in_dim(
        seg, (seg.shape[0], _SUBLANES, seg.shape[1]), (0, 2))


def _q_seg_spec(pl, pltpu, h, block_q, q_block_of):
    """Tile of the lane-broadcast q segment ids; the batch coordinate is
    bh // h (ids are per batch, the grid is per batch·head) and the token
    block must ride the same (possibly clamped) fetch as its Q tile."""
    return pl.BlockSpec(
        (1, block_q, _LANES),
        lambda bh, i, j: (bh // h, q_block_of(i, j), 0),
        memory_space=pltpu.VMEM)


def _kv_seg_spec(pl, pltpu, h, block_k, kv_block_of):
    return pl.BlockSpec(
        (1, _SUBLANES, block_k),
        lambda bh, i, j: (bh // h, 0, kv_block_of(i, j)),
        memory_space=pltpu.VMEM)


def _kv_bh_map(h, h_kv):
    """Grid-coordinate map for grouped-query attention: the grid iterates
    q-heads (``bh = b·h + hq``), and each group of ``h // h_kv`` q-heads
    reads the SAME K/V head — the map lands their fetches on its flattened
    coordinate ``b·h_kv + hq // group``. Identity when ``h == h_kv``
    (the arithmetic reduces to ``bh``), so one code path serves both."""
    group = h // h_kv

    def kv_bh(bh):
        return (bh // h) * h_kv + (bh % h) // group

    return kv_bh


def _check_gqa_heads(q, k, v, bwd_impl=None):
    """Validate the grouped-query head contract: K and V share a head
    count that divides Q's. Returns ``(h, h_kv)``."""
    h, h_kv = q.shape[2], k.shape[2]
    if v.shape[2] != h_kv:
        raise ValueError(
            f"k has {h_kv} heads but v has {v.shape[2]}; K and V must "
            "share their (possibly grouped) head count")
    if h % h_kv:
        raise ValueError(
            f"{h} query heads do not group over {h_kv} K/V heads "
            "(grouped-query attention requires h % h_kv == 0)")
    if bwd_impl == "reference" and h_kv != h:
        raise NotImplementedError(
            "bwd_impl='reference' does not support grouped-query K/V "
            "(the dense oracle is single-ratio); repeat K/V to the query "
            "head count for the oracle, or use bwd_impl='flash'")
    return h, h_kv


def _group_sum_kv_grad(grad_bh, b, h, h_kv, t_kv):
    """Per-q-head dK/dV partials ``[B·H, Tk_pad, D]`` → ``[B, Tk, h_kv,
    D]``: each K/V head's gradient is the sum over its q-head group
    (f32 accumulation — a bf16 group-sum would round between partials).
    The ungrouped path short-circuits to the plain reshape/transpose so
    standard MHA backward keeps its exact pre-GQA form (no f32 transient
    at the memory-sweep ceiling)."""
    if h == h_kv:
        return _from_bh(grad_bh[:, :t_kv], b, h)
    d = grad_bh.shape[-1]
    g = grad_bh[:, :t_kv].reshape(b, h_kv, h // h_kv, t_kv, d)
    g = g.astype(jnp.float32).sum(axis=2)
    return g.transpose(0, 2, 1, 3).astype(grad_bh.dtype)


def _check_seg_blocks(block_k):
    if block_k > _LANES and block_k % _LANES:
        raise ValueError(
            f"segment_ids requires block_k <= {_LANES} or a multiple of "
            f"{_LANES} (the lane-tiled id compare), got {block_k}")


def _flash_forward(q, k, v, block_q, block_k, interpret, causal=False,
                   return_residuals=False, kv_lengths=None,
                   segment_ids=None, causal_shift=0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_dtype = q.dtype
    b, t_q, h, d = q.shape
    t_kv = k.shape[1]
    h_kv = k.shape[2]
    kv_bh = _kv_bh_map(h, h_kv)

    qf = _pad_t(_to_bh(q), block_q)
    kf = _pad_t(_to_bh(k), block_k)
    vf = _pad_t(_to_bh(v), block_k)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    # causal_shift slides the diagonal: -1 = strict causal (k strictly
    # before q) — the striped-ring blocks where the key shard sits "after"
    # the query shard in the interleaved global order.
    causal_offset = (t_kv - t_q + causal_shift) if causal else None
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=1.0 / float(d) ** 0.5,
        block_q=block_q,
        block_k=block_k,
        kv_len=t_kv,
        # Align the LAST query with the LAST key (suffix-query convention).
        causal_offset=causal_offset,
        emit_lse=return_residuals,
        has_lens=kv_lengths is not None,
        has_segs=segment_ids is not None,
        precision=_dot_precision(orig_dtype),
    )
    if causal_offset is None:
        kv_block = lambda i, j: j  # noqa: E731
    else:
        def kv_block(i, j):
            # Clamp skipped (fully-above-causal-boundary) K/V fetches to the
            # last USEFUL block for this Q block: pl.when skips their
            # compute, and an unchanged block index lets the pipeline skip
            # the HBM->VMEM copy too — the skip saves bandwidth, not just
            # MXU time.
            last = (i * block_q + causal_offset + block_q - 1) // block_k
            return jnp.minimum(j, jnp.maximum(last, 0))

    kv_index = lambda bh, i, j: (kv_bh(bh), kv_block(i, j), 0)  # noqa: E731
    q_index = lambda bh, i, j: (bh, i, 0)  # noqa: E731
    out_shape = jax.ShapeDtypeStruct((b * h, tq_p, d), orig_dtype)
    out_specs = pl.BlockSpec((1, block_q, d), q_index,
                             memory_space=pltpu.VMEM)
    if return_residuals:
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((b * h, tq_p, _LANES), jnp.float32))
        out_specs = (out_specs,
                     pl.BlockSpec((1, block_q, _LANES), q_index,
                                  memory_space=pltpu.VMEM))

    in_specs = [
        pl.BlockSpec((1, block_q, d), q_index,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index,
                     memory_space=pltpu.VMEM),
    ]
    inputs = [qf, kf, vf]
    if kv_lengths is not None:
        in_specs.append(_lens_spec(pl, pltpu, b * h))
        inputs.append(_lens_to_bh(kv_lengths, b, h))
    if segment_ids is not None:
        _check_seg_blocks(block_k)
        q_ids, kv_ids = _split_segment_ids(segment_ids)
        in_specs.append(_q_seg_spec(pl, pltpu, h, block_q,
                                    lambda i, j: i))
        in_specs.append(_kv_seg_spec(pl, pltpu, h, block_k, kv_block))
        inputs.extend([_q_segs_arr(q_ids, block_q),
                       _kv_segs_arr(kv_ids, block_k)])

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(*inputs)

    if return_residuals:
        out_padded, lse = out
        # lse is lane-broadcast (all 128 lanes equal); store one column.
        return out_padded, lse[:, :, 0]
    return _from_bh(out[:, :t_q, :], b, h)


def _masked_scores(q, k, kb, qb, *, sm_scale, block_q, block_k, kv_len,
                   causal_offset, precision, q_seg=None, kv_seg=None):
    """Recompute the masked score tile s = mask(scale·q kᵀ) for one
    (Q-block, K-block) pair — shared by both backward kernels; identical
    masking semantics to the forward kernel. ``q_seg``/``kv_seg``:
    optional ``(1, block)`` int32 segment-id tiles — positions in different
    segments (packed sequences) never attend to each other."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                                 precision=precision) * sm_scale
    col_ids = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    s = jnp.where(col_ids < kv_len, s, -jnp.inf)
    if causal_offset is not None:
        row_ids = (qb * block_q + causal_offset
                   + jax.lax.broadcasted_iota(jnp.int32, s.shape,
                                              dimension=0))
        s = jnp.where(col_ids <= row_ids, s, -jnp.inf)
    if q_seg is not None:
        # q_seg: [block_q, 128] lane-broadcast; kv_seg: [1, block_k]. Slice
        # or lane-tile q's ids to block_k columns, then a broadcast compare
        # yields the [block_q, block_k] same-segment mask (upstream TPU
        # flash-attention idiom — no transpose, MXU-friendly layouts).
        lanes = q_seg.shape[1]
        if block_k <= lanes:
            qs = q_seg[:, :block_k]
        else:
            qs = jnp.tile(q_seg, (1, block_k // lanes))
        s = jnp.where(qs == kv_seg, s, -jnp.inf)
    return s


def _split_bwd_refs(refs, has_lens, has_segs):
    """Unpack a backward kernel's refs: 6 fixed inputs (q, k, v, do, o,
    lse), then the optional lens / segment-id inputs, then outputs+scratch."""
    q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref = refs[:6]
    idx = 6
    lens_ref = None
    if has_lens:
        lens_ref = refs[idx]
        idx += 1
    qseg_ref = kvseg_ref = None
    if has_segs:
        qseg_ref, kvseg_ref = refs[idx:idx + 2]
        idx += 2
    return (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, lens_ref,
            qseg_ref, kvseg_ref, refs[idx:])


def _flash_bwd_dq_kernel(*refs, sm_scale, block_q, block_k, kv_len,
                         causal_offset, has_lens, has_segs, has_dlse,
                         precision):
    """dQ sweep: grid (B·H, Tq/block_q, Tk/block_k) — K blocks iterate
    innermost, dq accumulates in VMEM scratch. Per tile:
    p = exp(s - lse); ds = p·(do·vᵀ - Δ [+ dlse])·scale; dq += ds·k, with
    Δ = rowsum(do ∘ o) recomputed from the residuals (O(block·d), cheaper
    than staging a third stats tensor). ``dlse`` is the cotangent of the
    emitted log-sum-exp when the caller consumed it (ring merging):
    ∂lse_i/∂s_ij = p_ij, so it adds inside the parenthesis."""
    from jax.experimental import pallas as pl

    (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, lens_ref, qseg_ref,
     kvseg_ref, rest) = _split_bwd_refs(refs, has_lens, has_segs)
    if has_dlse:
        dlse_ref = rest[0]
        rest = rest[1:]
    else:
        dlse_ref = None
    dq_ref, dq_acc = rest
    kv_len = _kv_limit(lens_ref, kv_len)

    qb = pl.program_id(1)
    kb = pl.program_id(2)
    last_kb = pl.num_programs(2) - 1

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute_block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)

        s = _masked_scores(q, k, kb, qb, sm_scale=sm_scale, block_q=block_q,
                           block_k=block_k, kv_len=kv_len,
                           causal_offset=causal_offset,
                           precision=precision,
                           q_seg=None if qseg_ref is None else qseg_ref[0],
                           kv_seg=(None if kvseg_ref is None
                                   else kvseg_ref[0, :1]))
        # lse is +inf for rows with no valid key, so every term is an exact
        # zero (finite-or-(-inf) minus +inf → -inf → exp 0; never inf-inf).
        p = jnp.exp(s - lse_ref[0][:, :1])
        delta = (do * o).sum(axis=1, keepdims=True)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=precision)
        inner = dp - delta
        if dlse_ref is not None:
            inner = inner + dlse_ref[0][:, :1]
        ds = p * inner * sm_scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
                                 precision=precision)

    if causal_offset is None:
        compute_block()
    else:
        last_valid_col = qb * block_q + causal_offset + block_q - 1
        pl.when(kb * block_k <= last_valid_col)(compute_block)

    @pl.when(kb == last_kb)
    def _emit():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, sm_scale, block_q, block_k, kv_len,
                          causal_offset, has_lens, has_segs, has_dlse,
                          precision):
    """dK/dV sweep: grid (B·H, Tk/block_k, Tq/block_q) — Q blocks iterate
    innermost, dk/dv accumulate in VMEM scratch. Per tile:
    dv += pᵀ·do; dk += dsᵀ·q (same recomputed p/ds as the dQ sweep,
    including the optional dlse term)."""
    from jax.experimental import pallas as pl

    (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, lens_ref, qseg_ref,
     kvseg_ref, rest) = _split_bwd_refs(refs, has_lens, has_segs)
    if has_dlse:
        dlse_ref = rest[0]
        rest = rest[1:]
    else:
        dlse_ref = None
    dk_ref, dv_ref, dk_acc, dv_acc = rest
    kv_len = _kv_limit(lens_ref, kv_len)

    kb = pl.program_id(1)
    qb = pl.program_id(2)
    last_qb = pl.num_programs(2) - 1

    @pl.when(qb == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute_block():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)

        s = _masked_scores(q, k, kb, qb, sm_scale=sm_scale, block_q=block_q,
                           block_k=block_k, kv_len=kv_len,
                           causal_offset=causal_offset,
                           precision=precision,
                           q_seg=None if qseg_ref is None else qseg_ref[0],
                           kv_seg=(None if kvseg_ref is None
                                   else kvseg_ref[0, :1]))
        p = jnp.exp(s - lse_ref[0][:, :1])
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
                                 precision=precision)
        delta = (do * o).sum(axis=1, keepdims=True)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=precision)
        inner = dp - delta
        if dlse_ref is not None:
            inner = inner + dlse_ref[0][:, :1]
        ds = p * inner * sm_scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
                                 precision=precision)

    if causal_offset is None:
        compute_block()
    else:
        # Q block qb touches K block kb iff its causal boundary reaches it.
        last_valid_col = qb * block_q + causal_offset + block_q - 1
        pl.when(last_valid_col >= kb * block_k)(compute_block)

    @pl.when(qb == last_qb)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o_padded, lse, g, block_q, block_k, interpret,
                    causal, kv_lengths=None, segment_ids=None,
                    causal_shift=0, dlse=None):
    """Flash-2 backward: two pallas sweeps, O(block²) VMEM, no [T, T]
    buffer. ``o_padded``/``lse`` are [B·H, Tq_padded(, )] residuals from the
    forward; q/k/v are the user-shaped [B, T, H, D] primals."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t_q, h, d = q.shape
    t_kv = k.shape[1]
    h_kv = k.shape[2]
    kv_bh = _kv_bh_map(h, h_kv)

    qf = _pad_t(_to_bh(q), block_q)
    kf = _pad_t(_to_bh(k), block_k)
    vf = _pad_t(_to_bh(v), block_k)
    dof = _pad_t(_to_bh(g), block_q)
    tq_p, tk_p = qf.shape[1], kf.shape[1]
    n_qb, n_kb = tq_p // block_q, tk_p // block_k

    # Rebroadcast the stored lse column across the lane dim so backward
    # loads see the same Mosaic-friendly (block_q, 128) layout the forward
    # scratch used (a [block_q]-vector would not tile).
    lse_b = jnp.broadcast_to(lse[:, :, None], (b * h, tq_p, _LANES))

    lens_inputs, lens_specs = [], []
    if kv_lengths is not None:
        lens_inputs = [_lens_to_bh(kv_lengths, b, h)]
        lens_specs = [_lens_spec(pl, pltpu, b * h)]
    seg_inputs = []
    if segment_ids is not None:
        _check_seg_blocks(block_k)
        q_ids, kv_ids = _split_segment_ids(segment_ids)
        seg_inputs = [_q_segs_arr(q_ids, block_q),
                      _kv_segs_arr(kv_ids, block_k)]
    dlse_inputs = []
    if dlse is not None:
        # The lse cotangent, lane-broadcast like the lse residual itself
        # ([B·H, Tq_pad] from the vjp wrapper).
        dlse_inputs = [jnp.broadcast_to(dlse[:, :, None],
                                        (b * h, tq_p, _LANES))]

    causal_offset = (t_kv - t_q + causal_shift) if causal else None
    common = dict(sm_scale=1.0 / float(d) ** 0.5, block_q=block_q,
                  block_k=block_k, kv_len=t_kv, causal_offset=causal_offset,
                  has_lens=kv_lengths is not None,
                  has_segs=segment_ids is not None,
                  has_dlse=dlse is not None,
                  precision=_dot_precision(q.dtype))

    q_spec = lambda ix: pl.BlockSpec((1, block_q, d), ix,  # noqa: E731
                                     memory_space=pltpu.VMEM)
    kv_spec = lambda ix: pl.BlockSpec((1, block_k, d), ix,  # noqa: E731
                                      memory_space=pltpu.VMEM)

    # --- dQ sweep: (bh, qb, kb), K innermost --------------------------------
    dq_q_index = lambda bh, i, j: (bh, i, 0)  # noqa: E731
    if causal_offset is None:
        dq_kv_block = lambda i, j: j  # noqa: E731
    else:
        def dq_kv_block(i, j):
            # Clamp fetches of skipped (fully-future) K/V blocks, exactly as
            # in the forward, so the pipeline skips the copy too.
            last = (i * block_q + causal_offset + block_q - 1) // block_k
            return jnp.minimum(j, jnp.maximum(last, 0))

    dq_kv_index = \
        lambda bh, i, j: (kv_bh(bh), dq_kv_block(i, j), 0)  # noqa: E731
    dq_stats_spec = pl.BlockSpec((1, block_q, _LANES), dq_q_index,
                                 memory_space=pltpu.VMEM)
    dq_seg_specs = []
    if segment_ids is not None:
        dq_seg_specs = [_q_seg_spec(pl, pltpu, h, block_q,
                                    lambda i, j: i),
                        _kv_seg_spec(pl, pltpu, h, block_k, dq_kv_block)]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(b * h, n_qb, n_kb),
        in_specs=[
            q_spec(dq_q_index),
            kv_spec(dq_kv_index),
            kv_spec(dq_kv_index),
            q_spec(dq_q_index),                      # do
            q_spec(dq_q_index),                      # o
            dq_stats_spec,                           # lse
        ] + lens_specs + dq_seg_specs + (
            # dlse must ride the EXACT same fetch as lse (same Q block).
            [dq_stats_spec] if dlse is not None else []),
        out_specs=q_spec(dq_q_index),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, o_padded, lse_b, *lens_inputs, *seg_inputs,
      *dlse_inputs)

    # --- dK/dV sweep: (bh, kb, qb), Q innermost -----------------------------
    # The grid stays per Q-HEAD: each grid row reads its group's shared K/V
    # block (kv_bh-mapped INPUT fetch) but writes its OWN per-q-head dk/dv
    # partial (un-mapped OUTPUT index) — grouped heads writing one output
    # block from different grid rows would race; the wrapper group-sums the
    # partials instead.
    dkv_kv_in_index = lambda bh, i, j: (kv_bh(bh), i, 0)  # noqa: E731
    dkv_kv_index = lambda bh, i, j: (bh, i, 0)  # noqa: E731
    if causal_offset is None:
        dkv_q_block = lambda i, j: j  # noqa: E731
    else:
        def dkv_q_block(i, j):
            # First Q block whose causal boundary reaches K block i; clamp
            # skipped earlier-Q fetches to it (ceil with floor-division).
            first = -((causal_offset + block_q - 1 - i * block_k) // block_q)
            first = jnp.clip(first, 0, n_qb - 1)
            return jnp.maximum(j, first)

    dkv_q_index = lambda bh, i, j: (bh, dkv_q_block(i, j), 0)  # noqa: E731
    dkv_stats_spec = pl.BlockSpec((1, block_q, _LANES), dkv_q_index,
                                  memory_space=pltpu.VMEM)
    dkv_seg_specs = []
    if segment_ids is not None:
        dkv_seg_specs = [_q_seg_spec(pl, pltpu, h, block_q, dkv_q_block),
                         _kv_seg_spec(pl, pltpu, h, block_k,
                                      lambda i, j: i)]

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(b * h, n_kb, n_qb),
        in_specs=[
            q_spec(dkv_q_index),
            kv_spec(dkv_kv_in_index),
            kv_spec(dkv_kv_in_index),
            q_spec(dkv_q_index),                     # do
            q_spec(dkv_q_index),                     # o
            dkv_stats_spec,                          # lse
        ] + lens_specs + dkv_seg_specs + (
            # dlse must ride the EXACT same fetch as lse (same Q block).
            [dkv_stats_spec] if dlse is not None else []),
        out_specs=(kv_spec(dkv_kv_index), kv_spec(dkv_kv_index)),
        out_shape=(jax.ShapeDtypeStruct((b * h, tk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, tk_p, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, o_padded, lse_b, *lens_inputs, *seg_inputs,
      *dlse_inputs)

    dq = _from_bh(dq[:, :t_q], b, h)
    dk = _group_sum_kv_grad(dk, b, h, h_kv, t_kv)
    dv = _group_sum_kv_grad(dv, b, h, h_kv, t_kv)
    return dq, dk, dv


def _should_interpret():
    """Mosaic lowering on real TPU; interpreter elsewhere (CPU tests)."""
    return jax.default_backend() != "tpu"


# Length-aware block_k default, measured on v5e (alternating A/B, fwd+bwd
# at bf16): 512 beats 128 by ~1.05x at T=8192 and ~1.35x at T=16384 — 4x
# fewer K-grid steps amortize the per-block revisit overhead — while 128
# stays right below the threshold (min-tile padding waste, and short-T
# shapes often don't divide 512).
_LONG_T_BLOCK_K = 512
_LONG_T_THRESHOLD = 4096


def _default_blocks(t_kv, block_q, block_k):
    """Resolve ``None`` block sizes (the public wrappers call this BEFORE
    the custom_vjp captures them, so forward and backward always agree)."""
    if block_q is None:
        block_q = 128
    if block_k is None:
        block_k = (_LONG_T_BLOCK_K if t_kv >= _LONG_T_THRESHOLD
                   else 128)
    return block_q, block_k


def flash_attention(q, k, v, block_q=None, block_k=None, interpret=None,
                    causal=False, bwd_impl="flash", kv_lengths=None,
                    segment_ids=None):
    """Tiled attention over ``[B, T, H, D]`` tensors; matches
    ``attention_reference`` numerics (f32 softmax) without materializing the
    ``[T, T]`` score matrix — in the forward OR the backward.

    :param block_q / block_k: VMEM tile sizes (``None`` = auto: 128, with
        ``block_k`` rising to 512 once ``T_kv`` reaches 4096 — measured
        faster on v5e at long T; see ``_default_blocks``).
    :param interpret: force the pallas interpreter (None = auto: interpret
        off-TPU, Mosaic on TPU).
    :param causal: mask key positions after each query's (last-aligned)
        position — decoder-style attention.
    :param bwd_impl: ``"flash"`` (hand-tiled dq + dk/dv Pallas sweeps,
        O(block²) memory) or ``"reference"`` (XLA autodiff through the dense
        oracle — materializes [T, T] in the backward; kept for debugging and
        as the numerics oracle).
    :param kv_lengths: optional per-example valid key counts [B] (int) —
        keys at or past ``kv_lengths[b]`` are masked out for example ``b``
        (ragged NGram windows padded to a common T). With ``causal``, the
        causal alignment still uses the STATIC T_q/T_kv shapes.
    :param segment_ids: optional int ids for PACKED batches (see
        ``jax_utils.packing``): positions only attend within their own
        segment. Either one [B, T] array (self-attention — requires
        ``T_q == T_kv``) or a ``(q_ids [B, Tq], kv_ids [B, Tkv])`` pair
        (cross-length, e.g. the flash ring's per-block ids). Mutually
        exclusive with ``kv_lengths`` (give padded slots a unique id
        instead). Composes with ``causal``.

    Grouped-query attention (GQA/MQA): ``k``/``v`` may carry FEWER heads
    than ``q`` (``h % h_kv == 0``; ``h_kv == 1`` is multi-query) — each
    group of ``h // h_kv`` query heads attends to one shared K/V head,
    equivalent to repeating K/V heads but without materializing the
    repeat: the kernels' K/V fetches are group-mapped in the BlockSpec
    index maps, so HBM traffic and residual memory scale with ``h_kv``,
    and dK/dV come back group-summed at the K/V head count (f32
    accumulation). Not supported with ``bwd_impl="reference"``.
    """
    _check_bwd_impl(bwd_impl)
    _check_gqa_heads(q, k, v, bwd_impl)
    block_q, block_k = _default_blocks(k.shape[1], block_q, block_k)
    if segment_ids is not None:
        if kv_lengths is not None:
            raise ValueError(
                "segment_ids and kv_lengths are mutually exclusive: give "
                "padded slots their own segment id instead")
        _check_segment_ids(segment_ids, q.shape[1], k.shape[1])
        return _flash_aux(q, k, v, segment_ids, block_q, block_k,
                          interpret, causal, bwd_impl, "segs")
    if kv_lengths is None:
        return _flash_static(q, k, v, block_q, block_k, interpret, causal,
                             bwd_impl)
    return _flash_aux(q, k, v, kv_lengths, block_q, block_k, interpret,
                      causal, bwd_impl, "lens")


def _check_bwd_impl(bwd_impl):
    if bwd_impl not in ("flash", "reference"):
        raise ValueError(
            f"bwd_impl {bwd_impl!r} is not 'flash' or 'reference'")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_static(q, k, v, block_q, block_k, interpret, causal, bwd_impl):
    if interpret is None:
        interpret = _should_interpret()
    return _flash_forward(q, k, v, block_q, block_k, interpret, causal)


def _fwd(q, k, v, block_q, block_k, interpret, causal, bwd_impl,
         kv_lengths=None, segment_ids=None):
    if interpret is None:
        interpret = _should_interpret()
    if bwd_impl == "reference":
        out = _flash_forward(q, k, v, block_q, block_k, interpret, causal,
                             kv_lengths=kv_lengths, segment_ids=segment_ids)
        return out, (q, k, v, None, None)
    out_padded, lse = _flash_forward(q, k, v, block_q, block_k, interpret,
                                     causal, return_residuals=True,
                                     kv_lengths=kv_lengths,
                                     segment_ids=segment_ids)
    b, t_q, h, _ = q.shape
    out = _from_bh(out_padded[:, :t_q], b, h)
    # o is saved PADDED in [B·H, T, D] form: the backward consumes it block
    # by block in exactly this layout, so nothing is re-transposed there.
    return out, (q, k, v, out_padded, lse)


def _bwd(block_q, block_k, interpret, causal, bwd_impl, residuals, g,
         kv_lengths=None, segment_ids=None):
    if interpret is None:
        interpret = _should_interpret()
    q, k, v, o_padded, lse = residuals
    if bwd_impl == "reference":
        # Recompute-through-the-oracle backward: XLA materializes the [T, T]
        # scores inside its fused backward. Correctness oracle only.
        _, vjp = jax.vjp(
            functools.partial(_attention_reference, causal=causal), q, k, v)
        return vjp(g)
    return _flash_backward(q, k, v, o_padded, lse, g, block_q, block_k,
                           interpret, causal, kv_lengths=kv_lengths,
                           segment_ids=segment_ids)


def _static_fwd(q, k, v, block_q, block_k, interpret, causal, bwd_impl):
    return _fwd(q, k, v, block_q, block_k, interpret, causal, bwd_impl)


def _static_bwd(block_q, block_k, interpret, causal, bwd_impl, residuals, g):
    return _bwd(block_q, block_k, interpret, causal, bwd_impl, residuals, g)


_flash_static.defvjp(_static_fwd, _static_bwd)


# One custom_vjp serves both integer-aux variants (per-example kv_lengths
# and packed-batch segment_ids): the wrappers differ only in which keyword
# the aux array threads through, so ``aux_kind`` selects it statically.
_AUX_KW = {"lens": "kv_lengths", "segs": "segment_ids"}


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_aux(q, k, v, aux, block_q, block_k, interpret, causal,
               bwd_impl, aux_kind):
    if interpret is None:
        interpret = _should_interpret()
    return _flash_forward(q, k, v, block_q, block_k, interpret, causal,
                          **{_AUX_KW[aux_kind]: aux})


def _aux_fwd(q, k, v, aux, block_q, block_k, interpret, causal, bwd_impl,
             aux_kind):
    if bwd_impl == "reference":
        raise NotImplementedError(
            f"bwd_impl='reference' does not support {_AUX_KW[aux_kind]}; "
            "the dense oracle lives in "
            "models.sequence_model.attention_reference")
    out, residuals = _fwd(q, k, v, block_q, block_k, interpret, causal,
                          bwd_impl, **{_AUX_KW[aux_kind]: aux})
    return out, residuals + (aux,)


def _aux_bwd(block_q, block_k, interpret, causal, bwd_impl, aux_kind,
             residuals, g):
    aux = residuals[-1]
    dq, dk, dv = _bwd(block_q, block_k, interpret, causal, bwd_impl,
                      residuals[:-1], g, **{_AUX_KW[aux_kind]: aux})
    # Integer aux carries no gradient: float0 zeros (handles the
    # (q_ids, kv_ids) pair form of segment_ids too).
    return dq, dk, dv, _int_aux_zeros(aux)


_flash_aux.defvjp(_aux_fwd, _aux_bwd)


def flash_attention_with_lse(q, k, v, block_q=None, block_k=None,
                             interpret=None, causal=False, causal_shift=0,
                             kv_lengths=None, segment_ids=None):
    """Flash attention that ALSO returns the per-row log-sum-exp — the
    merge statistic for combining partial attention over K/V shards
    (ring/blockwise attention: two normalized partials with lse's combine
    exactly into attention over their union).

    Returns ``(out [B, Tq, H, D], lse [B, Tq, H] f32)`` with
    ``lse = -inf`` for rows with no valid key (the true logsumexp of an
    empty set — an empty partial contributes zero weight to a merge).
    Differentiable in BOTH outputs: the backward kernels fold the lse
    cotangent into ds (∂lse/∂s = p). ``causal_shift=-1`` gives STRICT
    causal (key strictly before query) — the striped-ring blocks whose key
    shard sits after the query shard in the interleaved global order.
    ``segment_ids`` may be one [B, T] array (self-attention over a packed
    batch) or a ``(q_ids, kv_ids)`` pair (the ring: the resident K/V block
    carries its own ids); mutually exclusive with ``kv_lengths``.
    """
    _check_gqa_heads(q, k, v)
    block_q, block_k = _default_blocks(k.shape[1], block_q, block_k)
    if segment_ids is not None:
        if kv_lengths is not None:
            raise ValueError(
                "segment_ids and kv_lengths are mutually exclusive: give "
                "padded slots their own segment id instead")
        _check_segment_ids(segment_ids, q.shape[1], k.shape[1])
    return _flash_with_lse(q, k, v, kv_lengths, segment_ids, block_q,
                           block_k, interpret, causal, causal_shift)


def _lse_to_public(lse_raw, b, h, t_q):
    """[B·H, Tq_pad] residual → [B, Tq, H] public lse; the kernel's +inf
    no-valid-key convention flips to -inf (empty-set logsumexp)."""
    lse = lse_raw[:, :t_q]
    lse = jnp.where(jnp.isposinf(lse), -jnp.inf, lse)
    return lse.reshape(b, h, t_q).transpose(0, 2, 1)


def _dlse_to_bh(dlse, tq_p):
    """[B, Tq, H] cotangent → [B·H, Tq_pad] kernel layout (zero-padded)."""
    b, t_q, h = dlse.shape
    flat = dlse.astype(jnp.float32).transpose(0, 2, 1).reshape(b * h, t_q)
    pad = tq_p - t_q
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat


def _int_aux_zeros(aux):
    """float0 zero cotangent matching an integer aux pytree (or None)."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda a: np.zeros(jnp.shape(a), dtype=jax.dtypes.float0), aux)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_with_lse(q, k, v, kv_lengths, segment_ids, block_q, block_k,
                    interpret, causal, causal_shift):
    out, _, lse_pub = _with_lse_primal(q, k, v, kv_lengths, segment_ids,
                                       block_q, block_k, interpret, causal,
                                       causal_shift)
    return out, lse_pub


def _with_lse_primal(q, k, v, kv_lengths, segment_ids, block_q, block_k,
                     interpret, causal, causal_shift):
    if interpret is None:
        interpret = _should_interpret()
    out_padded, lse_raw = _flash_forward(
        q, k, v, block_q, block_k, interpret, causal,
        return_residuals=True, kv_lengths=kv_lengths,
        segment_ids=segment_ids, causal_shift=causal_shift)
    b, t_q, h, _ = q.shape
    out = _from_bh(out_padded[:, :t_q], b, h)
    return out, (out_padded, lse_raw), _lse_to_public(lse_raw, b, h, t_q)


def _with_lse_fwd(q, k, v, kv_lengths, segment_ids, block_q, block_k,
                  interpret, causal, causal_shift):
    out, (out_padded, lse_raw), lse_pub = _with_lse_primal(
        q, k, v, kv_lengths, segment_ids, block_q, block_k, interpret,
        causal, causal_shift)
    return (out, lse_pub), (q, k, v, out_padded, lse_raw, kv_lengths,
                            segment_ids)


def _with_lse_bwd(block_q, block_k, interpret, causal, causal_shift,
                  residuals, cotangents):
    if interpret is None:
        interpret = _should_interpret()
    q, k, v, o_padded, lse_raw, kv_lengths, segment_ids = residuals
    do, dlse = cotangents
    dlse_bh = _dlse_to_bh(dlse, lse_raw.shape[1])
    dq, dk, dv = _flash_backward(q, k, v, o_padded, lse_raw, do, block_q,
                                 block_k, interpret, causal,
                                 kv_lengths=kv_lengths,
                                 segment_ids=segment_ids,
                                 causal_shift=causal_shift, dlse=dlse_bh)
    return (dq, dk, dv, _int_aux_zeros(kv_lengths),
            _int_aux_zeros(segment_ids))


_flash_with_lse.defvjp(_with_lse_fwd, _with_lse_bwd)
