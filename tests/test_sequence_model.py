"""Ring-attention sequence model tests over the 8-device virtual CPU mesh.

This is the long-context/sequence-parallel story: NGram windows → [B, T, F]
→ shard_map ring attention (sequence sharded over the mesh, K/V rotating via
ppermute).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.sequence_model import (
    apply_seq_model,
    attention_reference,
    init_seq_params,
    make_seq_train_step,
    ring_attention,
    seq_param_partition_specs,
)


def _mesh(shape, names):
    return Mesh(np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape),
                names)


def test_ring_attention_matches_reference():
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 4, 8).astype(np.float32))
               for _ in range(3))
    expected = attention_reference(q, k, v)
    got = ring_attention(q, k, v, mesh, "sp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_with_sharded_inputs():
    mesh = _mesh((8,), ("sp",))
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    rng = np.random.RandomState(1)
    arrs = [jax.device_put(rng.randn(1, 64, 2, 16).astype(np.float32), spec)
            for _ in range(3)]
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, "sp"))(*arrs)
    expected = attention_reference(*arrs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_attention_matches_reference():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 8, 16).astype(np.float32))
               for _ in range(3))
    got = ulysses_attention(q, k, v, mesh, "sp")
    expected = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_attention_sharded_and_jitted():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((2, 4), ("data", "sp"))
    spec = NamedSharding(mesh, P("data", "sp", None, None))
    rng = np.random.RandomState(5)
    arrs = [jax.device_put(rng.randn(2, 32, 4, 8).astype(np.float32), spec)
            for _ in range(3)]
    out = jax.jit(lambda a, b, c: ulysses_attention(
        a, b, c, mesh, "sp", batch_axis="data"))(*arrs)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(*arrs)),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_requires_divisible_heads():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(6)
    q, k, v = (jnp.asarray(rng.randn(1, 16, 3, 8).astype(np.float32))
               for _ in range(3))  # 3 heads over an 8-way axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh, "sp")


def test_seq_train_step_ulysses():
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)

    mesh = _mesh((2, 4), ("data", "sp"))
    params = init_seq_params(jax.random.PRNGKey(3), feature_dim=4,
                             d_model=32, num_heads=4, num_classes=3)
    step = jax.jit(make_seq_train_step(0.05, num_heads=4, mesh=mesh,
                                       attn_impl="ulysses"))
    windows = jnp.asarray(np.random.RandomState(7)
                          .randn(4, 16, 4).astype(np.float32))
    labels = jnp.zeros(4, jnp.int32)
    mask = jnp.ones(4, bool)
    params, loss = step(params, windows, labels, mask)
    assert np.isfinite(float(loss))


def test_seq_train_step_default_works_without_mesh():
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)

    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=4,
                             d_model=16, num_heads=2, num_classes=3)
    step = make_seq_train_step(0.05, num_heads=2)  # no mesh, defaults
    windows = jnp.zeros((2, 8, 4), jnp.float32)
    params, loss = step(params, windows, jnp.zeros(2, jnp.int32),
                        jnp.ones(2, bool))
    assert np.isfinite(float(loss))


def test_apply_seq_model_rejects_unknown_attn_impl():
    from petastorm_tpu.models.sequence_model import (apply_seq_model,
                                                     init_seq_params)

    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=4,
                             d_model=16, num_heads=2)
    windows = jnp.zeros((2, 8, 4), jnp.float32)
    with pytest.raises(ValueError, match="attn_impl"):
        apply_seq_model(params, windows, num_heads=2, attn_impl="ulyses")
    mesh = _mesh((8,), ("sp",))
    with pytest.raises(ValueError, match="attn_impl"):
        apply_seq_model(params, windows, num_heads=2, mesh=mesh,
                        attn_impl="flash")


def test_seq_model_forward_dense_vs_ring():
    mesh = _mesh((8,), ("sp",))
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=6,
                             d_model=32, num_heads=4)
    windows = np.random.RandomState(2).randn(4, 16, 6).astype(np.float32)
    dense = apply_seq_model(params, jnp.asarray(windows), num_heads=4,
                            mesh=None, compute_dtype=jnp.float32)
    ring = apply_seq_model(params, jnp.asarray(windows), num_heads=4,
                           mesh=mesh, compute_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_seq_train_step_over_data_sp_mesh():
    mesh = _mesh((2, 4), ("data", "sp"))
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=5,
                             d_model=16, num_heads=2, num_classes=3)
    specs = seq_param_partition_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    step = jax.jit(make_seq_train_step(0.1, num_heads=2, mesh=mesh))
    batch_sh = NamedSharding(mesh, P("data", "sp", None))

    windows = jax.device_put(
        np.random.RandomState(3).randn(4, 8, 5).astype(np.float32), batch_sh)
    labels = jax.device_put(np.array([0, 1, 2, 1], np.int32),
                            NamedSharding(mesh, P("data")))
    mask = jax.device_put(np.ones(4, bool), NamedSharding(mesh, P("data")))

    losses = []
    for _ in range(5):
        params, loss = step(params, windows, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_ngram_windows_feed_sequence_model(petastorm_dataset):
    """End-to-end: NGram reader → [B, T, ...] collation → ring attention."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.ngram import NGram

    mesh = _mesh((2,), ("sp",))
    ngram = NGram({0: ["^matrix$", "^id$"], 1: ["^matrix$", "^id$"]},
                  delta_threshold=10, timestamp_field="timestamp_s")
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         schema_fields=ngram, num_epochs=1,
                         shuffle_row_groups=False)
    loader = make_jax_dataloader(reader, 4, last_batch="drop",
                                 non_tensor_policy="drop",
                                 stage_to_device=False)
    with loader:
        batch = next(iter(loader))
    windows = batch["matrix"]            # [B, T, 4, 8]
    assert windows.shape[1:] == (2, 4, 8)
    flat = jnp.asarray(windows.reshape(windows.shape[0], 2, -1))
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=32,
                             d_model=16, num_heads=2)
    logits = apply_seq_model(params, flat, num_heads=2, mesh=mesh,
                             compute_dtype=jnp.float32)
    assert logits.shape == (windows.shape[0], 10)
    assert np.isfinite(np.asarray(logits)).all()


# --- causal sequence parallelism (round 4) --------------------------------

def test_causal_ring_striped_matches_reference():
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(10)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 4, 8).astype(np.float32))
               for _ in range(3))
    expected = attention_reference(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, "sp", causal=True,
                         placement="striped")
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
    # causal must differ from bidirectional (mask sanity)
    full = ring_attention(q, k, v, mesh, "sp")
    assert not np.allclose(np.asarray(got), np.asarray(full))


def test_causal_ring_contiguous_matches_reference():
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 16).astype(np.float32))
               for _ in range(3))
    expected = attention_reference(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, "sp", causal=True,
                         placement="contiguous")
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_causal_ring_jitted_on_data_sp_mesh():
    mesh = _mesh((2, 4), ("data", "sp"))
    spec = NamedSharding(mesh, P("data", "sp", None, None))
    rng = np.random.RandomState(12)
    arrs = [jax.device_put(rng.randn(2, 32, 2, 8).astype(np.float32), spec)
            for _ in range(3)]
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, "sp", batch_axis="data", causal=True))(*arrs)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(attention_reference(*arrs, causal=True)),
        rtol=2e-4, atol=2e-4)


def test_causal_ulysses_matches_reference():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(13)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 8, 16).astype(np.float32))
               for _ in range(3))
    got = ulysses_attention(q, k, v, mesh, "sp", causal=True)
    expected = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_flash_local_attention_matches():
    """Forcing the flash local attention (below the auto threshold) must
    match dense — the long-T path with no [T, T] buffer, causal and not."""
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((4,), ("sp",))
    rng = np.random.RandomState(14)
    q, k, v = (jnp.asarray(rng.randn(1, 64, 4, 8).astype(np.float32))
               for _ in range(3))
    for causal in (False, True):
        flash = ulysses_attention(q, k, v, mesh, "sp", causal=causal,
                                  local_attn="flash")
        dense = ulysses_attention(q, k, v, mesh, "sp", causal=causal,
                                  local_attn="dense")
        np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(flash),
            np.asarray(attention_reference(q, k, v, causal=causal)),
            rtol=2e-4, atol=2e-4)


def test_causal_seq_train_step_descends():
    mesh = _mesh((2, 4), ("data", "sp"))
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=5,
                             d_model=16, num_heads=4, num_classes=3)
    for attn_impl in ("ring", "ulysses"):
        step = jax.jit(make_seq_train_step(0.1, num_heads=4, mesh=mesh,
                                           attn_impl=attn_impl, causal=True))
        windows = jax.device_put(
            np.random.RandomState(3).randn(4, 8, 5).astype(np.float32),
            NamedSharding(mesh, P("data", "sp", None)))
        labels = jax.device_put(np.array([0, 1, 2, 1], np.int32),
                                NamedSharding(mesh, P("data")))
        mask = jax.device_put(np.ones(4, bool),
                              NamedSharding(mesh, P("data")))
        p, losses = dict(params), []
        for _ in range(4):
            p, loss = step(p, windows, labels, mask)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], (attn_impl, losses)


def test_striped_causal_requires_equal_lengths():
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(15)
    q = jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    with pytest.raises(ValueError, match="T_q == T_kv"):
        ring_attention(q, k, k, mesh, "sp", causal=True, placement="striped")


# --- per-example length masking (round 4) ---------------------------------

def _padded_vs_unpadded(attn_impl, t_full=24, t_real=16):
    params = init_seq_params(jax.random.PRNGKey(2), feature_dim=6,
                             d_model=32, num_heads=4, max_len=64)
    rng = np.random.RandomState(20)
    real = rng.randn(3, t_real, 6).astype(np.float32)
    padded = np.concatenate(
        [real, np.full((3, t_full - t_real, 6), 7.7, np.float32)], axis=1)
    unpadded_logits = apply_seq_model(
        params, jnp.asarray(real), num_heads=4, compute_dtype=jnp.float32,
        attn_impl=attn_impl)
    padded_logits = apply_seq_model(
        params, jnp.asarray(padded), num_heads=4, compute_dtype=jnp.float32,
        attn_impl=attn_impl, lengths=jnp.full(3, t_real, jnp.int32))
    return np.asarray(unpadded_logits), np.asarray(padded_logits)


def test_lengths_dense_padded_logits_match_unpadded():
    # Ulp-level, not bitwise: XLA's reduction tree (softmax denominator,
    # einsum contraction) associates differently for T=24 than T=16, so the
    # zero-contribution terms shift rounding by ~1e-7. Exact invariance at
    # EQUAL shapes is covered by
    # test_lengths_train_step_gradients_ignore_padding.
    unpadded, padded = _padded_vs_unpadded("dense")
    np.testing.assert_allclose(padded, unpadded, rtol=1e-6, atol=1e-6)


def test_lengths_flash_padded_logits_match_unpadded():
    unpadded, padded = _padded_vs_unpadded("flash")
    np.testing.assert_allclose(padded, unpadded, rtol=1e-5, atol=1e-6)


def test_lengths_train_step_gradients_ignore_padding():
    """Gradients must not depend on values in the padded tail."""
    step = make_seq_train_step(0.05, num_heads=2)
    params = init_seq_params(jax.random.PRNGKey(4), feature_dim=4,
                             d_model=16, num_heads=2, num_classes=3)
    rng = np.random.RandomState(21)
    w1 = rng.randn(2, 12, 4).astype(np.float32)
    w2 = w1.copy()
    w2[:, 8:, :] = 123.0  # different garbage in the padded tail
    lengths = jnp.full(2, 8, jnp.int32)
    labels, mask = jnp.zeros(2, jnp.int32), jnp.ones(2, bool)
    p1, l1 = step(params, jnp.asarray(w1), labels, mask, lengths)
    p2, l2 = step(params, jnp.asarray(w2), labels, mask, lengths)
    assert float(l1) == float(l2)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_length_example_is_nan_free_in_grads():
    """A fully-padded example (lengths[b]=0, mask[b]=False) must not poison
    the other examples' gradients with NaN."""
    step = make_seq_train_step(0.05, num_heads=2)
    params = init_seq_params(jax.random.PRNGKey(5), feature_dim=4,
                             d_model=16, num_heads=2, num_classes=3)
    windows = jnp.asarray(np.random.RandomState(22)
                          .randn(3, 8, 4).astype(np.float32))
    lengths = jnp.asarray([8, 0, 5], jnp.int32)
    mask = jnp.asarray([True, False, True])
    new_params, loss = step(params, windows, jnp.zeros(3, jnp.int32), mask,
                            lengths)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(new_params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_causal_ring_rejects_cross_lengths_any_placement():
    mesh = _mesh((4,), ("sp",))
    rng = np.random.RandomState(16)
    q = jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    for placement in ("striped", "contiguous"):
        with pytest.raises(ValueError, match="T_q == T_kv"):
            ring_attention(q, k, k, mesh, "sp", causal=True,
                           placement=placement)


def test_ulysses_flash_tiny_t_falls_back_to_dense():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((2,), ("sp",))
    rng = np.random.RandomState(17)
    q, k, v = (jnp.asarray(rng.randn(1, 4, 2, 8).astype(np.float32))
               for _ in range(3))  # t_full=4 < 8: must not hit the kernel
    out = ulysses_attention(q, k, v, mesh, "sp", local_attn="flash")
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=2e-4, atol=2e-4)


def test_lengths_ring_attention_matches_reference():
    mesh = _mesh((4,), ("sp",))
    rng = np.random.RandomState(30)
    q, k, v = (jnp.asarray(rng.randn(3, 32, 2, 8).astype(np.float32))
               for _ in range(3))
    lengths = jnp.asarray([32, 17, 9], jnp.int32)
    for causal, placement in ((False, "contiguous"), (True, "striped"),
                              (True, "contiguous")):
        got = ring_attention(q, k, v, mesh, "sp", causal=causal,
                             placement=placement, lengths=lengths)
        want = attention_reference(q, k, v, causal=causal, lengths=lengths)
        # rows past each example's length attend nothing real; compare only
        # valid rows (the model pools them away)
        for b2, le in enumerate(np.asarray(lengths)):
            np.testing.assert_allclose(
                np.asarray(got)[b2, :le], np.asarray(want)[b2, :le],
                rtol=2e-4, atol=2e-4, err_msg=f"{causal}/{placement}/b{b2}")


def test_lengths_ulysses_attention_matches_reference():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((4,), ("sp",))
    rng = np.random.RandomState(31)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 4, 8).astype(np.float32))
               for _ in range(3))
    lengths = jnp.asarray([32, 11], jnp.int32)
    for local_attn in ("dense", "flash"):
        got = ulysses_attention(q, k, v, mesh, "sp", lengths=lengths,
                                local_attn=local_attn)
        want = attention_reference(q, k, v, lengths=lengths)
        for b2, le in enumerate(np.asarray(lengths)):
            np.testing.assert_allclose(
                np.asarray(got)[b2, :le], np.asarray(want)[b2, :le],
                rtol=2e-4, atol=2e-4, err_msg=f"{local_attn}/b{b2}")


def test_lengths_sharded_train_step_descends():
    mesh = _mesh((2, 4), ("data", "sp"))
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=5,
                             d_model=16, num_heads=4, num_classes=3)
    for attn_impl in ("ring", "ulysses"):
        step = jax.jit(make_seq_train_step(0.1, num_heads=4, mesh=mesh,
                                           attn_impl=attn_impl, causal=True))
        windows = jax.device_put(
            np.random.RandomState(3).randn(4, 8, 5).astype(np.float32),
            NamedSharding(mesh, P("data", "sp", None)))
        labels = jax.device_put(np.array([0, 1, 2, 1], np.int32),
                                NamedSharding(mesh, P("data")))
        mask = jax.device_put(np.ones(4, bool), NamedSharding(mesh, P("data")))
        lengths = jax.device_put(np.array([8, 5, 8, 6], np.int32),
                                 NamedSharding(mesh, P("data")))
        p, losses = dict(params), []
        for _ in range(3):
            p, loss = step(p, windows, labels, mask, lengths)
            losses.append(float(loss))
        assert np.isfinite(losses).all(), (attn_impl, losses)
        assert losses[-1] < losses[0], (attn_impl, losses)


def test_lengths_ring_default_placement_non_causal():
    """Regression: lengths + causal=False + the DEFAULT placement="striped"
    must use contiguous position math (no striping happens without causal)."""
    mesh = _mesh((4,), ("sp",))
    rng = np.random.RandomState(33)
    q, k, v = (jnp.asarray(rng.randn(2, 32, 2, 8).astype(np.float32))
               for _ in range(3))
    lengths = jnp.asarray([32, 9], jnp.int32)
    got = ring_attention(q, k, v, mesh, "sp", lengths=lengths)  # defaults
    want = attention_reference(q, k, v, lengths=lengths)
    for b2, le in enumerate(np.asarray(lengths)):
        np.testing.assert_allclose(np.asarray(got)[b2, :le],
                                   np.asarray(want)[b2, :le],
                                   rtol=2e-4, atol=2e-4)


# --- packed batches (segment ids) over the sequence-parallel paths --------

def _packed_case(b=2, t=32, h=4, d=8, seed=7):
    rng = np.random.RandomState(seed)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))
    # two packed rows: 3 and 2 segments (incl. a -1 padded tail)
    seg = np.stack([
        np.array([0] * 10 + [1] * 14 + [2] * 8),
        np.array([0] * 20 + [1] * 6 + [-1] * 6),
    ]).astype(np.int32)
    return q, k, v, jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ring_attention_matches_reference(causal):
    """Packed-batch ring attention: the ids ride the K/V ring (striped and
    contiguous placements both) and must match the dense oracle."""
    mesh = _mesh((8,), ("sp",))
    q, k, v, seg = _packed_case()
    expected = attention_reference(q, k, v, causal=causal, segment_ids=seg)
    for placement in ("striped", "contiguous"):
        got = ring_attention(q, k, v, mesh, "sp", causal=causal,
                             placement=placement, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"{placement} causal={causal}")


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ulysses_attention_matches_reference(causal):
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((8,), ("sp",))
    q, k, v, seg = _packed_case(h=8)
    expected = attention_reference(q, k, v, causal=causal, segment_ids=seg)
    for local in ("dense", "flash"):
        got = ulysses_attention(q, k, v, mesh, "sp", causal=causal,
                                local_attn=local, segment_ids=seg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"{local} causal={causal}")


def test_segment_ring_rejects_lengths_combo():
    mesh = _mesh((8,), ("sp",))
    q, k, v, seg = _packed_case()
    with pytest.raises(ValueError, match="mutually exclusive"):
        ring_attention(q, k, v, mesh, "sp", segment_ids=seg,
                       lengths=jnp.full((2,), 10))


def test_segment_ring_jitted_on_data_sp_mesh():
    """dp x sp: batch over data, sequence over sp, ids sharded like the
    sequence — the packed path compiles and matches under jit."""
    mesh = _mesh((2, 4), ("data", "sp"))
    q, k, v, seg = _packed_case()
    expected = attention_reference(q, k, v, causal=True, segment_ids=seg)
    fn = jax.jit(lambda a, b, c, s: ring_attention(
        a, b, c, mesh, "sp", batch_axis="data", causal=True,
        segment_ids=s))
    got = fn(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


# --- flash-local ring: no [L, L] block even per ring step -----------------

def _flash_ring_case(b=2, t=64, h=2, d=16, seed=11):
    rng = np.random.RandomState(seed)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))
    lens = jnp.asarray(np.array([t, t - 27] * (b // 2)), jnp.int32)
    return q, k, v, lens


@pytest.mark.parametrize("causal,placement", [
    (False, "striped"), (True, "striped"), (True, "contiguous")])
def test_flash_ring_matches_reference(causal, placement):
    """local_attn='flash': per-step Pallas partials merged by log-sum-exp
    must equal dense attention over the full sequence — both causal
    placements, with and without ragged lengths."""
    mesh = _mesh((8,), ("sp",))
    q, k, v, lens = _flash_ring_case()
    for lengths in (None, lens):
        want = attention_reference(q, k, v, causal=causal, lengths=lengths)
        got = ring_attention(q, k, v, mesh, "sp", causal=causal,
                             placement=placement, lengths=lengths,
                             local_attn="flash")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"causal={causal} {placement} lens={lengths is not None}")


def test_flash_ring_gradients_match_reference():
    """Backward rides the kernel's lse-cotangent path through the merge —
    must equal the dense oracle's gradients."""
    mesh = _mesh((8,), ("sp",))
    q, k, v, _ = _flash_ring_case(seed=12)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, "sp", causal=True,
                               local_attn="flash") ** 2).sum()

    def loss_dense(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_flash_ring_jitted_dp_sp_and_guards():
    mesh = _mesh((2, 4), ("data", "sp"))
    q, k, v, _ = _flash_ring_case(t=32, seed=13)
    want = attention_reference(q, k, v, causal=True)
    fn = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, "sp", batch_axis="data", causal=True,
        local_attn="flash"))
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # below the min tile (L < 8) it silently falls back to dense
    small_mesh = _mesh((8,), ("sp",))
    qs, ks, vs, _ = _flash_ring_case(t=32, seed=14)  # L = 4
    got = ring_attention(qs, ks, vs, small_mesh, "sp", local_attn="flash")
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(attention_reference(qs, ks, vs)),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal,placement", [
    (False, "striped"), (True, "striped"), (True, "contiguous")])
def test_flash_ring_packed_segments_match_reference(causal, placement):
    """Packed batches through the flash ring: the local q ids pair with the
    ring-carried kv ids per step — must match the dense packed oracle."""
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(15)
    b, t, h, d = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))
    seg = jnp.asarray(np.stack([
        np.array([0] * 20 + [1] * 30 + [2] * 14),
        np.array([0] * 40 + [1] * 16 + [-1] * 8),
    ]), jnp.int32)
    want = attention_reference(q, k, v, causal=causal, segment_ids=seg)
    got = ring_attention(q, k, v, mesh, "sp", causal=causal,
                         placement=placement, segment_ids=seg,
                         local_attn="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4,
                               err_msg=f"{placement} causal={causal}")


def test_flash_ring_packed_gradients_match_reference():
    mesh = _mesh((8,), ("sp",))
    rng = np.random.RandomState(16)
    b, t, h, d = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))
    seg = jnp.asarray(np.stack([
        np.array([0] * 30 + [1] * 34),
        np.array([0] * 50 + [-1] * 14),
    ]), jnp.int32)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, "sp", causal=True,
                               segment_ids=seg,
                               local_attn="flash") ** 2).sum()

    def loss_dense(q, k, v):
        return (attention_reference(q, k, v, causal=True,
                                    segment_ids=seg) ** 2).sum()

    gf = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


# ---------------------------------------------------------------------------
# Grouped-query attention through the ring
# ---------------------------------------------------------------------------

def _gqa_ring_inputs(h=4, h_kv=2, b=2, t=32, d=8, seed=30):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    return q, k, v


def _gqa_oracle(q, k, v, **kw):
    g = q.shape[2] // k.shape[2]
    return attention_reference(q, jnp.repeat(k, g, axis=2),
                               jnp.repeat(v, g, axis=2), **kw)


@pytest.mark.parametrize("h_kv", [2, 1])
@pytest.mark.parametrize("placement", ["striped", "contiguous"])
def test_ring_gqa_causal_matches_repeated_kv_reference(h_kv, placement):
    """GQA K/V ride the ring at the GROUPED head count (ICI traffic
    shrinks by the group factor); the dense local path repeats heads only
    at local compute. Must equal attention with repeated K/V."""
    mesh = _mesh((8,), ("sp",))
    q, k, v = _gqa_ring_inputs(h_kv=h_kv)
    got = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, "sp", causal=True, placement=placement))(q, k, v)
    want = _gqa_oracle(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_gqa_flash_local_matches_reference():
    """The flash-local ring with grouped K/V: the kernel group-maps
    fetches in-kernel — no repeat anywhere. Needs L = T/sp >= 8."""
    mesh = _mesh((8,), ("sp",))
    q, k, v = _gqa_ring_inputs(h_kv=2, t=64, seed=31)
    got = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, "sp", causal=True, local_attn="flash"))(q, k, v)
    want = _gqa_oracle(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_gqa_gradients_match_repeated_kv_autodiff():
    mesh = _mesh((8,), ("sp",))
    q, k, v = _gqa_ring_inputs(h_kv=2, t=64, seed=32)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, "sp", causal=True,
                               local_attn="flash") ** 2).sum()

    def loss_ref(q, k, v):
        return (_gqa_oracle(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), got, want):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_ring_gqa_with_lengths_and_packed_segments():
    mesh = _mesh((8,), ("sp",))
    q, k, v = _gqa_ring_inputs(h_kv=2, seed=33)
    t = q.shape[1]
    lens = jnp.asarray([t, t - 8], jnp.int32)
    got = ring_attention(q, k, v, mesh, "sp", causal=True, lengths=lens)
    want = _gqa_oracle(q, k, v, causal=True, lengths=lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    segs = jnp.asarray(np.repeat(np.arange(4), t // 4)[None]
                       .repeat(2, 0), jnp.int32)
    got = ring_attention(q, k, v, mesh, "sp", causal=True,
                         segment_ids=segs)
    want = _gqa_oracle(q, k, v, causal=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_gqa_rejects_bad_ratio_and_ulysses_rejects_gqa():
    from petastorm_tpu.models.sequence_model import ulysses_attention

    mesh = _mesh((8,), ("sp",))
    q, k, v = _gqa_ring_inputs(h_kv=2)
    with pytest.raises(ValueError, match="divide"):
        ring_attention(q, k[:, :, :1].repeat(3, axis=2),
                       v[:, :, :1].repeat(3, axis=2), mesh, "sp")
    with pytest.raises(NotImplementedError, match="ring_attention"):
        ulysses_attention(q, k, v, mesh, "sp")
