"""Elastic multi-tenant fleet: jobs, fair scheduling, and autoscaling.

Layers under test (docs/guides/service.md#multi-tenancy-and-autoscaling):

- the pure fair-share planner (``fleet.plan_fair_shares``): weighted
  max-min water-filling goldens — equal weights, weighted ratios, quota
  caps, demand-capped redistribution;
- the pure autoscale planner (``fleet.AutoscalePlanner``): scale-up on
  backlog, drain on idle, retire on drain completion, hysteresis no-flap —
  canned-signal goldens in the ``plan_steals`` tradition;
- dispatcher multi-tenancy: ``register_job``/``end_job`` lifecycle
  (rejected under fcfs with the constraint named), job-scoped fencing
  isolation (restarting job A never bumps job B's epoch), per-job
  recovery/steal attribution in ``status``, fair-share credit scaling;
- WAL durability: an interleaved multi-job lifecycle (register / assign /
  steal / autoscale / cancel) replays to byte-identical per-job state
  across a dispatcher restart;
- worker lifecycle states: standby workers excluded from grants until
  admitted; draining workers shed their queued backlog to serving peers
  and retire;
- ephemeral data sharing end-to-end: two jobs over one dataset share one
  decoded-batch cache — job B's epoch decodes nothing (hit rate 1.0), with
  per-job attribution on the worker;
- the slow fleet soak: 8 workers, 3 concurrent jobs, autoscaler live,
  chaos (``job-cancel`` + ``worker-drain``) on — zero-dup/zero-loss per
  job, identical per-job stream digests (same seed + ordered ⇒ the three
  jobs' byte streams must be equal), a max-min fairness bound on per-job
  delivery, and the autoscale decisions journaled + replayed.
"""

import json
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.reader_impl.framed_socket import FramedConnection
from petastorm_tpu.service import BatchWorker, Dispatcher, ServiceBatchSource
from petastorm_tpu.service.fleet import (
    AutoscaleConfig,
    AutoscalePlanner,
    JobHandle,
    credit_scales,
    end_job,
    open_job_registrations,
    plan_fair_shares,
    register_job,
)

pytestmark = pytest.mark.service


def _rpc(address, header):
    with FramedConnection.connect(tuple(address), timeout=5.0) as conn:
        reply, _ = conn.request(header)
    return reply


def _register_worker(dispatcher, worker_id, num_pieces=12, standby=False,
                     port=9):
    reply = _rpc(dispatcher.address, {
        "type": "register_worker", "worker_id": worker_id,
        "host": "127.0.0.1", "port": port, "num_pieces": num_pieces,
        "standby": standby})
    assert reply["type"] == "ok", reply
    return reply


# ---------------------------------------------------------------------------
# fair-share planner (pure goldens)
# ---------------------------------------------------------------------------

def test_plan_fair_shares_equal_weights_split_evenly():
    shares = plan_fair_shares(6.0, {"a": 10.0, "b": 10.0, "c": 10.0})
    assert shares == {"a": 2.0, "b": 2.0, "c": 2.0}


def test_plan_fair_shares_weighted_ratio():
    shares = plan_fair_shares(6.0, {"heavy": 100.0, "light": 100.0},
                              weights={"heavy": 2.0, "light": 1.0})
    assert shares["heavy"] == pytest.approx(4.0)
    assert shares["light"] == pytest.approx(2.0)


def test_plan_fair_shares_demand_capped_redistributes():
    # Max-min: "a" only wants 1 — its unused entitlement flows to the
    # others instead of idling (the whole point of water-filling).
    shares = plan_fair_shares(9.0, {"a": 1.0, "b": 100.0, "c": 100.0})
    assert shares["a"] == pytest.approx(1.0)
    assert shares["b"] == pytest.approx(4.0)
    assert shares["c"] == pytest.approx(4.0)


def test_plan_fair_shares_quota_caps_even_when_idle():
    shares = plan_fair_shares(8.0, {"capped": 100.0, "free": 100.0},
                              quotas={"capped": 2.0})
    assert shares["capped"] == pytest.approx(2.0)
    assert shares["free"] == pytest.approx(6.0)


def test_plan_fair_shares_never_overallocates():
    shares = plan_fair_shares(4.0, {"a": 100.0, "b": 3.0},
                              weights={"a": 1.0, "b": 10.0})
    assert sum(shares.values()) <= 4.0 + 1e-9
    assert shares["b"] <= 3.0 + 1e-9


def test_credit_scales_largest_share_keeps_full_window():
    scales = credit_scales({"heavy": 4.0, "light": 2.0})
    assert scales["heavy"] == pytest.approx(1.0)
    assert scales["light"] == pytest.approx(0.5)
    # Degenerate all-zero shares: nobody is throttled.
    assert credit_scales({"a": 0.0}) == {"a": 1.0}


# ---------------------------------------------------------------------------
# autoscale planner (pure goldens)
# ---------------------------------------------------------------------------

def _signals(serving=(), standby=(), draining=(), backlog=None):
    return {"serving": list(serving), "standby": list(standby),
            "draining": list(draining), "backlog": dict(backlog or {}),
            "rates": {}}


def test_autoscale_planner_scales_up_on_backlog():
    planner = AutoscalePlanner(AutoscaleConfig(
        scale_up_backlog=4.0, up_windows=2, cooldown_windows=1))
    hot = _signals(serving=["w0"], standby=["s0", "s1"],
                   backlog={"w0": 10})
    assert planner.plan(hot) == []          # window 1: streak building
    decisions = planner.plan(hot)           # window 2: admit
    assert [d["action"] for d in decisions] == ["admit"]
    assert decisions[0]["worker_id"] == "s0"  # deterministic first


def test_autoscale_planner_drains_on_idle():
    planner = AutoscalePlanner(AutoscaleConfig(
        scale_down_backlog=0.5, down_windows=2, min_serving=1))
    idle = _signals(serving=["w0", "w1"], backlog={})
    assert planner.plan(idle) == []
    decisions = planner.plan(idle)
    assert [d["action"] for d in decisions] == ["drain"]
    # Least-backlogged victim, ties broken by id.
    assert decisions[0]["worker_id"] == "w0"
    # Never below min_serving: a one-worker fleet is never drained.
    solo = AutoscalePlanner(AutoscaleConfig(down_windows=1))
    assert solo.plan(_signals(serving=["w0"], backlog={})) == []


def test_autoscale_planner_retires_drained_worker_immediately():
    planner = AutoscalePlanner()
    decisions = planner.plan(_signals(
        serving=["w0"], draining=["d0", "d1"],
        backlog={"w0": 2, "d0": 0, "d1": 3}))
    # d0's backlog hit zero -> retire; d1 still owes pieces -> keep.
    assert decisions == [{"action": "retire", "worker_id": "d0",
                          "reason": "drain complete (backlog 0)"}]


def test_autoscale_planner_hysteresis_no_flap():
    """A signal oscillating across the thresholds every window never
    completes a streak — zero decisions, however long it flaps."""
    planner = AutoscalePlanner(AutoscaleConfig(
        scale_up_backlog=4.0, scale_down_backlog=0.5,
        up_windows=2, down_windows=2))
    hot = _signals(serving=["w0", "w1"], standby=["s0"],
                   backlog={"w0": 10, "w1": 10})
    calm = _signals(serving=["w0", "w1"], standby=["s0"],
                    backlog={"w0": 2, "w1": 2})
    for _ in range(6):
        assert planner.plan(hot) == []
        assert planner.plan(calm) == []


def test_autoscale_planner_cooldown_blocks_back_to_back_decisions():
    planner = AutoscalePlanner(AutoscaleConfig(
        scale_up_backlog=1.0, up_windows=1, cooldown_windows=2))
    hot = _signals(serving=["w0"], standby=["s0", "s1"],
                   backlog={"w0": 50})
    assert [d["action"] for d in planner.plan(hot)] == ["admit"]
    assert planner.plan(hot) == []   # cooldown window 1
    assert planner.plan(hot) == []   # cooldown window 2
    assert [d["action"] for d in planner.plan(hot)] == ["admit"]


def test_autoscale_planner_emergency_admit_outranks_cooldown():
    """Zero serving workers is an outage, not a pacing question: the
    unconditional admit fires even inside a post-decision cooldown and
    even without a backlog signal."""
    planner = AutoscalePlanner(AutoscaleConfig(
        scale_down_backlog=0.5, down_windows=1, cooldown_windows=5))
    # Trigger a drain to arm the cooldown...
    assert [d["action"] for d in planner.plan(
        _signals(serving=["w0", "w1"], backlog={}))] == ["drain"]
    # ...then the serving set empties (last worker died): admit NOW.
    empty = dict(_signals(serving=[], standby=["s0"], backlog={}),
                 backlog_known=False)
    decisions = planner.plan(empty)
    assert [(d["action"], d["worker_id"]) for d in decisions] \
        == [("admit", "s0")]


def test_autoscale_planner_without_backlog_signal_only_retires():
    """Static/fcfs dispatchers report backlog_known=False: an absent
    progress signal must not read as an idle fleet — no admit/drain
    guesses, but an in-flight drain still completes."""
    planner = AutoscalePlanner(AutoscaleConfig(down_windows=1,
                                               up_windows=1))
    signals = dict(_signals(serving=["w0", "w1"], standby=["s0"],
                            draining=["d0"], backlog={}),
                   backlog_known=False)
    for _ in range(5):
        assert planner.plan(signals) == [
            {"action": "retire", "worker_id": "d0",
             "reason": "drain complete (backlog 0)"}]


def test_autoscale_config_rejects_inverted_thresholds():
    with pytest.raises(ValueError, match="scale_down_backlog"):
        AutoscaleConfig(scale_up_backlog=1.0, scale_down_backlog=2.0)
    with pytest.raises(ValueError, match="min_serving"):
        AutoscaleConfig(min_serving=0)


# ---------------------------------------------------------------------------
# dispatcher multi-tenancy: job lifecycle, fencing isolation, fair shares
# ---------------------------------------------------------------------------

def test_register_job_under_fcfs_rejected_with_constraint_named():
    from petastorm_tpu.service.client import ServiceError

    with Dispatcher(port=0, mode="fcfs").start() as disp:
        with pytest.raises(ServiceError) as err:
            register_job(disp.address, "jobA")
        message = str(err.value)
        assert "fcfs" in message and "dynamic" in message
        assert "per-job" in message
    # The failed registration is not tracked as open.
    assert not any(job == "jobA" for _addr, job in open_job_registrations())


def test_job_scoped_fencing_isolation():
    """Restarting (re-registering) job A bumps A's scoped fencing epoch
    and leaves job B's untouched — one job's chaos can never fence a
    peer's streams. A fleet-wide event still moves both."""
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        register_job(disp.address, "jobA")
        register_job(disp.address, "jobB")
        try:
            status = _rpc(disp.address, {"type": "status"})
            a0 = status["jobs"]["jobA"]["fencing_epoch"]
            b0 = status["jobs"]["jobB"]["fencing_epoch"]
            # Job A restarts: only its epoch moves.
            register_job(disp.address, "jobA")
            status = _rpc(disp.address, {"type": "status"})
            assert status["jobs"]["jobA"]["fencing_epoch"] == a0 + 1
            assert status["jobs"]["jobB"]["fencing_epoch"] == b0
            assert status["jobs"]["jobA"]["recovery"]["fencing_bumps"] >= 1
            # A fleet-wide bump (worker reported dead) moves every job.
            _rpc(disp.address, {"type": "report_failure",
                                "client_id": "cA", "job_id": "jobA",
                                "worker_id": "w0", "pieces": []})
            status = _rpc(disp.address, {"type": "status"})
            assert status["jobs"]["jobA"]["fencing_epoch"] == a0 + 2
            assert status["jobs"]["jobB"]["fencing_epoch"] == b0 + 1
            # ...and the failure is attributed to the reporting job only.
            assert (status["jobs"]["jobA"]["recovery"]
                    ["failures_reported"]) == 1
            assert (status["jobs"]["jobB"]["recovery"]
                    .get("failures_reported", 0)) == 0
        finally:
            end_job(disp.address, "jobA")
            end_job(disp.address, "jobB")


def test_end_job_releases_clients_and_state():
    with Dispatcher(port=0, mode="dynamic").start() as disp:
        _register_worker(disp, "w0")
        with JobHandle(disp.address, "ephemeral", weight=2.0):
            reply = _rpc(disp.address, {
                "type": "dynamic_plan", "client_id": "cE",
                "job_id": "ephemeral", "client_index": 0,
                "num_clients": 1, "epoch": 0})
            assert reply["type"] == "plan"
            status = _rpc(disp.address, {"type": "status"})
            assert "cE" in status["jobs"]["ephemeral"]["clients"]
            assert status["dynamic"]["per_job"]["ephemeral"]["backlog"] > 0
        # JobHandle.__exit__ ended the job: clients + queues released.
        status = _rpc(disp.address, {"type": "status"})
        assert "ephemeral" not in status["jobs"]
        assert "cE" not in status["clients"]
        assert "ephemeral" not in (status["dynamic"]["per_job"] or {})
        # Idempotent: a second end is a no-op reply, not an error.
        assert end_job(disp.address, "ephemeral")["removed"] is False


def test_unequal_weights_scale_credit_windows():
    """The fair-share plan's enforceable lever: the lighter job's
    assignment reply carries a credit_scale < 1, the heavier job's stays
    at 1.0 (and a lone/equal-weight job always sees 1.0)."""
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        # Single (implicit) job: identity.
        reply = _rpc(disp.address, {
            "type": "get_assignment", "client_id": "c0",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        assert reply["credit_scale"] == 1.0
        register_job(disp.address, "heavy", weight=3.0)
        register_job(disp.address, "light", weight=1.0)
        try:
            heavy = _rpc(disp.address, {
                "type": "get_assignment", "client_id": "cH",
                "job_id": "heavy", "client_index": 0, "num_clients": 1,
                "epoch": 0})
            light = _rpc(disp.address, {
                "type": "get_assignment", "client_id": "cL",
                "job_id": "light", "client_index": 0, "num_clients": 1,
                "epoch": 0})
            assert heavy["credit_scale"] == 1.0
            assert 0 < light["credit_scale"] <= 1.0 / 3.0 + 0.05
        finally:
            end_job(disp.address, "heavy")
            end_job(disp.address, "light")


def test_standby_worker_excluded_from_grants_until_admitted():
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        _register_worker(disp, "pool0", standby=True)
        listed = _rpc(disp.address, {"type": "list_workers"})
        assert sorted(listed["workers"]) == ["w0"]
        reply = _rpc(disp.address, {
            "type": "get_assignment", "client_id": "c0",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        assert sorted(reply["assignments"]) == ["w0"]
        status = _rpc(disp.address, {"type": "status"})
        assert status["fleet"]["workers_by_state"]["standby"] == ["pool0"]
        # Admission: next assignment spans both.
        assert disp.admit_worker("pool0")
        reply = _rpc(disp.address, {
            "type": "get_assignment", "client_id": "c0",
            "client_index": 0, "num_clients": 1, "epoch": 1})
        assert sorted(reply["assignments"]) == ["pool0", "w0"]
        # Invalid transitions are no-ops, not corruption.
        assert not disp.retire_worker("pool0")   # serving, not draining
        assert not disp.admit_worker("missing")


def test_drain_sheds_backlog_to_serving_peers_and_retires():
    """A drained worker's queued (stealable) pieces move to serving peers
    through the ordinary steal path in ONE sync; once its backlog is
    gone the planner retires it to standby."""
    with Dispatcher(port=0, mode="dynamic").start() as disp:
        _register_worker(disp, "w0")
        _register_worker(disp, "w1")
        plan = _rpc(disp.address, {
            "type": "dynamic_plan", "client_id": "c0",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        owned = {wid: sorted(int(t[0]) for t in pairs)
                 for wid, pairs in plan["assignments"].items()}
        assert disp.drain_worker("w1")
        reply = _rpc(disp.address, {
            "type": "dynamic_sync", "client_id": "c0", "epoch": 0,
            "done": [], "owned": owned,
            "stealable": owned,  # nothing started yet: all stealable
            "rates": {}, "failed_steals": []})
        moves = reply["steals"]
        assert moves, "drain shed nothing"
        assert all(d["from"] == "w1" and d["to"] == "w0" for d in moves)
        assert sorted(d["piece"] for d in moves) == owned["w1"]
        # Report the handoff applied + everything done: backlog reaches 0
        # and the autoscale planner retires the drained worker.
        _rpc(disp.address, {
            "type": "dynamic_sync", "client_id": "c0", "epoch": 0,
            "done": sorted(owned["w0"] + owned["w1"]), "owned": {},
            "stealable": {}, "rates": {}, "failed_steals": []})
        planner = AutoscalePlanner()
        decisions = planner.plan(disp.fleet_signals())
        assert {(d["action"], d["worker_id"]) for d in decisions} \
            == {("retire", "w1")}
        assert disp.retire_worker("w1")
        status = _rpc(disp.address, {"type": "status"})
        assert status["fleet"]["workers_by_state"]["standby"] == ["w1"]
        assert status["fleet"]["autoscale"]["drain"] == 1
        assert status["fleet"]["autoscale"]["retire"] == 1


def test_autoscaler_controller_thread_lifecycle_and_admission():
    """Dispatcher(autoscale=...) runs the fleet-autoscale controller:
    backlog above threshold admits the standby worker (journal-free
    in-memory mode), and stop() tears the thread down (the conftest leak
    guard enforces the teardown half)."""
    with Dispatcher(port=0, mode="dynamic",
                    autoscale={"interval_s": 0.05, "scale_up_backlog": 2.0,
                               "up_windows": 2,
                               "cooldown_windows": 1}).start() as disp:
        assert any(t.name.startswith("fleet-autoscale")
                   for t in threading.enumerate())
        _register_worker(disp, "w0")
        _register_worker(disp, "pool0", standby=True)
        _rpc(disp.address, {
            "type": "dynamic_plan", "client_id": "c0",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if disp.fleet_signals()["serving"] == ["pool0", "w0"]:
                break
            time.sleep(0.05)
        assert disp.fleet_signals()["serving"] == ["pool0", "w0"], \
            "autoscaler never admitted the standby worker under backlog"
        status = _rpc(disp.address, {"type": "status"})
        assert status["fleet"]["autoscale"]["admit"] >= 1
        assert status["fleet"]["autoscaler_armed"] is True


def test_job_fencing_monotone_across_end_and_recreate():
    """A recreated job's scoped fencing epoch starts strictly past every
    token its ended namesake's clients could hold — end_job must not
    reset the epoch under a stale client's feet (it would pass the
    stale-fencing check and act on a superseded plan)."""
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        register_job(disp.address, "phoenix")
        register_job(disp.address, "phoenix")  # restart: offset 1
        status = _rpc(disp.address, {"type": "status"})
        old_epoch = status["jobs"]["phoenix"]["fencing_epoch"]
        end_job(disp.address, "phoenix")
        reply = register_job(disp.address, "phoenix")
        assert reply["fencing_epoch"] > old_epoch
        # A token from the OLD incarnation is stale against the new one.
        stale = _rpc(disp.address, {
            "type": "report_failure", "client_id": "ghost",
            "job_id": "phoenix", "worker_id": "w0", "pieces": [],
            "fencing_epoch": old_epoch})
        assert stale["type"] == "stale_fencing"
        end_job(disp.address, "phoenix")


def test_drain_never_empties_the_serving_set():
    """Concurrent drainers (autoscaler + chaos + operator) each
    check-then-act from their own snapshots: the journaled apply path
    enforces the hard floor — the LAST serving worker refuses to drain."""
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        _register_worker(disp, "w1")
        assert disp.drain_worker("w0")
        assert not disp.drain_worker("w1")  # would empty the serving set
        status = _rpc(disp.address, {"type": "status"})
        assert status["fleet"]["workers_by_state"]["serving"] == ["w1"]


def test_idle_clientless_job_does_not_shrink_active_windows():
    """A registered-but-clientless heavy job is an idle reservation: it
    must not cut an actively-training job's credit window (max-min: no
    capacity idles while anyone has demand)."""
    with Dispatcher(port=0, mode="static").start() as disp:
        _register_worker(disp, "w0")
        register_job(disp.address, "big-idle", weight=3.0)
        register_job(disp.address, "small-active", weight=1.0)
        try:
            reply = _rpc(disp.address, {
                "type": "get_assignment", "client_id": "cS",
                "job_id": "small-active", "client_index": 0,
                "num_clients": 1, "epoch": 0})
            # big-idle has no clients -> zero demand -> small-active
            # holds the whole (and thus the largest) share: scale 1.0.
            assert reply["credit_scale"] == 1.0
        finally:
            end_job(disp.address, "big-idle")
            end_job(disp.address, "small-active")


# ---------------------------------------------------------------------------
# WAL durability: interleaved multi-job lifecycle replays byte-identically
# ---------------------------------------------------------------------------

def test_wal_replay_interleaved_multi_job_lifecycle(tmp_path):
    """ISSUE tier-1: register / assign / steal / autoscale / cancel across
    two jobs, then restart from the journal — every job's assignments,
    scoped fencing offset, per-job recovery counters, worker lifecycle
    states, and autoscale decision counts restore byte-identically (only
    the global fencing base and replay bookkeeping move)."""
    journal_dir = str(tmp_path / "journal")
    with Dispatcher(port=0, mode="dynamic",
                    journal_dir=journal_dir).start() as disp:
        _register_worker(disp, "w0")
        _register_worker(disp, "w1")
        _register_worker(disp, "pool0", standby=True)
        register_job(disp.address, "jobA", weight=2.0)
        register_job(disp.address, "jobB", weight=1.0, quota=1.5)
        register_job(disp.address, "jobC")
        planA = _rpc(disp.address, {
            "type": "dynamic_plan", "client_id": "cA", "job_id": "jobA",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        _rpc(disp.address, {
            "type": "dynamic_plan", "client_id": "cB", "job_id": "jobB",
            "client_index": 0, "num_clients": 1, "epoch": 0})
        # A steal inside job A: report w1's deque done, w0's stealable —
        # the drained receiver pulls pieces over (intra-job by design).
        ownedA = {wid: sorted(int(t[0]) for t in pairs)
                  for wid, pairs in planA["assignments"].items()}
        reply = _rpc(disp.address, {
            "type": "dynamic_sync", "client_id": "cA", "job_id": "jobA",
            "epoch": 0, "done": ownedA["w1"],
            "owned": {"w0": ownedA["w0"]},
            "stealable": {"w0": ownedA["w0"]},
            "rates": {}, "failed_steals": []})
        assert reply["steals"], "expected a drain-trigger steal"
        # Autoscale decisions: admit the pooled worker, drain a serving
        # one. Both journaled.
        assert disp.admit_worker("pool0")
        assert disp.drain_worker("w1")
        # Job A restarts (scoped fence bump), job C is cancelled.
        register_job(disp.address, "jobA", weight=2.0)
        end_job(disp.address, "jobC")
        before = disp.state_snapshot()

    with Dispatcher(port=0, mode="dynamic",
                    journal_dir=journal_dir).start() as restarted:
        after = restarted.state_snapshot()
        volatile = ("fencing_epoch", "recovery")
        plan_before = {k: v for k, v in before.items() if k not in volatile}
        plan_after = {k: v for k, v in after.items() if k not in volatile}
        assert (json.dumps(plan_before, sort_keys=True)
                == json.dumps(plan_after, sort_keys=True))
        # Spot-check the fleet-tier state specifically.
        assert after["jobs"] == before["jobs"]
        # jobC was cancelled; the implicit default job never materialized
        # (every client in this lifecycle named its job explicitly).
        assert sorted(after["jobs"]) == ["jobA", "jobB"]
        assert after["jobs"]["jobA"]["fencing_offset"] == 1
        assert after["jobs"]["jobB"]["quota"] == 1.5
        assert after["autoscale"] == {"admit": 1, "drain": 1, "retire": 0}
        assert after["workers"]["pool0"]["state"] == "serving"
        assert after["workers"]["w1"]["state"] == "draining"
        assert after["job_recovery"] == before["job_recovery"]
        assert after["dyn"] == before["dyn"]
        # jobA/jobB survive the restart as registered jobs; end them
        # against the restarted dispatcher so the leak guard stays green.
        end_job(restarted.address, "jobA")
        end_job(restarted.address, "jobB")
    # The tracked (address, job) handles point at the ORIGINAL stopped
    # dispatcher; the ends above released the server-side state, so drop
    # the stale client-side handles.
    _clear_tracked_jobs(("jobA", "jobB"))


def _clear_tracked_jobs(names):
    """Drop tracked registrations against already-stopped dispatchers
    (ending them over RPC is impossible once the server is gone)."""
    from petastorm_tpu.service import fleet

    with fleet._OPEN_JOBS_LOCK:
        fleet._OPEN_JOBS.difference_update(
            {entry for entry in fleet._OPEN_JOBS if entry[1] in names})


# ---------------------------------------------------------------------------
# ephemeral data sharing: N jobs, one cache, one decode
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_dataset(tmp_path_factory):
    """60 rows in 12 five-row pieces (piece p holds ids [5p, 5p+5))."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    path = tmp_path_factory.mktemp("fleet_ds")
    url = f"file://{path}/ds"
    create_test_scalar_dataset(url, rows_count=60, rows_per_row_group=5)
    return url, 60


def test_two_jobs_share_one_cache_decode_once(fleet_dataset):
    """Ephemeral data sharing (tf.data service §4): job A's epoch fills
    the shared decoded-batch cache; job B — different job, same dataset —
    hits on every piece (order-independent PR 9 keys are job-independent
    by construction). Per-job attribution proves it: B's lookups are 100%
    hits, and the worker's rows are bucketed per job."""
    from petastorm_tpu.cache_impl import CacheConfig

    url, rows = fleet_dataset
    with Dispatcher(port=0, mode="dynamic") as disp:
        disp.start()
        worker = BatchWorker(
            url, dispatcher_address=disp.address, batch_size=5,
            reader_factory="batch", worker_id="w0",
            batch_cache=CacheConfig(mode="mem", mem_mb=64.0).build(),
            reader_kwargs={"workers_count": 2}).start()
        try:
            with JobHandle(disp.address, "jobA"), \
                    JobHandle(disp.address, "jobB"):
                for job in ("jobA", "jobB"):
                    source = ServiceBatchSource(
                        disp.address, job_id=job, client_id=f"client-{job}",
                        dynamic_sync_interval_s=0.1)
                    got = [int(i) for batch in source()
                           for i in batch["id"]]
                    assert sorted(got) == list(range(rows)), job
                by_job = worker.cache_stats_by_job()
                assert by_job["jobA"]["misses"] == 12  # the one cold fill
                assert by_job["jobB"]["misses"] == 0
                assert by_job["jobB"]["hits"] == 12    # decoded NOTHING
                served = worker.rows_by_job()
                assert served["jobA"]["rows"] == rows
                assert served["jobB"]["rows"] == rows
                diag = worker.diagnostics_snapshot()
                assert diag["jobs"]["jobB"]["rows"] == rows
                assert diag["cache_by_job"]["jobB"]["hits"] == 12
        finally:
            worker.stop()


def test_fcfs_client_with_job_id_rejected(fleet_dataset):
    url, _rows = fleet_dataset
    with Dispatcher(port=0, mode="fcfs") as disp:
        disp.start()
        worker = BatchWorker(url, dispatcher_address=disp.address,
                             batch_size=5, reader_factory="batch",
                             reader_kwargs={"workers_count": 2}).start()
        try:
            source = ServiceBatchSource(disp.address, job_id="jobX")
            with pytest.raises(ValueError, match="fcfs"):
                source()
        finally:
            worker.stop()


# ---------------------------------------------------------------------------
# slow fleet soak: 8 workers, 3 jobs, autoscaler, chaos
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_soak_three_jobs_autoscaler_chaos(tmp_path):
    """ISSUE acceptance: a 3-job / 8-worker soak with the autoscaler and
    chaos (job-cancel + worker-drain) live delivers every job
    exactly-once (0 lost / 0 dup), the three jobs' ordered seeded streams
    are byte-identical to each other (same dataset, same seed, same
    canonical order ⇒ equal digests — per-job byte-determinism), per-job
    delivery rates respect a 0.7 max-min fairness bound under equal
    weights, and ≥1 admit + ≥1 drain decision is journaled and replayed
    byte-identically across a dispatcher restart."""
    from petastorm_tpu.cache_impl import CacheConfig
    from petastorm_tpu.service.chaos import (
        ChaosInjector,
        StreamDigest,
        job_cancel_action,
        worker_drain_action,
    )
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/soak_ds"
    rows = 240
    create_test_scalar_dataset(url, rows_count=rows, rows_per_row_group=10)
    journal_dir = str(tmp_path / "journal")
    cache_dir = str(tmp_path / "cache")
    jobs = ("job0", "job1", "job2")
    dispatcher = Dispatcher(
        port=0, mode="dynamic", num_epochs=2, journal_dir=journal_dir,
        shuffle_seed=7,
        autoscale={"interval_s": 0.2, "scale_up_backlog": 3.0,
                   "up_windows": 2, "down_windows": 10,
                   "min_serving": 4}).start()
    fleet = []
    results = {}
    errors = []
    try:
        for i in range(8):
            fleet.append(BatchWorker(
                url, dispatcher_address=dispatcher.address, batch_size=10,
                reader_factory="batch", worker_id=f"w{i}",
                standby=(i >= 6),      # 2 pooled for the autoscaler
                batch_delay_s=0.03,    # pace so chaos lands mid-epoch
                heartbeat_interval_s=0.5,
                batch_cache=CacheConfig(mode="mem+disk", mem_mb=32.0,
                                        cache_dir=cache_dir).build(),
                reader_kwargs={"workers_count": 2}).start())
        for job in jobs:
            register_job(dispatcher.address, job, weight=1.0)

        def run_job(job):
            try:
                source = ServiceBatchSource(
                    dispatcher.address, job_id=job,
                    client_id=f"client-{job}", ordered=True,
                    heartbeat_interval_s=0.3, dynamic_sync_interval_s=0.1)
                digest = StreamDigest()
                ids = []
                # Fairness wall anchored at the FIRST batch, not at
                # setup: thread scheduling + plan latency jitter is not
                # a scheduling-fairness signal, and on a loaded 1-core
                # host it can dominate a short epoch.
                t0 = None
                for batch in source():
                    if t0 is None:
                        t0 = time.perf_counter()
                    digest.update(batch)
                    ids.extend(int(i) for i in batch["id"])
                results[job] = {
                    "ids": ids,
                    "digest": digest.hexdigest(),
                    "wall_s": time.perf_counter() - (t0 or 0.0),
                }
            except BaseException as exc:  # surfaced after the join
                errors.append((job, exc))

        # Warm the shared cache tier first (one throwaway pass under the
        # implicit default job): the fairness bound compares the three
        # concurrent jobs under LIKE conditions — without this, whichever
        # job starts last rides the entries its peers just decoded and
        # finishes several times faster (shared-cache economics, not a
        # scheduling-fairness signal).
        warm = ServiceBatchSource(dispatcher.address,
                                  client_id="client-warmup",
                                  dynamic_sync_interval_s=0.1)
        assert sum(len(b["id"]) for b in warm()) == 2 * rows  # 2 epochs

        injector = ChaosInjector(
            [("worker-drain", worker_drain_action(lambda: dispatcher,
                                                  min_serving=3)),
             ("job-cancel", job_cancel_action(lambda: dispatcher.address))],
            interval_s=0.35, initial_delay_s=0.2).start()
        threads = [threading.Thread(target=run_job, args=(job,),
                                    name=f"soak-{job}") for job in jobs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        # A fast warm run can outpace the injector's rotation: let it
        # finish at least one full round (both kinds) before stopping —
        # the lifecycle actions are valid against an idle fleet too.
        deadline = time.monotonic() + 8.0
        while (time.monotonic() < deadline
               and {label for _t, label in injector.events}
               < {"job-cancel", "worker-drain"}):
            time.sleep(0.1)
        injector.stop()
        assert not errors, errors
        assert not injector.errors, injector.errors
        assert {label for _t, label in injector.events} >= {
            "job-cancel", "worker-drain"}

        # Exactly-once per job, and byte-identical per-job streams: all
        # three jobs read the same dataset under the same seed in ordered
        # mode, so their digests must be EQUAL (any dup/loss/reorder in
        # any one of them breaks the equality).
        for job in jobs:
            assert (sorted(results[job]["ids"])
                    == sorted(list(range(rows)) * 2)), job  # 2 epochs
        digests = {results[job]["digest"] for job in jobs}
        assert len(digests) == 1, f"per-job streams diverged: {digests}"

        # Max-min fairness bound on per-job delivery rates (equal
        # weights, equal data -> rate ratio = inverse wall ratio).
        walls = [results[job]["wall_s"] for job in jobs]
        ratio = min(walls) / max(walls)
        assert ratio >= 0.7, f"per-job delivery unfair: walls={walls}"

        # The chaos drained (and the autoscaler re-balanced) for real:
        # >=1 admit and >=1 drain journaled.
        snapshot = dispatcher.state_snapshot()
        assert snapshot["autoscale"]["drain"] >= 1
        assert snapshot["autoscale"]["admit"] >= 1
        for job in jobs:
            end_job(dispatcher.address, job)
        before = dispatcher.state_snapshot()
    finally:
        for worker in fleet:
            worker.stop()
        dispatcher.stop()
        _clear_tracked_jobs(jobs)

    # Replay: the journaled fleet history (jobs, autoscale decisions,
    # worker states, steals) restores byte-identically.
    with Dispatcher(port=0, mode="dynamic", num_epochs=2,
                    journal_dir=journal_dir,
                    shuffle_seed=7).start() as restarted:
        after = restarted.state_snapshot()
        volatile = ("fencing_epoch", "recovery")
        assert (json.dumps({k: v for k, v in before.items()
                            if k not in volatile}, sort_keys=True)
                == json.dumps({k: v for k, v in after.items()
                               if k not in volatile}, sort_keys=True))
        assert after["autoscale"] == before["autoscale"]
