"""Expert-parallel mixture-of-experts — the ep axis of the parallelism story.

The reference ships no model compute (SURVEY.md §2: petastorm is an
input-data library); this module completes the parallelism families the TPU
delivery path exercises end-to-end — dp (batch sharding), tp
(``image_classifier``), sp (``sequence_model``), pp (``pipeline``),
model-parallel tables (``tabular_dlrm``) — with true token-routed expert
parallelism.

The construction is the canonical TPU MoE (GShard/Switch recipe):

- the E experts' FFN weights live STACKED ``[E, ...]`` and shard over the
  mesh's ``"ep"`` axis; tokens shard over the same axis (each device is both
  a data shard and an expert host, as in GShard);
- routing is **top-k with a fixed capacity** ``C = k·n·f/E`` per (expert,
  data shard) — ``top_k=1`` is Switch (raw gate probability), ``top_k=2``
  is GShard top-2 (chosen gates renormalized; first choices enqueue before
  any second choice, and a full queue degrades gracefully: the surviving
  choice still contributes). Static shapes throughout — assignments beyond
  capacity are *dropped* (a token losing every assignment outputs exactly
  zero, so the surrounding residual passes it through unchanged).
  Dispatch/combine are one-hot einsum contractions, so the scatter/gather
  the routing implies runs as batched matmuls on the MXU instead of
  dynamic scatters XLA can't tile;
- inside ``shard_map``, two ``lax.all_to_all`` collectives over ``"ep"``
  move ``[E, C, d]`` token slots to their expert owners and back — the ICI
  realization of the NCCL all-to-all GPU MoE stacks hand-write. Backward is
  the same pair of all_to_alls run by transposition — no custom gradient;
- the Switch load-balancing auxiliary loss (num_experts ×
  Σ_e fraction_routed_e · mean_gate_e, = 1 at perfect balance) is returned
  alongside the output so training can keep the router from collapsing.

``reference_forward`` runs the identical routing math (including capacity
drops) densely on one device — the sharded path must match it exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_moe_params(rng, feature_dim, d_model=32, d_hidden=64,
                    num_experts=8, num_classes=10, dtype=jnp.float32):
    """Parameter pytree: replicated embed/router/head + ``[E, ...]``-stacked
    expert FFNs (shard the leading axis over ``"ep"``).

    Keep ``num_experts`` a multiple of the mesh's ep-axis size.
    """
    keys = jax.random.split(rng, 5)
    s = lambda fan: 1.0 / jnp.sqrt(fan)  # noqa: E731
    return {
        "embed": jax.random.normal(keys[0], (feature_dim, d_model),
                                   dtype) * s(feature_dim),
        "router": jax.random.normal(keys[1], (d_model, num_experts),
                                    dtype) * s(d_model),
        "w1": jax.random.normal(keys[2], (num_experts, d_model, d_hidden),
                                dtype) * s(d_model),
        "w2": jax.random.normal(keys[3], (num_experts, d_hidden, d_model),
                                dtype) * s(d_hidden),
        "head": jax.random.normal(keys[4], (d_model, num_classes),
                                  dtype) * s(d_model),
    }


def moe_param_partition_specs():
    """PartitionSpecs over a mesh with an ``"ep"`` axis: expert stacks split
    on their leading (expert) axis; embed/router/head replicated (tiny)."""
    return {"embed": P(), "router": P(),
            "w1": P("ep", None, None), "w2": P("ep", None, None),
            "head": P()}


def _route_topk(gates, capacity, top_k=1):
    """Top-k routing with a fixed per-expert capacity.

    ``gates``: ``[n, E]`` router softmax.  Returns ``(dispatch, combine,
    aux)`` where ``dispatch`` is the ``[n, E, C]`` one-hot token→slot
    assignment (a token can hold up to ``top_k`` slots, in distinct
    experts), ``combine`` carries the router weight back to the token, and
    ``aux`` is the Switch load-balance loss. Tokens whose expert queue is
    already full lose that assignment (top-1: dropped entirely; top-2: the
    surviving choice still contributes — GShard's graceful degradation).

    Choice priority follows GShard: ALL first choices enqueue before any
    second choice (per-expert queue offsets accumulate across choice
    rounds), so a token's 2nd pick cannot evict another token's 1st pick.
    Gate weights: top-1 uses the raw chosen probability (Switch); top-k>1
    renormalizes the chosen gates to sum to 1 (GShard).
    """
    n, num_experts = gates.shape
    _, top_idx = jax.lax.top_k(gates, top_k)  # [n, k]
    # Routing bookkeeping stays int32/f32 regardless of the gate dtype: a
    # bf16 cumsum is exact only to 256, which would collide queue positions
    # (two tokens in one slot) once capacity grows past it.
    onehots = jax.nn.one_hot(top_idx, num_experts, dtype=jnp.int32)  # [n,k,E]
    gate_chosen = jnp.take_along_axis(gates, top_idx, axis=1)  # [n, k]
    if top_k > 1:
        gate_weight = gate_chosen / jnp.maximum(
            gate_chosen.sum(axis=1, keepdims=True), 1e-9)
    else:
        gate_weight = gate_chosen  # Switch: raw probability
    dispatch = jnp.zeros((n, num_experts, capacity), gates.dtype)
    combine = jnp.zeros_like(dispatch)
    counts = jnp.zeros((num_experts,), jnp.int32)  # earlier-choice claims
    for j in range(top_k):
        oh = onehots[:, j]  # [n, E] int
        # Queue position of each token within its chosen expert (0-based):
        # cumsum over the token axis counts earlier claims on the same
        # expert within this choice round, offset by all prior rounds'.
        pos = (jnp.cumsum(oh, axis=0) - 1) * oh + counts[None, :] * oh
        keep = (pos < capacity) & (oh > 0)  # [n, E] bool
        slot = jax.nn.one_hot(jnp.minimum(pos, capacity - 1), capacity,
                              dtype=gates.dtype)  # [n, E, C]
        d_j = slot * keep.astype(gates.dtype)[..., None]
        dispatch = dispatch + d_j
        combine = combine + d_j * gate_weight[:, j][:, None, None]
        counts = counts + oh.sum(axis=0)
    # Switch aux loss over FIRST choices: E * Σ_e (fraction of tokens whose
    # top choice is e) * (mean gate prob of e). 1.0 at perfect balance;
    # grows as routing collapses. Accumulated in f32 — a bf16 mean over
    # many tokens loses the signal.
    fraction = onehots[:, 0].astype(jnp.float32).mean(axis=0)
    importance = gates.astype(jnp.float32).mean(axis=0)
    aux = num_experts * jnp.sum(fraction * importance)
    return dispatch, combine, aux


def _moe_body(w1, w2, router, x, axis_name, capacity, batch_axis=None,
              top_k=1):
    """Per-device MoE layer (runs inside shard_map over ``"ep"``).

    ``w1``/``w2``: this device's expert slice, ``[E_local, d, h]`` /
    ``[E_local, h, d]``. ``x``: local tokens ``[n_local, d]``. Returns the
    local tokens' MoE output (zero rows for dropped tokens) + aux loss.
    """
    gates = jax.nn.softmax(x @ router)  # [n_local, E]
    dispatch, combine, aux = _route_topk(gates, capacity, top_k=top_k)
    # Local contribution to every expert's queue, then all_to_all so each
    # device receives its experts' slots from all data shards: [E, C, d] →
    # [E_local, ep*C, d]. The transpose (backward) is the reverse exchange.
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)
    expert_in = jax.lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=1, tiled=True)
    h = jax.nn.relu(jnp.einsum("egd,edh->egh", expert_in, w1))
    out = jnp.einsum("egh,ehd->egd", h, w2)  # [E_local, ep*C, d]
    out = jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                             tiled=True)  # [E, C, d] back at the data owner
    y = jnp.einsum("ecd,nec->nd", out, combine)
    aux = jax.lax.pmean(aux, axis_name)
    if batch_axis is not None:
        aux = jax.lax.pmean(aux, batch_axis)
    return y, aux


def _capacity(tokens_per_shard, num_experts, capacity_factor, top_k=1):
    """Static per-(expert, data-shard) queue length (scales with ``top_k``:
    k assignments per token compete for slots — GShard's C = k·n·f/E)."""
    return max(1, int(tokens_per_shard * top_k * capacity_factor
                      / num_experts))


def moe_ffn(params, x, mesh, axis_name="ep", capacity_factor=2.0,
            batch_axis=None, top_k=1):
    """Routed expert FFN over tokens ``x`` ``[N, d_model]`` → ``(y, aux)``.

    ``N`` must divide by the mesh's token-sharding extent (ep × optional
    ``batch_axis`` for dp × ep — routing and the capacity budget are then
    per (dp, ep) shard, with expert weights replicated over dp).
    ``top_k``: experts per token (1 = Switch, 2 = GShard top-2).
    """
    from jax import shard_map

    ep = mesh.shape[axis_name]
    if params["w1"].shape[0] % ep:
        raise ValueError(
            f"{params['w1'].shape[0]} experts do not split over the mesh's "
            f"{axis_name!r} axis of {ep} devices")
    token_axes = ((batch_axis,) if batch_axis else ()) + (axis_name,)
    shards = 1
    for a in token_axes:
        shards *= mesh.shape[a]
    if x.shape[0] % shards:
        raise ValueError(f"{x.shape[0]} tokens do not shard over {shards} "
                         f"devices ({token_axes})")
    capacity = _capacity(x.shape[0] // shards, params["w1"].shape[0],
                         capacity_factor, top_k=top_k)
    body = functools.partial(_moe_body, axis_name=axis_name,
                             capacity=capacity, batch_axis=batch_axis,
                             top_k=top_k)
    x_spec = P(token_axes)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name, None, None), P(axis_name, None, None), P(),
                  x_spec),
        out_specs=(x_spec, P()))(
        params["w1"], params["w2"], params["router"], x)


def apply_moe_model(params, features, mesh, axis_name="ep",
                    capacity_factor=2.0, batch_axis=None, top_k=1):
    """``features`` ``[B, F]`` → ``(logits [B, C] f32, aux)`` through
    embed → residual MoE FFN → head."""
    x = features @ params["embed"]
    y, aux = moe_ffn(params, x, mesh, axis_name=axis_name,
                     capacity_factor=capacity_factor, batch_axis=batch_axis,
                     top_k=top_k)
    x = x + y  # dropped tokens pass through the residual unchanged
    return (x @ params["head"]).astype(jnp.float32), aux


def reference_forward(params, features, num_shards=1, capacity_factor=2.0,
                      top_k=1):
    """Dense single-device oracle running the IDENTICAL routing math —
    including per-shard capacity drops when ``num_shards`` matches the
    sharded run's token-shard count — that the ep-sharded path must match."""
    x = features @ params["embed"]
    n, d = x.shape
    capacity = _capacity(n // num_shards, params["w1"].shape[0],
                         capacity_factor, top_k=top_k)
    outs = []
    auxes = []
    for shard in range(num_shards):
        xs = x[shard * (n // num_shards):(shard + 1) * (n // num_shards)]
        gates = jax.nn.softmax(xs @ params["router"])
        dispatch, combine, aux = _route_topk(gates, capacity, top_k=top_k)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, xs)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in, params["w1"]))
        out = jnp.einsum("ech,ehd->ecd", h, params["w2"])
        outs.append(jnp.einsum("ecd,nec->nd", out, combine))
        auxes.append(aux)
    y = x + jnp.concatenate(outs, axis=0)
    logits = (y @ params["head"]).astype(jnp.float32)
    return logits, jnp.mean(jnp.stack(auxes))


def make_moe_train_step(learning_rate=0.05, aux_weight=0.01, mesh=None,
                        axis_name="ep", capacity_factor=2.0,
                        batch_axis=None, top_k=1):
    """``step(params, features, labels, mask) -> (params, loss)`` — masked
    cross-entropy + Switch aux loss, SGD through both all_to_alls."""

    def loss_fn(params, features, labels, mask):
        logits, aux = apply_moe_model(params, features, mesh,
                                      axis_name=axis_name,
                                      capacity_factor=capacity_factor,
                                      batch_axis=batch_axis, top_k=top_k)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        ce = nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)
        return ce + aux_weight * aux

    def step(params, features, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, features, labels,
                                                  mask)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
