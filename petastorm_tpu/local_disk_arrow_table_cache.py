"""Local-disk cache for ``pa.Table`` payloads (batch-reader variant).

Reference parity: ``petastorm/local_disk_arrow_table_cache.py``. Tables are
stored as Arrow IPC files (columnar, memory-mappable) rather than pickles.
"""

from __future__ import annotations

import pyarrow as pa

from petastorm_tpu.local_disk_cache import LocalDiskCache


class LocalDiskArrowTableCache(LocalDiskCache):
    def _serialize(self, value):
        if not isinstance(value, pa.Table):
            raise ValueError(
                f"LocalDiskArrowTableCache stores pa.Table, got {type(value)}"
            )
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, value.schema) as writer:
            writer.write_table(value)
        return sink.getvalue().to_pybytes()

    def _deserialize(self, payload):
        with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
            return reader.read_all()
