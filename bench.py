"""Driver benchmark: end-to-end JAX-loader throughput on a synthetic image set.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

What it measures: rows/sec through the full delivery path — Parquet row
groups → thread-pool workers (parallel column read + PNG decode) →
fixed-size batch collation → async ``jax.device_put`` into device memory —
versus a naive sequential baseline (dummy pool, no pipelining), which is the
performance floor a reference-style single-threaded consumer would see.
Input-stall % for the device consumer rides along (the north-star metric,
BASELINE.md).
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", "768"))
ROWS_PER_RG = 64
IMAGE_SHAPE = (64, 64, 3)
BATCH = 64
EPOCHS = int(os.environ.get("BENCH_EPOCHS", "2"))


def _write_dataset(url):
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import (CompressedImageCodec,
                                             NdarrayCodec, ScalarCodec)
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("BenchSchema", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("image", np.uint8, IMAGE_SHAPE,
                       CompressedImageCodec("png"), False),
        UnischemaField("features", np.float32, (16,), NdarrayCodec(), False),
        UnischemaField("label", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(0)

    def rows():
        for i in range(ROWS):
            yield {"id": i,
                   "image": rng.randint(0, 255, IMAGE_SHAPE, dtype=np.uint8),
                   "features": rng.rand(16).astype(np.float32),
                   "label": np.int32(i % 10)}

    materialize_rows(url, schema, rows(), rows_per_row_group=ROWS_PER_RG)


def _baseline_rows_per_sec(url):
    """Sequential floor: dummy pool (in-caller-thread), row-at-a-time."""
    from petastorm_tpu import make_reader

    reader = make_reader(url, reader_pool_type="dummy", num_epochs=1,
                         shuffle_row_groups=False)
    n = 0
    t0 = time.perf_counter()
    with reader:
        for _ in reader:
            n += 1
    return n / (time.perf_counter() - t0)


def _pipeline_rows_per_sec(url):
    """Full path: thread pool + JAX loader staging batches onto the device."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    import jax

    workers = min(os.cpu_count() or 4, 16)
    reader = make_reader(url, reader_pool_type="thread",
                         workers_count=workers, num_epochs=EPOCHS,
                         shuffle_row_groups=True)
    loader = make_jax_dataloader(reader, BATCH, last_batch="drop",
                                 non_tensor_policy="drop",
                                 host_prefetch=8, device_prefetch=2)
    rows = 0
    last = None
    t0 = time.perf_counter()
    with loader:
        for batch in loader:
            rows += batch["image"].shape[0]
            last = batch["image"]
    if last is not None:
        jax.block_until_ready(last)
    dt = time.perf_counter() - t0
    return rows / dt, loader.diagnostics


def main():
    import logging

    logging.disable(logging.WARNING)
    tmpdir = tempfile.mkdtemp(prefix="petastorm_tpu_bench_")
    try:
        url = f"file://{os.path.join(tmpdir, 'ds')}"
        _write_dataset(url)
        # Warm the JAX runtime off the clock.
        import jax

        jax.device_put(np.zeros(8)).block_until_ready()

        baseline = _baseline_rows_per_sec(url)
        value, diag = _pipeline_rows_per_sec(url)
        print(json.dumps({
            "metric": "jax_loader_rows_per_sec",
            "value": round(value, 1),
            "unit": "rows/s",
            "vs_baseline": round(value / baseline, 2),
            "baseline_sequential_rows_per_sec": round(baseline, 1),
            "input_stall_pct": diag["input_stall_pct"],
            "device": jax.devices()[0].platform,
        }))
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
