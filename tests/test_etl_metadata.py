"""ETL metadata tests: materialization, _common_metadata, schema round-trips,
row-group enumeration, reference-pickle read compatibility."""

import io
import json
import pickle

import numpy as np
import pyarrow.fs as pafs
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.errors import PetastormMetadataError
from petastorm_tpu.etl.metadata import (
    ROW_GROUPS_PER_FILE_KEY,
    UNISCHEMA_KEY,
    add_to_dataset_metadata,
    get_schema,
    get_schema_from_dataset_url,
    infer_or_load_unischema,
    load_row_groups,
    materialize_rows,
    read_dataset_metadata,
    unischema_from_json,
    unischema_from_reference_pickle,
    unischema_to_json,
    write_rows,
)
from petastorm_tpu.schema.codecs import (
    CompressedImageCodec,
    NdarrayCodec,
    ScalarCodec,
)
from petastorm_tpu.schema.unischema import Unischema, UnischemaField


def _toy_schema():
    return Unischema("Toy", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("name", str, (), ScalarCodec(), True),
        UnischemaField("vec", np.float32, (4,), NdarrayCodec(), False),
        UnischemaField("img", np.uint8, (8, 8, 3), CompressedImageCodec("png"), False),
    ])


def _toy_rows(n=10):
    rng = np.random.RandomState(0)
    return [{
        "id": i,
        "name": f"row{i}",
        "vec": rng.rand(4).astype(np.float32),
        "img": rng.randint(0, 255, (8, 8, 3), dtype=np.uint8),
    } for i in range(n)]


def test_schema_json_roundtrip():
    schema = _toy_schema()
    restored = unischema_from_json(unischema_to_json(schema))
    assert list(restored.fields) == list(schema.fields)
    for name in schema.fields:
        assert restored.fields[name] == schema.fields[name]


def test_materialize_and_load_schema(tmp_path):
    url = f"file://{tmp_path}/ds"
    schema = _toy_schema()
    materialize_rows(url, schema, _toy_rows(), rows_per_row_group=4)
    loaded = get_schema_from_dataset_url(url)
    assert list(loaded.fields) == ["id", "name", "vec", "img"]
    assert loaded.fields["vec"].shape == (4,)


def test_row_group_enumeration_uses_metadata(tmp_path):
    url = f"file://{tmp_path}/ds"
    schema = _toy_schema()
    materialize_rows(url, schema, _toy_rows(10), rows_per_row_group=4)
    fs = pafs.LocalFileSystem()
    path = str(tmp_path / "ds")
    metadata = read_dataset_metadata(fs, path)
    assert ROW_GROUPS_PER_FILE_KEY in metadata
    counts = json.loads(metadata[ROW_GROUPS_PER_FILE_KEY])
    assert sum(counts.values()) == 3  # 10 rows / 4-per-group -> 3 row groups
    pieces = load_row_groups(fs, path)
    assert len(pieces) == 3
    # Materialization persists per-row-group row counts, so the metadata fast
    # path yields fully-resolved pieces — planning arithmetic (equal-step
    # SPMD coordination) never needs a footer read.
    assert [p.num_rows for p in pieces] == [4, 4, 2]
    table = pieces[0].read(fs, columns=["id"])
    assert table.num_rows == 4


def test_parallel_encode_write_matches_serial(tmp_path):
    """encode_workers > 1 must produce the identical dataset (ordered row
    groups, same file rotation) as the serial path."""
    import pyarrow.parquet as pq_mod

    schema = _toy_schema()
    serial_url = f"file://{tmp_path}/serial"
    parallel_url = f"file://{tmp_path}/parallel"
    write_rows(serial_url, schema, _toy_rows(25), rows_per_row_group=4,
               rows_per_file=12)
    write_rows(parallel_url, schema, _toy_rows(25), rows_per_row_group=4,
               rows_per_file=12, encode_workers=4)
    for name in ("serial", "parallel"):
        files = sorted(p.name for p in (tmp_path / name).iterdir()
                       if p.name.endswith(".parquet"))
        assert len(files) == 3  # 12 + 12 + 1 rows
    serial = pq_mod.read_table(str(tmp_path / "serial")).to_pylist()
    parallel = pq_mod.read_table(str(tmp_path / "parallel")).to_pylist()
    assert serial == parallel


def test_load_row_groups_fallback_scan(tmp_path):
    """Without _common_metadata, row groups come from a fragment scan."""
    url = f"file://{tmp_path}/plain"
    schema = _toy_schema()
    write_rows(url, schema, _toy_rows(8), rows_per_row_group=4)
    fs = pafs.LocalFileSystem()
    pieces = load_row_groups(fs, str(tmp_path / "plain"))
    assert len(pieces) == 2
    assert all(p.num_rows == 4 for p in pieces)


def test_infer_or_load(tmp_path):
    url = f"file://{tmp_path}/ds"
    schema = _toy_schema()
    materialize_rows(url, schema, _toy_rows(4))
    fs = pafs.LocalFileSystem()
    loaded, attached = infer_or_load_unischema(fs, str(tmp_path / "ds"))
    assert attached and list(loaded.fields) == list(schema.fields)

    url2 = f"file://{tmp_path}/plain"
    write_rows(url2, schema, _toy_rows(4))
    inferred, attached2 = infer_or_load_unischema(fs, str(tmp_path / "plain"))
    assert not attached2
    assert "id" in inferred.fields


def test_get_schema_missing_raises(tmp_path):
    url = f"file://{tmp_path}/plain"
    write_rows(url, _toy_schema(), _toy_rows(2))
    with pytest.raises(PetastormMetadataError, match="make_batch_reader"):
        get_schema_from_dataset_url(url)


def test_add_to_dataset_metadata_merges(tmp_path):
    url = f"file://{tmp_path}/ds"
    materialize_rows(url, _toy_schema(), _toy_rows(2))
    fs = pafs.LocalFileSystem()
    path = str(tmp_path / "ds")
    add_to_dataset_metadata(fs, path, b"my.key", b"my-value")
    metadata = read_dataset_metadata(fs, path)
    assert metadata[b"my.key"] == b"my-value"
    assert ROW_GROUPS_PER_FILE_KEY in metadata  # prior keys survive


# --- reference-pickle compatibility -------------------------------------

def _fabricate_reference_pickle():
    """Craft a pickle byte-stream shaped like the reference's
    ``dataset-toolkit.unischema.v1`` payload (petastorm module paths,
    pyspark-typed ScalarCodec) without petastorm/pyspark installed.

    Fake ``petastorm.*`` / ``pyspark.sql.types`` modules are injected into
    ``sys.modules`` only for the duration of the dump, so pickle's GLOBAL
    opcodes carry the reference's module paths on the wire.
    """
    import sys
    import types
    from collections import namedtuple

    fake_modules = {}

    def make_module(name):
        mod = types.ModuleType(name)
        fake_modules[name] = mod
        return mod

    m_uni = make_module("petastorm.unischema")
    m_codecs = make_module("petastorm.codecs")
    m_spark = make_module("pyspark.sql.types")
    make_module("petastorm")
    make_module("pyspark")
    make_module("pyspark.sql")

    field_cls = namedtuple("UnischemaField",
                           ["name", "numpy_dtype", "shape", "codec", "nullable"])
    field_cls.__module__ = "petastorm.unischema"
    m_uni.UnischemaField = field_cls

    def plain_class(module, name):
        cls = type(name, (), {})
        cls.__module__ = module.__name__
        setattr(module, name, cls)
        return cls

    uni_cls = plain_class(m_uni, "Unischema")
    scalar_cls = plain_class(m_codecs, "ScalarCodec")
    ndarray_cls = plain_class(m_codecs, "NdarrayCodec")
    int_type_cls = plain_class(m_spark, "IntegerType")

    spark_int = int_type_cls()
    scalar_codec = scalar_cls()
    scalar_codec._spark_type = spark_int
    ndarray_codec = ndarray_cls()

    f1 = field_cls("id", np.int32, (), scalar_codec, False)
    f2 = field_cls("emb", np.dtype("float64"), (3,), ndarray_codec, True)
    schema = uni_cls()
    schema._name = "RefSchema"
    schema._fields = {"id": f1, "emb": f2}

    saved = {k: sys.modules.get(k) for k in fake_modules}
    sys.modules.update(fake_modules)
    try:
        payload = pickle.dumps(schema, protocol=2)
    finally:
        for k, old in saved.items():
            if old is None:
                sys.modules.pop(k, None)
            else:  # pragma: no cover
                sys.modules[k] = old
    return payload


def test_reference_pickle_read_compat():
    payload = _fabricate_reference_pickle()
    schema = unischema_from_reference_pickle(payload)
    assert list(schema.fields) == ["id", "emb"]
    id_field = schema.fields["id"]
    assert id_field.numpy_dtype == np.dtype("int32")
    assert isinstance(id_field.codec, ScalarCodec)
    emb = schema.fields["emb"]
    assert emb.shape == (3,)
    assert isinstance(emb.codec, NdarrayCodec)
    assert emb.nullable


def test_reference_pickle_via_common_metadata(tmp_path):
    """A dataset carrying only the reference's pickled-schema key loads."""
    url = f"file://{tmp_path}/refds"
    schema = _toy_schema()
    write_rows(url, schema, _toy_rows(4))
    fs = pafs.LocalFileSystem()
    path = str(tmp_path / "refds")
    add_to_dataset_metadata(fs, path, UNISCHEMA_KEY, _fabricate_reference_pickle())
    loaded = get_schema(fs, path)
    assert list(loaded.fields) == ["id", "emb"]


def test_restricted_unpickler_refuses_arbitrary_classes():
    evil = pickle.dumps(io.BytesIO())  # io.BytesIO not on the allowlist
    with pytest.raises(Exception, match="refusing|Unpickling"):
        unischema_from_reference_pickle(evil)


def test_restricted_unpickler_refuses_numpy_gadgets():
    """np.save/np.load etc. must NOT be reachable through the unpickler."""
    from petastorm_tpu.etl.metadata import _RestrictedUnpickler

    up = _RestrictedUnpickler(io.BytesIO(b""))
    for gadget in ("save", "savetxt", "load", "fromfile", "frombuffer"):
        with pytest.raises(pickle.UnpicklingError, match="refusing"):
            up.find_class("numpy", gadget)
    assert up.find_class("numpy", "dtype") is np.dtype  # machinery still allowed


def test_scalar_codec_decimal_and_tz_arrow_types_roundtrip():
    import pyarrow as pa

    schema = Unischema("D", [
        UnischemaField("d", __import__("decimal").Decimal, (),
                       ScalarCodec(pa.decimal128(38, 18)), False),
        UnischemaField("t", np.dtype("datetime64[us]"), (),
                       ScalarCodec(pa.timestamp("us", tz="UTC")), False),
    ])
    restored = unischema_from_json(unischema_to_json(schema))
    assert restored.fields["d"].codec.arrow_dtype() == pa.decimal128(38, 18)
    assert restored.fields["t"].codec.arrow_dtype() == pa.timestamp("us", tz="UTC")


def test_write_rows_streams_generator(tmp_path):
    """write_rows must accept a pure generator without materializing it."""
    schema = Unischema("G", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
    ])

    def gen():
        for i in range(1000):
            yield {"id": i}

    url = f"file://{tmp_path}/gen"
    write_rows(url, schema, gen(), rows_per_row_group=128)
    fs = pafs.LocalFileSystem()
    pieces = load_row_groups(fs, str(tmp_path / "gen"))
    assert sum(p.num_rows for p in pieces) == 1000
    assert len(pieces) == 8  # ceil(1000/128)


def test_row_group_size_mb_controls_groups(tmp_path):
    url = f"file://{tmp_path}/sized"
    schema = _toy_schema()
    write_rows(url, schema, _toy_rows(64), row_group_size_mb=1)
    files = list((tmp_path / "sized").glob("*.parquet"))
    assert files
    pf = pq.ParquetFile(files[0])
    assert pf.metadata.num_row_groups >= 1
