"""Content fingerprints for decoded-batch cache keys.

A cached batch sequence is only reusable when *everything that shaped it*
matches: the dataset, the row-group pieces read, the selected fields /
schema view, the batch size and last-batch policy, and any transform. The
fingerprint canonicalizes all of that into one hex digest; changing any
ingredient changes the key, so a stale entry is simply never found (miss →
re-decode → refill) rather than ever being served wrong.

Keys are **order-independent by contract**: what is cached (decoded bytes
in canonical piece order) is separated from how it is served (a seed-tree
permutation composed at serve time — ``service/seedtree.py``), so nothing
that only shapes *serve order* may reach a key. Shuffle seeds, epoch
numbers, and shuffle flags are banned ingredients — epoch 1's fill must
hit on every later epoch, and N jobs running the same dataset under
different seeds must share one disk-tier fill ("decode once"). The ban is
enforced, not advisory: :func:`batch_fingerprint` rejects ``extra`` keys
that smell order-dependent (see ``_ORDER_DEPENDENT_KEYS``), and the tier-1
golden test pins that the shipped keys are invariant to seed/epoch/shuffle
configuration.

Two keying granularities share this function:

- the service worker keys **per piece** (``pieces=[piece_index]``), so an
  epoch's stream is a sequence of per-piece lookups and a re-partitioned
  plan (worker takeover) still hits on the pieces both plans share;
- the JAX loader keys **per reader plan** (``pieces=[(path, row_group),
  ...]``), one entry for the whole epoch's batch sequence.
"""

from __future__ import annotations

import hashlib
import json

#: Bump when the on-wire/cached entry layout changes: old entries must
#: become misses, not deserialization errors.
FINGERPRINT_VERSION = 1

#: ``extra`` key names (exact, case-insensitive) that name an
#: order-dependent ingredient. Serve order is composed at serve time from
#: the seed tree; letting any of these into a key would silently split
#: the cache per seed/epoch and forfeit both the warm-epoch hit rate
#: under shuffle and the cross-job "decode once" disk-tier share. Exact
#: names, not substrings: content-shaping ingredients that merely contain
#: one of these words (``num_epochs`` — how many passes an entry holds —
#: or a hypothetical ``sort_order_version``) must stay usable.
_ORDER_DEPENDENT_KEYS = frozenset((
    "seed", "shuffle_seed", "shard_seed", "random_seed",
    "shuffle", "shuffle_row_groups", "shuffle_buffer_size",
    "epoch", "cache_epoch", "fill_epoch",
    "order", "item_order", "row_order", "piece_order", "serve_order",
))


def _reject_order_dependent(value, path="extra"):
    if isinstance(value, dict):
        for key, child in value.items():
            if str(key).lower() in _ORDER_DEPENDENT_KEYS:
                raise ValueError(
                    f"batch_fingerprint ingredient {path}[{key!r}] is "
                    f"order-dependent: cache keys must exclude "
                    f"serve-order inputs (seed, epoch, shuffle flags) — "
                    f"serve order is composed at serve time "
                    f"(docs/guides/caching.md#shuffle-compatible-serving)")
            _reject_order_dependent(child, f"{path}[{key!r}]")
    elif isinstance(value, (list, tuple)):
        for index, child in enumerate(value):
            _reject_order_dependent(child, f"{path}[{index}]")


def _canonical(value):
    """JSON-stable canonical form; non-JSON leaves fall back to ``repr``
    (transform specs, predicates, NGram objects — their repr is what the
    seed-parity row-group caches already key on)."""
    if isinstance(value, dict):
        return {str(k): _canonical(v) for k, v in sorted(value.items())}
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


def predicate_ingredient(predicate):
    """Canonical key ingredient for a row predicate.

    Wire-form predicates (:class:`~petastorm_tpu.predicates.ColumnPredicate`
    — anything with ``to_wire``) canonicalize to their wire dict, which is
    stable across processes and restarts: the filter-hoisting rewrite
    ships the predicate on stream requests, and a hoisted stream's warm
    disk-tier entries must stay warm after a worker restart. Arbitrary
    predicates fall back to ``repr`` (the seed-parity convention — their
    reprs are already required to be deterministic)."""
    if predicate is None:
        return None
    to_wire = getattr(predicate, "to_wire", None)
    if callable(to_wire):
        return to_wire()
    return repr(predicate)


def batch_fingerprint(dataset_url, pieces, batch_size, fields=None,
                      transform=None, factory=None, extra=None):
    """Hex digest keying a cached batch sequence.

    :param dataset_url: the dataset the batches were decoded from.
    :param pieces: piece identity — indices into the canonical row-group
        enumeration (service worker) or ``(path, row_group)`` pairs (local
        reader plan).
    :param batch_size: rows per collated batch.
    :param fields: the selected fields / schema view (names, regexes, or an
        NGram — anything with a stable repr).
    :param transform: transform config (a TransformSpec or its repr).
    :param factory: which reader family decoded the batches (``"row"`` /
        ``"batch"`` / ``"columnar"`` or a callable's qualname) — the three
        families emit different collation layouts for codec columns.
    :param extra: any further invalidation inputs (filters, predicate,
        last-batch policy, ...). Keys naming order-dependent ingredients
        (seed/epoch/shuffle/order) are rejected — see the module
        docstring.
    """
    _reject_order_dependent(extra)
    payload = json.dumps({
        "v": FINGERPRINT_VERSION,
        "url": str(dataset_url),
        "pieces": _canonical(list(pieces)),
        "batch_size": int(batch_size),
        "fields": _canonical(fields),
        "transform": _canonical(transform),
        "factory": _canonical(getattr(factory, "__qualname__", factory)),
        "extra": _canonical(extra),
    }, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()
