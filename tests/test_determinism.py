"""Deterministic, exactly-once delivery: seed-tree order, ordered-mode
byte-identity, v2 watermark resume, and takeover dedup counters.

The determinism contract (docs/guides/service.md#deterministic-order): the
delivered stream is a pure function of ``(seed, epoch, dataset)`` —
invariant to worker count, steal/failure history, and kill/resume. These
are the fast tier-1 checks; the slow chaos-matrix digests live in
``test_service_recovery.py``.
"""

import numpy as np
import pytest

from petastorm_tpu.service import (
    BatchWorker,
    Dispatcher,
    ServiceBatchSource,
)
from petastorm_tpu.service.chaos import StreamDigest
from petastorm_tpu.service.seedtree import (
    batch_permutation,
    fold_in,
    permutation,
    piece_key,
    piece_order,
)

pytestmark = pytest.mark.service


# ---------------------------------------------------------------------------
# seed tree (pure functions)
# ---------------------------------------------------------------------------

def test_fold_in_deterministic_and_collision_free_on_inputs():
    assert fold_in(7, ("epoch", 0)) == fold_in(7, ("epoch", 0))
    assert fold_in(7, ("epoch", 0)) != fold_in(7, ("epoch", 1))
    assert fold_in(7, ("epoch", 0)) != fold_in(8, ("epoch", 0))
    # Namespacing matters: an epoch node and a piece node of the same
    # integer must not alias.
    assert fold_in(7, ("epoch", 3)) != fold_in(7, ("piece", 3))


def test_seed_tree_order_is_pinned_across_versions():
    """The exact permutation is part of the on-disk/resume contract: a
    checkpoint taken by one build must replay the same order in the next.
    Pin golden values so an accidental change to the derivation (digest
    size, byte order, repr scheme) fails loudly instead of silently
    re-shuffling every resumed run."""
    assert fold_in(7, ("epoch", 0)) == 7973815963285622585
    assert piece_order(7, 0, range(8)) == [2, 4, 7, 1, 6, 3, 5, 0]
    assert piece_order(7, 1, range(8)) == [7, 6, 0, 3, 4, 1, 2, 5]
    assert piece_order(8, 0, range(8)) == [7, 0, 5, 6, 4, 3, 1, 2]


def test_piece_order_none_seed_is_ascending():
    assert piece_order(None, 3, [5, 1, 4]) == [1, 4, 5]


def test_batch_permutation_pinned_identity_and_valid():
    """The serve-time intra-piece batch permutation is part of the
    watermark/resume contract (ordinals number the permuted stream): pin
    golden orders so a derivation change fails loudly, and check the
    algebra — identity without a seed, a true permutation with one,
    sensitive to seed/epoch/piece."""
    assert batch_permutation(None, 0, 3, 4) == [0, 1, 2, 3]
    assert batch_permutation(7, 0, 3, 6) == [0, 5, 3, 2, 4, 1]
    assert batch_permutation(7, 1, 3, 6) == [2, 0, 5, 4, 1, 3]
    assert batch_permutation(8, 0, 3, 6) == [3, 0, 1, 4, 2, 5]
    assert batch_permutation(7, 0, 4, 6) != batch_permutation(7, 0, 3, 6)
    for n in (0, 1, 2, 17):
        assert sorted(batch_permutation(7, 2, 0, n)) == list(range(n))
    # The generic node-keyed permutation (the loader's whole-epoch serve).
    assert permutation(fold_in(7, ("cache-epoch", 1)), 5) == [4, 2, 0, 3, 1]


def test_fold_in_is_total_over_any_int_seed():
    """A negative or oversized ``--shuffle-seed`` reaches the request
    handlers unvalidated — it must derive an order, not crash the
    control plane (keys reduce mod 2**64)."""
    assert piece_order(-1, 0, range(4)) == piece_order(-1, 0, range(4))
    assert piece_order(2 ** 80 + 3, 0, range(4)) == piece_order(
        (2 ** 80 + 3) % 2 ** 64, 0, range(4))
    assert sorted(piece_order(-7, 1, range(8))) == list(range(8))


def test_piece_order_subset_stable():
    """The load-bearing property: ANY subset (a client shard, one worker's
    deque, a takeover's survivors) sorts into the same relative order as
    its restriction of the universe order — piece keys are independent, so
    sharding cannot perturb the stream."""
    universe = list(range(50))
    for seed, epoch in ((7, 0), (7, 5), (123456789, 2)):
        full = piece_order(seed, epoch, universe)
        for subset in (universe[::2], universe[10:20], [41, 3, 17, 29, 8]):
            expect = [p for p in full if p in set(subset)]
            assert piece_order(seed, epoch, subset) == expect


def test_piece_key_epoch_reshuffles():
    """Distinct epochs draw distinct key sets — epoch 2 is a fresh
    shuffle, not a replay of epoch 1."""
    keys0 = [piece_key(7, 0, p) for p in range(16)]
    keys1 = [piece_key(7, 1, p) for p in range(16)]
    assert keys0 != keys1
    assert len(set(keys0)) == 16  # no collisions on a small universe


# ---------------------------------------------------------------------------
# StreamDigest (the byte-identity certificate)
# ---------------------------------------------------------------------------

def _batch(seed):
    rng = np.random.RandomState(seed)
    return {"id": np.arange(4) + seed,
            "x": rng.rand(4, 3).astype(np.float32),
            "s": np.array([b"a", b"bb", "ccc", 4], dtype=object)}


def test_stream_digest_equal_for_equal_streams():
    a, b = StreamDigest(), StreamDigest()
    for seed in (1, 2, 3):
        a.update(_batch(seed))
        b.update(_batch(seed))
    assert a.hexdigest() == b.hexdigest()
    assert a.batches == 3


def test_stream_digest_is_order_sensitive():
    a, b = StreamDigest(), StreamDigest()
    a.update(_batch(1)).update(_batch(2))
    b.update(_batch(2)).update(_batch(1))
    assert a.hexdigest() != b.hexdigest()


def test_stream_digest_sees_a_single_flipped_bit():
    tampered = _batch(1)
    shape = tampered["x"].shape
    raw = tampered["x"].view(np.uint8).ravel().copy()
    raw[5] ^= 0x01
    tampered["x"] = raw.view(np.float32).reshape(shape)
    assert (StreamDigest().update(_batch(1)).hexdigest()
            != StreamDigest().update(tampered).hexdigest())


def test_stream_digest_sees_ragged_boundary_shifts():
    """Object-dtype elements are length-prefixed: the same bytes split
    differently across elements must NOT collide."""
    a = {"s": np.array([b"ab", b"c"], dtype=object)}
    b = {"s": np.array([b"a", b"bc"], dtype=object)}
    assert (StreamDigest().update(a).hexdigest()
            != StreamDigest().update(b).hexdigest())


def test_stream_digest_sees_dropped_and_duplicated_batches():
    base = StreamDigest().update(_batch(1)).update(_batch(2))
    dropped = StreamDigest().update(_batch(1))
    duplicated = (StreamDigest().update(_batch(1)).update(_batch(2))
                  .update(_batch(2)))
    assert len({base.hexdigest(), dropped.hexdigest(),
                duplicated.hexdigest()}) == 3


# ---------------------------------------------------------------------------
# ordered delivery: byte-identity across fleet shapes (loopback)
# ---------------------------------------------------------------------------

def _fleet(url, n_workers, shuffle_seed=7, num_epochs=1, batch_delay_s=0.0):
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=num_epochs,
                            shuffle_seed=shuffle_seed).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=7, reader_factory="row", worker_id=f"w{i}",
                    batch_delay_s=batch_delay_s,
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(n_workers)]
    return dispatcher, workers


def _stream_ids(source):
    """Per-batch id lists, in yield order — the sequence the trainer saw."""
    return [[int(i) for i in batch["id"]] for batch in source()]


@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_ordered_delivery_byte_identical_across_fleet_shapes(
        petastorm_dataset, transport):
    """One worker vs two workers, same seed, ordered=True: the yielded
    sequence (not just the multiset) is identical — the contract that
    lets a training run resize its input fleet without changing what the
    model trains on. Parametrized over the delivery tier: the contract
    is transport-invariant (docs/guides/service.md#transport-tiers)."""
    sequences, digests = [], []
    for n_workers in (1, 2):
        dispatcher, workers = _fleet(petastorm_dataset.url, n_workers)
        try:
            source = ServiceBatchSource(dispatcher.address, ordered=True,
                                        transport=transport)
            digest = StreamDigest()
            seq = []
            for batch in source():
                seq.append([int(i) for i in batch["id"]])
                digest.update(batch)
            sequences.append(seq)
            digests.append(digest.hexdigest())
        finally:
            for w in workers:
                w.stop()
            dispatcher.stop()
    assert sequences[0] == sequences[1]
    assert digests[0] == digests[1]
    # And the order is genuinely shuffled, not the ascending fallback.
    flat = [i for ids in sequences[0] for i in ids]
    assert flat != sorted(flat)
    assert sorted(flat) == sorted(int(r["id"]) for r in
                                  petastorm_dataset.rows)


def test_stream_digest_identical_across_transports(petastorm_dataset):
    """Same seed, ordered=True, one run over TCP and one over the shm
    ring: byte-identical delivered streams — the transport tier carries
    bytes, it never gets a say in WHAT is delivered. Also positively
    asserts the shm run actually rode the ring (a silent downgrade to
    TCP would make this test vacuous)."""
    digests, shm_streams = {}, 0
    for transport in ("tcp", "shm"):
        dispatcher, workers = _fleet(petastorm_dataset.url, 2)
        try:
            source = ServiceBatchSource(dispatcher.address, ordered=True,
                                        transport=transport)
            digest = StreamDigest()
            for batch in source():
                digest.update(batch)
            digests[transport] = digest.hexdigest()
            if transport == "shm":
                shm_streams = sum(
                    w.diagnostics_snapshot()["metrics"]
                    ["transport_streams_shm_total"] for w in workers)
        finally:
            for w in workers:
                w.stop()
            dispatcher.stop()
    assert digests["tcp"] == digests["shm"]
    assert shm_streams >= 2, (
        "transport='shm' on loopback must negotiate the ring, not "
        "silently fall back to TCP")


def test_ordered_delivery_reshuffles_per_epoch(petastorm_dataset):
    """Each epoch folds its number into the seed tree: two epochs of one
    run yield different orders, and a second run repeats both exactly."""
    runs = []
    for _ in range(2):
        dispatcher, workers = _fleet(petastorm_dataset.url, 2, num_epochs=2)
        try:
            source = ServiceBatchSource(dispatcher.address, ordered=True)
            runs.append(_stream_ids(source))
        finally:
            for w in workers:
                w.stop()
            dispatcher.stop()
    assert runs[0] == runs[1]
    n_rows = len(petastorm_dataset.rows)
    flat = [i for ids in runs[0] for i in ids]
    epoch1, epoch2 = flat[:n_rows], flat[n_rows:]
    assert sorted(epoch1) == sorted(epoch2)
    assert epoch1 != epoch2  # epoch 2 is a fresh shuffle


# ---------------------------------------------------------------------------
# v2 state_dict: mid-piece watermark resume, exactly-once and bit-exact
# ---------------------------------------------------------------------------

def test_v2_resume_is_bit_identical_from_snapshot_batch(petastorm_dataset):
    """Snapshot mid-piece, resume: the resumed stream must equal the
    uninterrupted run's tail EXACTLY — nothing re-delivered (the pre-v2
    at-least-once shape re-streamed mid-pieces whole), nothing lost."""
    dispatcher, workers = _fleet(petastorm_dataset.url, 2)
    try:
        source = ServiceBatchSource(dispatcher.address, ordered=True)
        full = _stream_ids(source)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()

    # Snapshot after 2 batches: with batch_size=7 over 10-row pieces, the
    # first piece is mid-delivery — the watermark path, not the
    # completed-piece path, carries the resume.
    cut = 2
    dispatcher, workers = _fleet(petastorm_dataset.url, 2)
    try:
        source = ServiceBatchSource(dispatcher.address, ordered=True)
        iterator = source()
        first = [[int(i) for i in next(iterator)["id"]] for _ in range(cut)]
        state = source.state_dict()
        iterator.close()
        assert state["version"] == 2
        assert state["watermarks"], "snapshot landed on a piece boundary"

        resumed = ServiceBatchSource(dispatcher.address, ordered=True,
                                     resume_state=state)
        rest = _stream_ids(resumed)
        assert first == full[:cut]
        assert rest == full[cut:]
        assert resumed.diagnostics["recovery"]["duplicates_dropped"] == 0
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# takeover recovery counters (ISSUE satellite): exactly-once, not
# at-least-once, when a worker dies mid-epoch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_takeover_is_exactly_once_and_reports_zero_duplicates(
        tmp_path, transport):
    """Kill one of two workers mid-epoch: survivors re-serve its pieces
    at their watermarks, so the epoch completes with every sample
    delivered exactly once and ``duplicates_dropped == 0`` (the safety
    net never had to fire), with the dedup/watermark telemetry families
    live. Parametrized over the delivery tier: a kill mid-shm-stream
    must recover exactly like a TCP disconnect (the ring's detach flag
    is the EOF)."""
    from petastorm_tpu.telemetry.registry import REGISTRY
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=60,
                                      rows_per_row_group=5)  # 12 pieces
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=4, reader_factory="batch", worker_id=f"w{i}",
                    batch_delay_s=0.02,
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(2)]
    try:
        source = ServiceBatchSource(dispatcher.address, max_retries=2,
                                    backoff_base=0.02, backoff_max=0.1,
                                    transport=transport)
        got, killed = [], False
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if not killed and len(got) >= 8:
                workers[1].kill()
                killed = True
        assert killed, "dataset too small to kill mid-epoch"
        expected = sorted(int(r["id"]) for r in rows)
        assert sorted(got) == expected  # exactly once: no loss AND no dup
        recovery = source.diagnostics["recovery"]
        assert recovery["takeovers"] >= 1
        assert recovery["duplicates_dropped"] == 0
        families = REGISTRY.families()
        assert "petastorm_service_client_dedup_dropped_total" in families
        assert "petastorm_service_client_watermark_lag" in families
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()
