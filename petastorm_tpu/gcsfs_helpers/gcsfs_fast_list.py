"""Fast recursive listing for GCS-backed datasets.

Reference parity: ``petastorm/gcsfs_helpers/gcsfs_fast_list.py`` — the
reference wraps gcsfs so dataset discovery does ONE recursive object-listing
sweep instead of the O(directories) sequential ``ls`` recursion naive
discovery produces (each ``ls`` is a network round-trip; on a TPU pod the
cost multiplies across hosts at reader construction).

GCS has no real directories — objects are flat keys. A recursive ``find``
therefore returns *files only*; every intermediate "directory" a path-based
consumer (pyarrow dataset discovery, ``fs.walk``) expects to see must be
synthesized. That synthesis — flat listing → directory tree with
pseudo-directory entries → fsspec dircache — is the actual work this module
does; it is pure logic, unit-testable without a network:

- :func:`fast_list` — one ``find(detail=True)`` sweep (a single paginated
  ``objects.list`` API sequence inside gcsfs).
- :func:`build_dircache` — flat ``{path: info}`` → ``{directory: [direct
  child infos]}`` with pseudo-directory entries for every intermediate level.
- :func:`seed_listing_cache` — install that tree into an fsspec filesystem's
  ``dircache`` so subsequent ``ls``/``info``/``isdir`` calls hit memory.
- :class:`FastListingFilesystem` — a read-through wrapper that serves
  ``ls``/``info``/``isdir``/``exists``/``walk`` entirely from one warmed
  sweep.

gcsfs is optional (zero-egress environments): import errors surface as a
clear message only when no explicit ``filesystem`` is supplied.
"""

from __future__ import annotations

DIRECTORY_TYPE = "directory"


def _strip_scheme(url):
    for scheme in ("gs://", "gcs://"):
        if url.startswith(scheme):
            return url[len(scheme):]
    return url


def fast_list(gcs_url, storage_options=None, detail=False, filesystem=None,
              retries=3, retry_base_delay=0.5):
    """Recursively list ``gs://bucket/prefix`` with one ``find()`` sweep.

    ``find`` maps to a single paginated ``objects.list`` API sequence —
    gcsfs follows ``nextPageToken`` internally, so a million-object prefix is
    still one logical call, not one per directory.

    The sweep retries with bounded exponential backoff + jitter
    (:func:`petastorm_tpu.utils.retry_with_backoff`): it runs exactly once
    per reader construction, so one transient listing failure would
    otherwise abort startup for a whole pod. ``FileNotFoundError`` is never
    retried — a missing dataset doesn't become present by waiting.

    :param filesystem: any fsspec-compatible filesystem (tests pass a fake;
        defaults to a ``gcsfs.GCSFileSystem`` built from ``storage_options``).
    :param detail: ``True`` → ``{path: info}``; ``False`` → sorted path list.
    :param retries: additional sweep attempts after the first (0 disables).
    :param retry_base_delay: backoff base in seconds (doubles per attempt).
    """
    from petastorm_tpu.utils import retry_with_backoff

    if filesystem is None:
        try:
            import gcsfs
        except ImportError as exc:  # pragma: no cover - gcsfs absent here
            raise ImportError(
                "gcsfs is required for GCS listing; pip install gcsfs, or "
                "pass an fsspec filesystem explicitly"
            ) from exc

        filesystem = gcsfs.GCSFileSystem(**(storage_options or {}))
    path = _strip_scheme(gcs_url)
    listing = retry_with_backoff(
        lambda: filesystem.find(path, detail=True),
        retries=retries, base_delay=retry_base_delay,
        no_retry_on=(FileNotFoundError, PermissionError),
        description=f"GCS listing sweep of {path!r}")
    if detail:
        return listing
    return sorted(listing)


def build_dircache(root, detail_listing):
    """Flat ``{file path: info}`` → ``{directory: [direct child infos]}``.

    Synthesizes the pseudo-directory entries GCS doesn't store: every
    intermediate path component between ``root`` and each file becomes a
    ``type="directory"`` entry in its parent's child list and gets a child
    list of its own. The result is a *complete* dircache — a consumer walking
    any directory under ``root`` finds an entry, so no listing falls through
    to the network.
    """
    root = _strip_scheme(root).rstrip("/")
    cache = {root: []}
    for path in sorted(detail_listing):
        info = dict(detail_listing[path])
        info.setdefault("name", path)
        info.setdefault("type", "file")
        if path == root or path.endswith("/"):
            # Zero-byte "directory marker" objects some tools create: the
            # prefix itself, or nested 'dir/' keys. They are placeholders,
            # not files — a dircache entry would surface phantom files.
            continue
        if not path.startswith(root + "/"):
            raise ValueError(
                f"Listed path {path!r} is not under the root {root!r}")
        rel = path[len(root) + 1:]
        parts = rel.split("/")
        # Create every intermediate pseudo-directory exactly once.
        parent = root
        for part in parts[:-1]:
            directory = parent + "/" + part
            if directory not in cache:
                cache[directory] = []
                cache[parent].append({
                    "name": directory,
                    "size": 0,
                    "type": DIRECTORY_TYPE,
                })
            parent = directory
        cache[parent].append(info)
    return cache


def seed_listing_cache(filesystem, prefix, detail_listing):
    """Seed ``filesystem.dircache`` from a :func:`fast_list` detail result.

    After seeding, per-directory ``ls`` calls on ``filesystem`` for any
    directory under ``prefix`` resolve from memory (fsspec consults
    ``dircache`` before the network). Returns ``filesystem``.
    """
    for parent, infos in build_dircache(prefix, detail_listing).items():
        filesystem.dircache[parent] = infos
    return filesystem


def warm_gcs_listing(filesystem, gcs_url):
    """One-call convenience: sweep ``gcs_url`` once and seed ``filesystem``'s
    dircache with the complete tree. Returns the number of files listed."""
    listing = fast_list(gcs_url, detail=True, filesystem=filesystem)
    seed_listing_cache(filesystem, _strip_scheme(gcs_url), listing)
    return len(listing)


class FastListingFilesystem:
    """Serves directory metadata for one prefix from a single listing sweep.

    Wraps any fsspec-compatible filesystem: construction performs one
    :func:`fast_list` sweep of ``root`` and builds the pseudo-directory tree;
    ``ls``/``info``/``isdir``/``isfile``/``exists``/``walk`` then answer from
    memory. File *content* operations (``open``, ``cat``, …) pass through to
    the wrapped filesystem untouched — only metadata is cached, so readers
    keep streaming bytes normally.

    This is the reference wrapper's role (``petastorm/gcsfs_helpers``):
    pyarrow dataset discovery over the wrapper costs one API sweep total
    instead of one ``ls`` per directory.
    """

    def __init__(self, filesystem, root):
        self._fs = filesystem
        self._root = _strip_scheme(root).rstrip("/")
        listing = fast_list(self._root, detail=True, filesystem=filesystem)
        self._cache = build_dircache(self._root, listing)
        self._info_by_path = {}
        for infos in self._cache.values():
            for info in infos:
                self._info_by_path[info["name"]] = info

    # --- cached metadata surface -----------------------------------------

    def ls(self, path, detail=False):
        path = _strip_scheme(path).rstrip("/")
        if path in self._cache:
            infos = self._cache[path]
        elif path in self._info_by_path:
            # fsspec contract: ls of a file path returns that file's entry.
            infos = [self._info_by_path[path]]
        else:
            raise FileNotFoundError(path)
        return list(infos) if detail else [i["name"] for i in infos]

    def info(self, path):
        path = _strip_scheme(path).rstrip("/")
        if path == self._root or path in self._cache:
            if path in self._info_by_path:
                return self._info_by_path[path]
            return {"name": path, "size": 0, "type": DIRECTORY_TYPE}
        if path in self._info_by_path:
            return self._info_by_path[path]
        raise FileNotFoundError(path)

    def isdir(self, path):
        return _strip_scheme(path).rstrip("/") in self._cache

    def isfile(self, path):
        info = self._info_by_path.get(_strip_scheme(path).rstrip("/"))
        return info is not None and info["type"] != DIRECTORY_TYPE

    def exists(self, path):
        path = _strip_scheme(path).rstrip("/")
        return path in self._cache or path in self._info_by_path

    def find(self, path, maxdepth=None, withdirs=False, detail=False,
             **kwargs):
        """fsspec ``find`` signature (pyarrow's ``FSSpecHandler`` drives
        recursive ``FileSelector`` traffic through it with
        ``maxdepth``/``withdirs``) — answered from the cached tree."""
        path = _strip_scheme(path).rstrip("/")

        def within(name):
            if not (name.startswith(path + "/") or name == path):
                return False
            if maxdepth is None or name == path:
                return True
            rel_depth = name[len(path) + 1:].count("/") + 1
            return rel_depth <= maxdepth

        out = {name: info for name, info in self._info_by_path.items()
               if (withdirs or info["type"] != DIRECTORY_TYPE)
               and within(name)}
        if withdirs and path in self._cache and path not in out:
            # fsspec includes the base directory itself when withdirs=True.
            out[path] = self.info(path)
        out = dict(sorted(out.items()))
        return out if detail else list(out)

    def walk(self, path=None):
        """Yield ``(dirpath, [subdir names], [file names])`` like ``os.walk``,
        entirely from the cached tree."""
        start = _strip_scheme(path).rstrip("/") if path else self._root
        stack = [start]
        while stack:
            current = stack.pop(0)
            infos = self._cache.get(current, [])
            dirs = [i["name"] for i in infos if i["type"] == DIRECTORY_TYPE]
            files = [i["name"] for i in infos if i["type"] != DIRECTORY_TYPE]
            yield (current,
                   [d.rsplit("/", 1)[1] for d in dirs],
                   [f.rsplit("/", 1)[1] for f in files])
            stack.extend(dirs)

    # --- content operations pass through ---------------------------------

    def __getattr__(self, name):
        return getattr(self._fs, name)
