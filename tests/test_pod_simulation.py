"""Pod-simulation integration: sharding × equal-step × resume together.

Simulates a multi-host pod with one reader+loader per virtual host (the way
each real host constructs its own pipeline) and checks the three invariants
that keep a pjit pod alive and correct:

1. disjoint, exhaustive row coverage across shards;
2. identical step counts on every host (SPMD lockstep), even with ragged
   shards;
3. after a mid-training interrupt + resume on EVERY host, rows are still
   delivered at-least-once with bounded over-delivery.
"""

import collections

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.jax_utils import make_jax_dataloader


HOSTS = 2


@pytest.fixture(scope="module")
def ragged_pod_dataset(tmp_path_factory):
    """5 row groups of 8 rows: 2 hosts get 3 and 2 groups (ragged)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("PodSchema", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("vec", np.float32, (4,), None, False),
    ])
    path = tmp_path_factory.mktemp("pod") / "ds"
    url = f"file://{path}"
    materialize_rows(url, schema,
                     ({"id": i, "vec": np.full(4, i, np.float32)}
                      for i in range(40)),
                     rows_per_row_group=8)
    return url


def _host_loader(url, host, batch_size=4, resume_state=None, epochs=1):
    reader = make_reader(url, reader_pool_type="thread", workers_count=2,
                         num_epochs=epochs, shuffle_row_groups=True,
                         shard_seed=3, cur_shard=host, shard_count=HOSTS,
                         resume_state=resume_state)
    return reader, make_jax_dataloader(reader, batch_size, last_batch="pad",
                                       stage_to_device=False)


def test_pod_lockstep_coverage_and_resume(ragged_pod_dataset):
    url = ragged_pod_dataset
    from petastorm_tpu.jax_utils.sharding import global_step_count

    steps = global_step_count(url, batch_size=4, shard_count=HOSTS,
                              last_batch="pad", shard_seed=3)

    # --- phase 1: every host runs `interrupt` steps, checkpoints ----------
    interrupt = steps // 2
    assert interrupt >= 1
    seen = collections.Counter()
    states = []
    for host in range(HOSTS):
        reader, loader = _host_loader(url, host)
        with loader:
            it = iter(loader)
            for _ in range(interrupt):
                batch = next(it)
                mask = batch.get("__pad_mask__",
                                 np.ones(len(batch["id"]), bool))
                seen.update(np.asarray(batch["id"])[mask].tolist())
            states.append(loader.state_dict())

    # --- phase 2: every host resumes and drains -------------------------
    host_steps = []
    for host in range(HOSTS):
        reader, loader = _host_loader(url, host, resume_state=states[host])
        n = 0
        with loader:
            for batch in loader:
                mask = batch.get("__pad_mask__",
                                 np.ones(len(batch["id"]), bool))
                seen.update(np.asarray(batch["id"])[mask].tolist())
                n += 1
        host_steps.append(n)

    # Coverage: every row delivered at least once across the pod.
    assert set(seen) == set(range(40))
    # At-least-once with bounded duplication: only the row groups in flight
    # at the interrupt may repeat (≤ one per host here), and the shards are
    # disjoint so no row crosses hosts.
    over = [k for k, c in seen.items() if c > 1]
    assert len(over) <= HOSTS * 8
    assert all(seen[k] == 2 for k in over)


def test_pod_equal_steps_without_interrupt(ragged_pod_dataset):
    url = ragged_pod_dataset
    counts = []
    for host in range(HOSTS):
        from petastorm_tpu.jax_utils.sharding import batch_sharding  # noqa: F401
        reader, loader = _host_loader(url, host)
        # Auto-derivation needs a sharding= to trigger; emulate by passing
        # max_batches from the same metadata arithmetic every host runs.
        from petastorm_tpu.jax_utils.sharding import (
            derive_equal_step_max_batches,
        )

        derived = derive_equal_step_max_batches(reader, 4, last_batch="pad")
        with loader:
            steps = 0
            for _ in loader:
                steps += 1
                if derived is not None and steps >= derived:
                    break
        counts.append(steps)
    assert len(set(counts)) == 1, f"hosts diverged: {counts}"


def test_predicate_ragged_pod_locksteps_via_agreement(ragged_pod_dataset):
    """The equal-step DECLINE case (row-level predicate) closed by the
    observe→agree loop: each host counts its deliverable batches with a
    counting pass, agrees the minimum, and every host then steps exactly
    that many times."""
    from petastorm_tpu.jax_utils.sharding import (agree_max_batches,
                                                  count_deliverable_batches)
    from petastorm_tpu.predicates import in_lambda

    url = ragged_pod_dataset
    pred = lambda v: v["id"] % 3 != 0  # noqa: E731 - data-dependent filter

    def host_reader(host):
        return make_reader(url, reader_pool_type="thread", workers_count=2,
                           num_epochs=1, shuffle_row_groups=True,
                           shard_seed=3, cur_shard=host, shard_count=HOSTS,
                           predicate=in_lambda(["id"], pred))

    # observe (one counting pass per host; warns about the declined
    # derivation are not emitted here — max_batches comes from agreement)
    local_counts = [count_deliverable_batches(host_reader(h), 4,
                                              last_batch="drop")
                    for h in range(HOSTS)]
    assert all(c > 0 for c in local_counts)
    # agree (single-process: agree_max_batches(min) == local min)
    agreed = min(agree_max_batches(c) for c in local_counts)
    assert agreed == min(local_counts)

    # lockstep: every host delivers exactly `agreed` batches
    seen = collections.Counter()
    for host in range(HOSTS):
        reader = host_reader(host)
        loader = make_jax_dataloader(reader, 4, last_batch="drop",
                                     max_batches=agreed,
                                     stage_to_device=False)
        steps = 0
        with loader:
            for batch in loader:
                steps += 1
                seen.update(batch["id"].tolist())
        assert steps == agreed, (host, steps, agreed)
    # every delivered row satisfies the predicate; no duplicates pre-cap
    assert all(pred({"id": i}) for i in seen)
    assert max(seen.values()) == 1


@pytest.fixture(scope="module")
def ragged_seq_pod_dataset(tmp_path_factory):
    """Ragged-sequence corpus whose 2 shards carry SKEWED length
    distributions: even row groups hold long docs, odd groups short ones,
    so round-robin sharding gives host 0 mostly-long and host 1
    mostly-short corpora — packed batch counts differ even where row
    counts would not. Same corpus the real two-process pod dryrun uses
    (one writer, no drift between the test and the dryrun)."""
    import __graft_entry__

    path = tmp_path_factory.mktemp("ragged_pod") / "ds"
    url = f"file://{path}"
    __graft_entry__._write_pod_ragged_dataset(url)
    return url


def test_packed_pod_locksteps_via_agreement(ragged_seq_pod_dataset):
    """Packed equal-step counting (VERDICT r4 next #5): the packed path's
    batch count is data-dependent through first-fit placement, so each
    virtual host observes its own count via ``count_packed_batches``, the
    pod agrees the min, and every host then iterates exactly that many
    packed batches under a global sharding — no hand-derived constant."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import (PACK_SEGMENT_KEY,
                                         agree_max_batches,
                                         count_packed_batches,
                                         make_packed_jax_dataloader)

    url = ragged_seq_pod_dataset
    slot_len, slots = 24, 4

    def host_reader(host):
        return make_columnar_reader(url, num_epochs=1,
                                    shuffle_row_groups=False,
                                    cur_shard=host, shard_count=HOSTS)

    local_counts = [
        count_packed_batches(host_reader(h), slot_len, slots,
                             sequence_fields=["seq"],
                             length_field="length")
        for h in range(HOSTS)]
    assert all(c > 0 for c in local_counts)
    assert len(set(local_counts)) > 1, \
        f"fixture must produce skewed packed counts, got {local_counts}"
    agreed = min(agree_max_batches(c) for c in local_counts)
    assert agreed == min(local_counts)

    # The counting helper must agree EXACTLY with what the packed loader
    # emits uncapped (same pack_ragged drain by construction).
    for h in range(HOSTS):
        loader = make_packed_jax_dataloader(
            host_reader(h), slot_len, slots, sequence_fields=["seq"],
            length_field="length", stage_to_device=False)
        with loader:
            full = sum(1 for _ in loader)
        assert full == local_counts[h], (h, full, local_counts)

    # Lockstep under a sharding: every host delivers exactly `agreed`
    # packed batches as sharded jax.Arrays.
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    for h in range(HOSTS):
        loader = make_packed_jax_dataloader(
            host_reader(h), slot_len, slots, sequence_fields=["seq"],
            length_field="length", sharding=sharding, max_batches=agreed)
        steps = 0
        with loader:
            for batch in loader:
                assert batch["seq"].shape == (slots, slot_len, 2)
                assert PACK_SEGMENT_KEY in batch
                steps += 1
        assert steps == agreed, (h, steps, agreed)


def test_count_packed_batches_rejects_infinite_reader(ragged_seq_pod_dataset):
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import count_packed_batches

    reader = make_columnar_reader(ragged_seq_pod_dataset, num_epochs=None)
    try:
        with pytest.raises(ValueError, match="num_epochs=None"):
            count_packed_batches(reader, 24, 4, sequence_fields=["seq"],
                                 length_field="length")
    finally:
        reader.stop()
        reader.join()


def test_agree_max_batches_multihost_semantics(monkeypatch):
    """min / host0 reduction over the (mocked) pod collective."""
    import types

    import petastorm_tpu.jax_utils.sharding as sh

    class _FakeJax:
        @staticmethod
        def process_count():
            return 3

    monkeypatch.setitem(
        __import__("sys").modules, "jax", _FakeJax())
    fake_mh = types.SimpleNamespace(
        process_allgather=lambda x: np.asarray([[7], [4], [9]]))
    monkeypatch.setitem(
        __import__("sys").modules, "jax.experimental", types.SimpleNamespace(
            multihost_utils=fake_mh))
    monkeypatch.setitem(
        __import__("sys").modules, "jax.experimental.multihost_utils",
        fake_mh)
    assert sh.agree_max_batches(7) == 4
    assert sh.agree_max_batches(7, reduce="host0") == 7
    with pytest.raises(ValueError, match="reduce"):
        sh.agree_max_batches(7, reduce="max")


def test_agree_max_batches_single_process_identity():
    from petastorm_tpu.jax_utils.sharding import agree_max_batches

    assert agree_max_batches(11) == 11
