"""Read the hello-world dataset through the torch DataLoader.

Reference analogue: ``examples/hello_world/petastorm_dataset/pytorch_hello_world.py``.
"""

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.pytorch import DataLoader


def pytorch_hello_world(dataset_url):
    reader = make_reader(dataset_url, schema_fields=["id", "image1"],
                         num_epochs=1)
    with DataLoader(reader, batch_size=4) as loader:
        for batch in loader:
            print(batch["id"].tolist(), batch["image1"].shape)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default="file:///tmp/hello_world_dataset")
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)
