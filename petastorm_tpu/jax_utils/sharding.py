"""Sharding helpers: pod-aware reader shards + global jax.Array assembly.

TPU-first replacement for the reference's implicit Horovod-rank sharding
(SURVEY.md §5 "distributed communication backend"): the reference expects the
user to pass ``cur_shard=hvd.rank(), shard_count=hvd.size()``; here the
defaults come from ``jax.process_index()/process_count()`` so a pod "just
works", and batches can be assembled into globally-sharded ``jax.Array`` s for
pjit. The data plane still never crosses hosts — each host reads its own row
groups from the (DCN-attached) store; ICI collectives belong to the training
step, exactly as the scaling recipe prescribes.
"""

from __future__ import annotations


def default_shard_options(cur_shard=None, shard_count=None):
    """Fill (cur_shard, shard_count) from the JAX runtime when unset.

    Single-process (or JAX absent): (None, None) — no sharding, matching the
    reference's default behavior.
    """
    if cur_shard is not None or shard_count is not None:
        return cur_shard, shard_count
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # pragma: no cover - jax missing/uninitialized
        pass
    return None, None


def batch_sharding(mesh, axis="data"):
    """NamedSharding that splits the batch (leading) axis over ``mesh[axis]``.

    The standard data-parallel input sharding: every other array dim is
    replicated; model/tensor axes of the mesh replicate the input so the
    training step's pjit can re-shard activations as it likes.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))


def local_data_to_global_array(sharding, array):
    """Host-local numpy batch → globally-sharded ``jax.Array``.

    Wraps ``jax.make_array_from_process_local_data``: each host contributes
    its shard of the global batch; XLA never moves the data over DCN — the
    global array is metadata stitching over per-host HBM buffers.
    """
    import jax

    return jax.make_array_from_process_local_data(sharding, array)
