"""Read a plain-Parquet store with make_batch_reader.

Reference analogue: ``examples/hello_world/external_dataset/python_hello_world_external.py``.
"""

import argparse

from petastorm_tpu import make_batch_reader


def python_hello_world_external(dataset_url):
    with make_batch_reader(dataset_url) as reader:
        for batch in reader:
            print(len(batch.id), "rows; first:", batch.id[0], batch.value2[0])


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default="file:///tmp/external_dataset")
    args = parser.parse_args()
    python_hello_world_external(args.dataset_url)
