"""Model-based fleet planner: fitted throughput model + what-if replay.

Replaces the streak heuristics of :class:`~petastorm_tpu.service.fleet.
AutoscalePlanner` with the tf.data-service-style model (PAPERS.md,
2210.14826): fit a per-worker throughput model from observed
``(serving_count, fleet rows/s)`` samples plus the journaled
``stage_profile`` records (PR 19), predict the *marginal* rows/s of the
next admit/drain, and only apply a decision after a **what-if replay**
over the sample history validates the model against what was actually
measured.  Every decision is journaled through the dispatcher's
``fleet_plan`` WAL op so scaling history replays byte-identically
(2604.21275's reproducibility framing).

Pure model + planner: no threads, no clocks, no sockets.  The
:class:`~petastorm_tpu.service.fleet.AutoscaleController` drives
``plan()`` once per interval with the dispatcher's ``fleet_signals()``
and journals what comes back.
"""

from .fleet import AutoscaleConfig

#: Throughput samples kept for fitting/what-if replay.  Small on purpose:
#: the model must track the *current* workload, not ancient history.
SAMPLES_KEPT = 64

#: A fleet-size decision must be predicted to change fleet throughput by
#: at least this fraction of one worker's modeled rate, otherwise the
#: planner holds (hysteresis against model noise).
MIN_MARGINAL_FRACTION = 0.5

#: What-if replay gate: median relative error of predict(n) vs the
#: measured samples must stay under this before any decision is applied.
WHATIF_TOLERANCE = 0.25


def fit_throughput_model(samples, stage_profiles=()):
    """Fit ``predict(n) = min(n * per_worker, ceiling)`` from samples.

    ``samples`` is an iterable of ``(serving_count, fleet_rows_per_s)``.
    The per-worker rate is taken from the *least saturated* fleet sizes
    (smallest n observed), where the linear regime holds; the ceiling is
    the best fleet throughput ever measured once adding workers stops
    paying (sublinear scaling detected).  ``stage_profiles`` (journaled
    ``stage_profile`` WAL records) provide a prior for the per-worker
    rate when samples are sparse: the reciprocal of the mean per-row
    critical-path time.
    """
    by_n = {}
    for n, rows_s in samples:
        n = int(n)
        if n <= 0 or rows_s is None or rows_s <= 0:
            continue
        by_n.setdefault(n, []).append(float(rows_s))
    means = {n: sum(v) / len(v) for n, v in by_n.items()}

    per_worker = None
    if means:
        n_min = min(means)
        per_worker = means[n_min] / n_min

    if per_worker is None:
        per_worker = _profile_rate_prior(stage_profiles)
    if per_worker is None or per_worker <= 0:
        return None

    ceiling = None
    if means:
        best = max(means.values())
        n_max = max(means)
        # Saturation: at the largest observed fleet the measured rate
        # fell clearly short of linear scaling — cap the model there.
        if n_max > min(means) and means[n_max] < 0.9 * n_max * per_worker:
            ceiling = best
    return ThroughputModel(per_worker, ceiling)


def _profile_rate_prior(stage_profiles):
    """Per-worker rows/s prior from journaled stage profiles: one over
    the mean per-span critical-path time of the heaviest stage (spans in
    this pipeline are batch-grained, so this is deliberately a coarse
    order-of-magnitude prior, not a fit)."""
    worst_mean_us = 0.0
    for record in stage_profiles or ():
        profile = (record or {}).get("profile") or {}
        for stats in profile.values():
            mean_us = (stats or {}).get("mean_us")
            if mean_us and mean_us > worst_mean_us:
                worst_mean_us = float(mean_us)
    if worst_mean_us <= 0:
        return None
    return 1e6 / worst_mean_us


class ThroughputModel(object):
    """``predict(n) = min(n * per_worker, ceiling)`` with marginals."""

    def __init__(self, per_worker_rows_s, ceiling_rows_s=None):
        self.per_worker_rows_s = float(per_worker_rows_s)
        self.ceiling_rows_s = (None if ceiling_rows_s is None
                               else float(ceiling_rows_s))

    def predict(self, n):
        """Modeled fleet rows/s with ``n`` serving workers."""
        if n <= 0:
            return 0.0
        linear = n * self.per_worker_rows_s
        if self.ceiling_rows_s is not None:
            return min(linear, self.ceiling_rows_s)
        return linear

    def marginal(self, n):
        """Predicted rows/s gained by admitting worker ``n + 1``."""
        return self.predict(n + 1) - self.predict(n)

    def to_dict(self):
        return {"per_worker_rows_s": self.per_worker_rows_s,
                "ceiling_rows_s": self.ceiling_rows_s}


def whatif_replay(model, samples):
    """Replay the model over measured history: median relative error of
    ``predict(n)`` vs each recorded ``(n, rows_s)`` sample.

    Returns ``(error, ok)`` where ``error`` is the median relative error
    (``None`` with ``ok=False`` when there is nothing to replay) and
    ``ok`` means the model is trustworthy enough to act on
    (``error <= WHATIF_TOLERANCE``).
    """
    errors = []
    for n, rows_s in samples:
        if n <= 0 or rows_s is None or rows_s <= 0:
            continue
        predicted = model.predict(n)
        errors.append(abs(predicted - rows_s) / rows_s)
    if not errors:
        return None, False
    errors.sort()
    mid = len(errors) // 2
    if len(errors) % 2:
        error = errors[mid]
    else:
        error = (errors[mid - 1] + errors[mid]) / 2.0
    return error, error <= WHATIF_TOLERANCE


class ModelPlanner(object):
    """Drop-in for :class:`~petastorm_tpu.service.fleet.AutoscalePlanner`:
    same ``plan(signals) -> [decision]`` contract, but decisions come
    from predicted marginal rows/s instead of backlog streaks.

    Extra signal consumed (both optional, planner degrades to hold):

    - ``signals["rates"]``: per-worker delivered rows/s (already in
      ``fleet_signals``) — summed into a throughput sample each tick.
    - ``signals["stage_profiles"]``: journaled profile records, the
      sparse-sample prior.

    Decisions carry ``model``/``predicted_rows_s``/``whatif_error`` keys
    so the controller can journal them as ``fleet_plan`` WAL records.
    Probe/revert: every admit/drain is a *probe*; if, ``probe_windows``
    ticks later, measured throughput landed outside the what-if
    tolerance of the prediction, the opposite action is issued and the
    model's ceiling is re-anchored to what was actually measured
    (autotuner-style revert, PR 10).
    """

    def __init__(self, config=None, probe_windows=3):
        self._config = (AutoscaleConfig() if config is None
                        else AutoscaleConfig.coerce(config))
        self._probe_windows = max(1, int(probe_windows))
        self._samples = []          # [(n_serving, fleet_rows_s)]
        self._cooldown = 0
        self._probe = None          # {"action","worker_id","predicted",
        #                             "age","n_target"}
        self.last_model = None
        self.last_whatif_error = None

    @property
    def config(self):
        """The coerced :class:`AutoscaleConfig` (controller parity with
        :class:`~petastorm_tpu.service.fleet.AutoscalePlanner`)."""
        return self._config

    # -- sample plumbing ------------------------------------------------

    def observe(self, n_serving, rows_s):
        """Record one throughput sample (test seam; ``plan`` does this
        from signals automatically)."""
        if n_serving > 0 and rows_s and rows_s > 0:
            self._samples.append((int(n_serving), float(rows_s)))
            del self._samples[:-SAMPLES_KEPT]

    @property
    def samples(self):
        return list(self._samples)

    # -- planning -------------------------------------------------------

    def plan(self, signals):
        serving = list(signals.get("serving", ()))
        standby = list(signals.get("standby", ()))
        draining = list(signals.get("draining", ()))
        rates = signals.get("rates") or {}
        n = len(serving)

        fleet_rows_s = sum(r for r in rates.values() if r and r > 0)
        self.observe(n, fleet_rows_s)

        # Retire finished drains exactly like the streak planner: a
        # draining worker with no backlog left goes back to standby.
        backlog = signals.get("backlog") or {}
        decisions = []
        for worker_id in draining:
            if not backlog.get(worker_id):
                decisions.append({"action": "retire", "worker_id": worker_id,
                                  "reason": "drain complete"})

        model = fit_throughput_model(
            self._samples, signals.get("stage_profiles") or ())
        self.last_model = model
        if model is None or n == 0:
            return decisions

        error, ok = whatif_replay(model, self._samples)
        self.last_whatif_error = error

        if self._probe is not None:
            decision = self._check_probe_locked(model, fleet_rows_s, n)
            if decision is not None:
                decisions.append(decision)
            return decisions

        if self._cooldown > 0:
            self._cooldown -= 1
            return decisions
        if not ok:
            # Model not validated by what-if replay: never act on it.
            return decisions

        threshold = MIN_MARGINAL_FRACTION * model.per_worker_rows_s
        if standby and model.marginal(n) >= threshold:
            worker_id = sorted(standby)[0]
            decisions.append(self._probe_decision(
                "admit", worker_id, model, error,
                predicted=model.predict(n + 1), n_target=n + 1,
                reason="marginal %.1f rows/s >= %.1f"
                       % (model.marginal(n), threshold)))
        elif (n > self._config.min_serving
              and model.marginal(n - 1) < threshold):
            # The n-th worker buys less than the hysteresis threshold:
            # predicted fleet loss of draining it is negligible.
            worker_id = self._drain_candidate(serving, rates)
            decisions.append(self._probe_decision(
                "drain", worker_id, model, error,
                predicted=model.predict(n - 1), n_target=n - 1,
                reason="marginal %.1f rows/s < %.1f"
                       % (model.marginal(n - 1), threshold)))
        return decisions

    @staticmethod
    def _drain_candidate(serving, rates):
        """Drain the slowest serving worker (ties broken by id so the
        choice is deterministic and journal-replayable)."""
        return sorted(serving,
                      key=lambda w: (rates.get(w) or 0.0, w))[0]

    def _probe_decision(self, action, worker_id, model, error, predicted,
                        n_target, reason):
        self._probe = {"action": action, "worker_id": worker_id,
                       "predicted": predicted, "age": 0,
                       "n_target": n_target}
        self._cooldown = self._config.cooldown_windows
        return {"action": action, "worker_id": worker_id,
                "reason": reason, "model": model.to_dict(),
                "predicted_rows_s": predicted, "whatif_error": error,
                "probe": True}

    def _check_probe_locked(self, model, fleet_rows_s, n):
        """Age the outstanding probe; revert it if measurement lands
        outside tolerance of its prediction once it matures."""
        probe = self._probe
        probe["age"] += 1
        if probe["age"] < self._probe_windows:
            return None
        self._probe = None
        predicted = probe["predicted"]
        if n != probe["n_target"]:
            # The fleet moved under us (operator action, worker death):
            # the probe is unjudgeable — drop it without reverting.
            return None
        if predicted > 0 and fleet_rows_s > 0:
            miss = abs(fleet_rows_s - predicted) / predicted
            if miss > WHATIF_TOLERANCE and probe["action"] == "admit":
                # Admit under-delivered: the fleet is ceiling-bound at
                # what we actually measured.  Re-anchor and revert.
                self._samples.append((n, fleet_rows_s))
                del self._samples[:-SAMPLES_KEPT]
                self._cooldown = self._config.cooldown_windows
                return {"action": "drain", "worker_id": probe["worker_id"],
                        "reason": "probe revert: measured %.1f vs "
                                  "predicted %.1f rows/s"
                                  % (fleet_rows_s, predicted),
                        "model": model.to_dict(),
                        "predicted_rows_s": model.predict(n - 1),
                        "whatif_error": miss, "probe": True}
        return None
