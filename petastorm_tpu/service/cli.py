"""``python -m petastorm_tpu.service`` — run a dispatcher or a batch worker.

A two-worker loopback service on one machine::

    python -m petastorm_tpu.service dispatcher --port 7077 --mode static
    python -m petastorm_tpu.service worker --dispatcher 127.0.0.1:7077 \\
        --dataset-url file:///data/ds --reader batch --batch-size 512 &
    python -m petastorm_tpu.service worker --dispatcher 127.0.0.1:7077 \\
        --dataset-url file:///data/ds --reader batch --batch-size 512 &

then, trainer-side::

    source = ServiceBatchSource(("127.0.0.1", 7077))
    loader = JaxDataLoader(None, 512, batch_source=source)

Each process prints one JSON line with its bound address (port 0 picks a
free port) and serves until SIGINT.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def parse_address(value):
    """``"host:port"`` (or bare ``"port"``) → ``(host, port)``."""
    host, _, port = str(value).rpartition(":")
    return (host or "127.0.0.1", int(port))


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m petastorm_tpu.service",
        description="Disaggregated data service: dispatcher owns split "
                    "assignment; workers serve collated numpy batches over "
                    "TCP (docs/guides/service.md)")
    sub = parser.add_subparsers(dest="role", required=True)

    disp = sub.add_parser("dispatcher", help="run the split dispatcher")
    disp.add_argument("--host", default="127.0.0.1")
    disp.add_argument("--port", type=int, default=7077,
                      help="0 picks a free port (printed on stdout)")
    disp.add_argument("--mode", choices=["static", "fcfs"], default="static")
    disp.add_argument("--num-epochs", type=int, default=1,
                      help="epochs to serve; 0 means serve forever")
    disp.add_argument("--journal-dir", default=None,
                      help="crash-recovery journal directory (JSONL WAL + "
                           "compacted snapshots); a restarted dispatcher "
                           "replays it and resumes with identical "
                           "assignments. Omit for in-memory-only state")
    disp.add_argument("--lease-timeout", type=float, default=30.0,
                      help="seconds without a heartbeat before a worker is "
                           "evicted; 0 disables lease expiry")
    disp.add_argument("--journal-fsync", action="store_true",
                      help="fsync the WAL per record (durable against OS "
                           "crash; default survives process crashes)")

    work = sub.add_parser("worker", help="run a batch worker")
    work.add_argument("--dispatcher", default=None,
                      help="dispatcher address host:port (omit to run an "
                           "unregistered worker addressed directly)")
    work.add_argument("--host", default="127.0.0.1")
    work.add_argument("--port", type=int, default=0)
    work.add_argument("--dataset-url", required=True)
    work.add_argument("--batch-size", type=int, default=256)
    work.add_argument("--reader", choices=["row", "batch", "columnar"],
                      default="row",
                      help="row=make_reader, batch=make_batch_reader, "
                           "columnar=make_columnar_reader")
    work.add_argument("--workers-count", type=int, default=4,
                      help="reader pool size inside this worker")
    work.add_argument("--reader-pool-type", default="thread",
                      choices=["thread", "process", "dummy"])
    work.add_argument("--worker-id", default=None)
    work.add_argument("--heartbeat-interval", type=float, default=5.0,
                      help="seconds between dispatcher lease renewals "
                           "(also drives automatic re-registration after "
                           "a dispatcher restart); 0 disables")
    return parser


def build_service_node(args):
    """argparse namespace → an unstarted Dispatcher or BatchWorker."""
    if args.role == "dispatcher":
        from petastorm_tpu.service.dispatcher import Dispatcher

        return Dispatcher(host=args.host, port=args.port, mode=args.mode,
                          num_epochs=args.num_epochs or None,
                          journal_dir=args.journal_dir,
                          lease_timeout_s=args.lease_timeout or None,
                          journal_fsync=args.journal_fsync)
    from petastorm_tpu.service.worker import BatchWorker

    return BatchWorker(
        args.dataset_url,
        dispatcher_address=(parse_address(args.dispatcher)
                            if args.dispatcher else None),
        host=args.host, port=args.port, batch_size=args.batch_size,
        reader_factory=args.reader, worker_id=args.worker_id,
        heartbeat_interval_s=args.heartbeat_interval or None,
        reader_kwargs={"workers_count": args.workers_count,
                       "reader_pool_type": args.reader_pool_type})


def main(argv=None, run_seconds=None, stop_event=None):
    """Entry point. ``run_seconds`` bounds the serve loop and
    ``stop_event`` stops it early (both for tests — an embedding test must
    be able to tear the node down instead of leaking its sockets for the
    rest of ``run_seconds``); the default serves until SIGINT/SIGTERM."""
    args = _build_parser().parse_args(argv)
    node = build_service_node(args)
    node.start()
    host, port = node.address
    print(json.dumps({"role": args.role, "host": host, "port": port,
                      **({"worker_id": node.worker_id}
                         if args.role == "worker" else {})}),
          flush=True)
    stop = stop_event if stop_event is not None else threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:
        pass  # not the main thread (tests)
    try:
        stop.wait(timeout=run_seconds)
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
