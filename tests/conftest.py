"""Test-session configuration.

JAX runs on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md §4 "implication for the rebuild").
Env vars must be set before jax is first imported anywhere in the test run.
"""

import os

# Force CPU even when the session has a real TPU attached (JAX_PLATFORMS=axon):
# the suite needs 8 virtual devices to exercise sharding; the single real chip
# is for bench.py only. The axon sitecustomize overrides the JAX_PLATFORMS env
# var via jax.config, so we must override back through jax.config too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from types import SimpleNamespace  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def petastorm_dataset(tmp_path_factory):
    """Session-scoped synthetic petastorm-format dataset (30 rows, 3 row
    groups) — the analogue of the reference's ``create_test_dataset`` fixture."""
    from petastorm_tpu.test_util.dataset_factory import TestSchema, create_test_dataset

    path = tmp_path_factory.mktemp("data") / "petastorm_ds"
    url = f"file://{path}"
    rows = create_test_dataset(url, rows_count=30, rows_per_row_group=10)
    return SimpleNamespace(url=url, path=str(path), rows=rows, schema=TestSchema)


@pytest.fixture(scope="session")
def scalar_dataset(tmp_path_factory):
    """Session-scoped plain-Parquet dataset for make_batch_reader tests."""
    from petastorm_tpu.test_util.dataset_factory import ScalarSchema, create_test_scalar_dataset

    path = tmp_path_factory.mktemp("data") / "scalar_ds"
    url = f"file://{path}"
    rows = create_test_scalar_dataset(url, rows_count=30, rows_per_row_group=10)
    return SimpleNamespace(url=url, path=str(path), rows=rows, schema=ScalarSchema)
