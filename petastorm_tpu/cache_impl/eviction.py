"""Shared size-budget LRU eviction for on-disk cache directories.

One policy, two users: the :class:`~petastorm_tpu.cache_impl.batch_cache.
BatchCache` disk tier and the seed-parity row-group caches
(``local_disk_cache.LocalDiskCache`` / ``LocalDiskArrowTableCache``) —
before this module each grew its own ad-hoc scan. Eviction is measured
(actual ``stat`` sizes, never estimates) and LRU by access time with an
mtime fallback (``relatime``/``noatime`` mounts may not advance atime; the
caches ``utime`` on every hit so both clocks move).

Concurrent-safe by construction: entries are one file per key written via
temp-file + atomic rename, so a concurrently-deleted file during the scan
is skipped, and two processes evicting the same directory converge on the
same budget.
"""

from __future__ import annotations

import os


def dir_size(path, suffix):
    """Total bytes of ``suffix``-named entries under ``path``."""
    total = 0
    try:
        names = os.listdir(path)
    except OSError:
        return 0
    for name in names:
        if not name.endswith(suffix):
            continue
        try:
            total += os.stat(os.path.join(path, name)).st_size
        except OSError:
            continue
    return total


def evict_dir_to_limit(path, size_limit, suffix):
    """Delete least-recently-used ``suffix`` entries under ``path`` until
    the directory fits ``size_limit`` bytes. Returns ``(files_deleted,
    bytes_deleted)`` — callers feed these into their eviction counters.

    ``size_limit=None`` disables the budget (nothing is deleted).
    """
    if size_limit is None:
        return 0, 0
    entries = []
    total = 0
    try:
        names = os.listdir(path)
    except OSError:
        return 0, 0
    for name in names:
        if not name.endswith(suffix):
            continue
        full = os.path.join(path, name)
        try:
            stat = os.stat(full)
        except OSError:
            continue
        # atime when the mount maintains it, else mtime: the caches utime()
        # entries on every hit, so either clock orders by recency.
        recency = max(stat.st_atime, stat.st_mtime)
        entries.append((recency, stat.st_size, full))
        total += stat.st_size
    deleted = freed = 0
    if total <= size_limit:
        return deleted, freed
    entries.sort()  # least recently used first
    for _, size, full in entries:
        if total <= size_limit:
            break
        try:
            os.unlink(full)
        except OSError:
            continue
        total -= size
        deleted += 1
        freed += size
    return deleted, freed
