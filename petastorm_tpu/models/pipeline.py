"""Pipeline-parallel encoder stack — the pp axis of the parallelism story.

The reference has no model compute at all (SURVEY.md §2: petastorm is a
data-input library); this module exists so the TPU delivery path exercises
every parallelism family a training stack uses: dp (batch sharding), tp
(tensor-parallel MLP in ``image_classifier``), sp (ring/Ulysses in
``sequence_model``), ep/model-parallel tables (``tabular_dlrm``) — and pp,
here.

The construction is the idiomatic JAX pipeline (scaling-book recipe):

- the stack's S homogeneous residual blocks live STACKED ``[S, ...]`` and
  shard over the mesh's ``"pp"`` axis — each device holds one stage's
  weights;
- inside ``shard_map``, a ``lax.scan`` over ``M + S - 1`` ticks runs the
  classic GPipe schedule: every tick each device applies its block to its
  current microbatch and ``ppermute``-shifts the activation to the next
  stage. Stage 0 injects microbatch ``t`` during the fill phase; stage
  S-1 records finished microbatches after the ``S-1``-tick bubble;
- ``lax.scan`` (not ``fori_loop``) keeps the whole schedule
  reverse-differentiable — backward is the same pipeline run by scan's
  transpose, with ``ppermute``'s transpose shifting gradients the other
  way. No hand-written backward schedule;
- warmup/drain ticks compute on clamped (repeated) microbatches whose
  outputs are never recorded, so they contribute exactly zero gradient.

Embed and classifier head are replicated (tiny next to the stack) and run
outside the shard_map; the pipeline maps ``[M, mb, d_model] →
[M, mb, d_model]``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_pipeline_params(rng, feature_dim, d_model=64, d_hidden=128,
                         num_stages=4, num_classes=10, dtype=jnp.float32):
    """Parameter pytree: replicated embed/head + ``[S, ...]``-stacked
    residual MLP blocks (shard the leading axis over ``"pp"``)."""
    keys = jax.random.split(rng, 4)
    s = lambda fan: 1.0 / jnp.sqrt(fan)  # noqa: E731
    return {
        "embed": jax.random.normal(keys[0], (feature_dim, d_model),
                                   dtype) * s(feature_dim),
        "w1": jax.random.normal(keys[1], (num_stages, d_model, d_hidden),
                                dtype) * s(d_model),
        "w2": jax.random.normal(keys[2], (num_stages, d_hidden, d_model),
                                dtype) * s(d_hidden),
        "head": jax.random.normal(keys[3], (d_model, num_classes),
                                  dtype) * s(d_model),
    }


def pipeline_param_partition_specs():
    """PartitionSpecs over a mesh with a ``"pp"`` axis: one stage's block
    per device; embed/head replicated."""
    return {"embed": P(), "w1": P("pp"), "w2": P("pp"), "head": P()}


def _block(w1, w2, x):
    """One pipeline stage: residual two-layer MLP (the stand-in for a
    transformer block — the schedule is what's under test here)."""
    return x + jax.nn.relu(x @ w1) @ w2


def _pipeline_body(w1, w2, x_mb, axis_name, num_stages, num_microbatches,
                   batch_axis=None):
    """Per-device pipeline schedule (runs inside shard_map).

    ``w1``/``w2``: this stage's block, ``[1, d, h]`` / ``[1, h, d]``.
    ``x_mb``: ``[M, mb, d]`` microbatches (replicated — every stage sees
    them, only stage 0 consumes them).
    Returns ``[1, M, mb, d]`` — garbage except on the last stage, whose
    copy the wrapper selects from the stacked ``out_specs=P("pp")`` result.
    """
    stage = jax.lax.axis_index(axis_name)
    last = num_stages - 1
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    mb_shape = x_mb.shape[1:]

    def tick(carry, t):
        act, outs = carry
        idx = jnp.clip(t, 0, num_microbatches - 1)
        inp = jnp.where(stage == 0,
                        jax.lax.dynamic_index_in_dim(x_mb, idx, axis=0,
                                                     keepdims=False),
                        act)
        out = _block(w1[0], w2[0], inp)
        # Record finished microbatch t-(S-1) on the last stage only; the
        # masked update keeps warmup/drain compute out of the loss (and
        # therefore out of the gradients).
        out_t = t - last
        out_idx = jnp.clip(out_t, 0, num_microbatches - 1)
        record = (out_t >= 0) & (stage == last)
        current = jax.lax.dynamic_index_in_dim(outs, out_idx, axis=0,
                                               keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(record, out, current), out_idx, axis=0)
        act_next = jax.lax.ppermute(out, axis_name, perm)
        return (act_next, outs), None

    init_act = jnp.zeros(mb_shape, x_mb.dtype)
    init_outs = jnp.zeros_like(x_mb)

    from petastorm_tpu.models._shard_compat import mark_varying

    def varying(v):
        axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
        return mark_varying(v, axes)

    (_, outs), _ = jax.lax.scan(
        tick, (varying(init_act), varying(init_outs)),
        jnp.arange(num_microbatches + num_stages - 1))
    return outs[None]


def pipeline_forward(params, x_mb, mesh, axis_name="pp", batch_axis=None):
    """``[M, mb, d_model]`` microbatches → ``[M, mb, d_model]`` through the
    S-stage pipeline sharded over ``mesh[axis_name]``.

    ``batch_axis``: mesh axis the microbatch dim (axis 1) is sharded over —
    dp × pp: each (data, pp) device runs the same schedule on its slice of
    every microbatch; the ``ppermute`` shifts stay within each data group.
    """
    from jax import shard_map

    num_stages = mesh.shape[axis_name]
    if params["w1"].shape[0] != num_stages:
        raise ValueError(
            f"params stack {params['w1'].shape[0]} stages but the mesh's "
            f"{axis_name!r} axis has {num_stages} devices")
    body = functools.partial(_pipeline_body, axis_name=axis_name,
                             num_stages=num_stages,
                             num_microbatches=x_mb.shape[0],
                             batch_axis=batch_axis)
    x_spec = P(None, batch_axis, None)
    stacked = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), x_spec),
        out_specs=P(axis_name, None, batch_axis, None))(
        params["w1"], params["w2"], x_mb)
    return stacked[-1]  # the last stage's copy holds the real outputs


def apply_pipeline_model(params, features, mesh, axis_name="pp",
                         num_microbatches=4, batch_axis=None):
    """``features``: [B, F] → f32 logits [B, C]; B must divide into
    ``num_microbatches`` equal microbatches. ``batch_axis``: mesh axis for
    data parallelism over the microbatch dim (dp × pp)."""
    b = features.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} does not divide into "
                         f"{num_microbatches} microbatches")
    if batch_axis is not None and mesh is not None:
        data = mesh.shape[batch_axis]
        if (b // num_microbatches) % data:
            raise ValueError(
                f"microbatch size {b // num_microbatches} does not shard "
                f"over the {data}-device {batch_axis!r} axis")
    x = features @ params["embed"]
    x_mb = x.reshape(num_microbatches, b // num_microbatches, -1)
    out = pipeline_forward(params, x_mb, mesh, axis_name,
                           batch_axis=batch_axis)
    logits = out.reshape(b, -1) @ params["head"]
    return logits.astype(jnp.float32)


def reference_forward(params, features):
    """Sequential oracle: the same stack applied block by block on one
    device — the pipeline must match it exactly."""
    x = features @ params["embed"]
    for i in range(params["w1"].shape[0]):
        x = _block(params["w1"][i], params["w2"][i], x)
    return (x @ params["head"]).astype(jnp.float32)


def make_pipeline_train_step(learning_rate=0.05, mesh=None, axis_name="pp",
                             num_microbatches=4, batch_axis=None):
    """``step(params, features, labels, mask) -> (params, loss)`` — masked
    cross-entropy + SGD through the pipeline schedule (backward runs the
    transposed pipeline; no hand-written schedule)."""
    def loss_fn(params, features, labels, mask):
        logits = apply_pipeline_model(params, features, mesh,
                                      axis_name=axis_name,
                                      num_microbatches=num_microbatches,
                                      batch_axis=batch_axis)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)

    def step(params, features, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, features, labels,
                                                  mask)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
